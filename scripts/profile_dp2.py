"""Round 2 of dp_scaling attribution: the pieces (fwdbwd / pmean /
update) sum to ~28s but the composed step costs 34.8s on the 8-device
mesh. Sweep step *compositions* to find what the composed program pays
for: donation, state-pmean placement, joint-vs-split pmean, GSPMD vs
shard_map."""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, time
import numpy as np
from __graft_entry__ import _ensure_devices
_ensure_devices(8)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.compat import shard_map_compat
shard_map = shard_map_compat()
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import build_mesh
from deeplearning4j_tpu.zoo import resnet50

n = int(os.environ["DP_DEVICES"])
b = int(os.environ["DP_BATCH"])
steps = int(os.environ.get("DP_STEPS", "3"))
variant = os.environ["DP_VARIANT"]

conf = resnet50(height=32, width=32, channels=3, n_classes=10,
                cifar_stem=True, learning_rate=0.01)
net = ComputationGraph(conf).init()
mesh = build_mesh(data=n, model=1, devices=jax.devices()[:n])
updater = net.updater_def
rep_sh = NamedSharding(mesh, P())
dp_sh = NamedSharding(mesh, P("data"))

def place(tree, sh):
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

rng = jax.random.PRNGKey(0)
lrs = {k: jnp.asarray(v, jnp.float32)
       for k, v in updater.scheduled_lrs(0).items()}
t = jnp.asarray(1.0, jnp.float32)
rs = np.random.RandomState(0)
x_h = rs.rand(b, 3, 32, 32).astype(np.float32)
y_h = np.eye(10, dtype=np.float32)[rs.randint(0, 10, b)]

rep = P(); dp = P("data")

def flat_pmean(tree, axis):
    # ONE fused all-reduce: ravel every leaf into a single flat
    # vector, pmean once, unflatten (DDP-style gradient bucketing --
    # collapses ~260 per-leaf collectives into 1)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate(
        [l.astype(jnp.float32).ravel() for l in leaves])
    flat = jax.lax.pmean(flat, axis)
    out, off = [], 0
    for l, s in zip(leaves, sizes):
        out.append(flat[off:off + s].reshape(l.shape).astype(l.dtype))
        off += s
    return jax.tree_util.tree_unflatten(treedef, out)

def make_step(state_mode, joint, flat):
    def step(params, upd, state, x, y, lrs, t, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if flat:
            red = (grads, score, new_state if state_mode == "pmean"
                   else None)
            grads, score, red_state = flat_pmean(red, "data")
            if state_mode == "pmean":
                new_state = red_state
        elif joint:
            to_red = (grads, score, new_state if state_mode == "pmean"
                      else None)
            grads, score, red_state = jax.lax.pmean(to_red, "data")
            if state_mode == "pmean":
                new_state = red_state
        else:
            grads = jax.lax.pmean(grads, "data")
            score = jax.lax.pmean(score, "data")
            if state_mode == "pmean":
                new_state = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "data"), new_state)
        new_params, new_upd = updater.update(grads, upd, params, lrs, t)
        return new_params, new_upd, new_state, score
    return step

def build(variant):
    donate = "donate" in variant
    state_mode = "local" if "nostate" in variant else "pmean"
    joint = "joint" in variant
    flat = "flat" in variant
    if variant.startswith("gspmd"):
        def step(params, upd, state, x, y, lrs, t, rng):
            def loss_fn(p):
                s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                        train=True, fmasks=None)
                return s, ns
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_upd = updater.update(
                grads, upd, params, lrs, t)
            return new_params, new_upd, new_state, score
        return jax.jit(
            step,
            in_shardings=(rep_sh, rep_sh, rep_sh, dp_sh, dp_sh,
                          None, None, None),
            out_shardings=(rep_sh, rep_sh, rep_sh, rep_sh),
            donate_argnums=(0, 1, 2) if donate else (),
        )
    f = shard_map(make_step(state_mode, joint, flat), mesh=mesh,
                  in_specs=(rep, rep, rep, dp, dp, rep, rep, rep),
                  out_specs=(rep, rep, rep, rep), check_rep=False)
    return jax.jit(f, donate_argnums=(0, 1, 2) if donate else ())

f = build(variant)
# host-side master copies: donation deletes the placed device arrays,
# so each iteration re-places from host (device_put of an array that
# already has the target sharding would alias, then die on donation)
params_h = jax.tree_util.tree_map(np.asarray, net.params)
upd_h = jax.tree_util.tree_map(np.asarray, net.updater_state)
state_h = jax.tree_util.tree_map(np.asarray, net.state)
times = []
for it in range(steps + 1):
    params = place(params_h, rep_sh)
    upd = place(upd_h, rep_sh)
    state = place(state_h, rep_sh)
    x = jax.device_put(x_h, dp_sh); y = jax.device_put(y_h, dp_sh)
    jax.block_until_ready((params, upd, state, x, y))
    t0 = time.perf_counter()
    out = f(params, upd, state, x, y, lrs, t, rng)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    if it > 0:  # first = compile
        times.append(dt)
    del out
print(json.dumps({"variant": variant, "devices": n, "batch": b,
                  "sec": min(times)}))
"""


def run(variant, n, b, steps=3):
    env = dict(os.environ)
    env.update({
        "JAX_COMPILATION_CACHE_DIR": "/tmp/deeplearning4j_tpu_jax_cache",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"
                      ).strip(),
        "DP_DEVICES": str(n), "DP_BATCH": str(b),
        "DP_STEPS": str(steps), "DP_VARIANT": variant,
        "PYTHONPATH": REPO,
    })
    t0 = time.time()
    out = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=3600)
    wall = time.time() - t0
    if out.returncode != 0:
        return {"variant": variant, "devices": n, "batch": b,
                "error": out.stderr[-1500:], "wall": round(wall, 1)}
    r = json.loads(out.stdout.strip().splitlines()[-1])
    r["wall"] = round(wall, 1)
    return r


def main():
    cases = [
        ("plain", 8, 64),
        ("donate", 8, 64),
        ("flat", 8, 64),
        ("flat_donate", 8, 64),
        ("joint", 8, 64),
        ("nostate", 8, 64),
        ("gspmd_donate", 8, 64),
        ("donate", 1, 8),
        ("flat_donate", 1, 8),
    ]
    for variant, n, b in cases:
        print(json.dumps(run(variant, n, b)), flush=True)


if __name__ == "__main__":
    main()
