#!/usr/bin/env bash
# Run the fault-injection (chaos) test subset with a fixed seed.
#
# Every chaos-marked test derives its failure schedules from
# DL4J_TPU_CHAOS_SEED (default 1337), so a red run here reproduces
# bit-for-bit: re-run with the same seed to replay the exact same
# injected faults. Override the seed to explore other schedules:
#
#   DL4J_TPU_CHAOS_SEED=7 scripts/run_chaos.sh
#
# Extra pytest args pass through (e.g. -k retry, -x). Each storm
# suite runs as its own pytest invocation with a faulthandler
# timeout (a hung storm dumps every thread's stack instead of dying
# silently), and the run ends with a per-storm pass/fail summary —
# the exit code is nonzero iff any storm failed.
set -uo pipefail
cd "$(dirname "$0")/.."

export DL4J_TPU_CHAOS_SEED="${DL4J_TPU_CHAOS_SEED:-1337}"
echo "chaos seed: ${DL4J_TPU_CHAOS_SEED}"

# Preamble: the metric signal catalog (docs/ARCHITECTURE.md) must
# match the names registered in code — drift fails loudly here,
# before the chaos suite spends a second (see scripts/lint_metrics.py).
python scripts/lint_metrics.py || exit 1
# ... and both engine wrappers must still delegate their hot paths to
# the unified functional core, nn/core.py (no reintroduced duplicate
# step/scan/remat implementations — see scripts/lint_parity.py).
python scripts/lint_parity.py || exit 1
# ... and the newest bench round must not have regressed beyond the
# tolerance band vs the previous one (scripts/perf_gate.py; passes
# when fewer than two comparable rounds exist).
python scripts/perf_gate.py || exit 1

# Registered chaos storms (suite -> what the storm asserts):
#   tests/test_resilience.py     — training runtime (retry/checkpoint/
#                                  guard, kill/resume incl. prefetch,
#                                  deadline-capped retry storms)
#   tests/test_serving.py        — serving tier (breaker + fault storms)
#   tests/test_batching.py       — micro-batch drain loop (seeded storms
#                                  through the batched path: sequential
#                                  determinism + concurrent chunk faults)
#   tests/test_input_pipeline.py — prefetch pipeline (flaky-source
#                                  storms surface as DL4JFaultException;
#                                  guarded bad-step trajectory
#                                  equivalence under async dispatch;
#                                  bounded shutdown re-raises pending
#                                  worker faults)
#   tests/test_compile.py        — compile artifacts (corrupted /
#                                  stale AOT bundles must degrade
#                                  silently to JIT, never error the
#                                  request path or the restore)
#   tests/test_fleet.py          — serving fleet (SIGKILL one backend
#                                  process under router load: zero
#                                  request loss via retries, backend
#                                  restarts warm from the shared
#                                  persistent compile cache and
#                                  rejoins on the next health poll;
#                                  wedged-backend /readyz probe
#                                  timeouts mark unhealthy instantly)
#   tests/test_loop.py           — continuous-learning loop, four
#                                  storms: kill the trainer mid-epoch
#                                  (bitwise resume, with prefetch +
#                                  artifacts in test_resilience.py),
#                                  corrupt the candidate checkpoint
#                                  (quarantined; live keeps serving),
#                                  fail the canary (rejected; old
#                                  version untouched), SIGKILL
#                                  mid-promotion (journal recovery
#                                  rolls the half-applied promotion
#                                  forward) — plus the traffic-shift
#                                  regression rollback with zero XLA
#                                  compiles, counter-asserted
#   tests/test_preemption.py     — preemption notices: SIGTERM
#                                  mid-epoch with prefetch + async
#                                  dispatch live -> emergency
#                                  checkpoint, exit code 75, bitwise
#                                  resume on both engines; the same
#                                  storm with megastep=K live (SIGTERM
#                                  mid-chunk -> emergency checkpoint on
#                                  the last chunk boundary, staleness
#                                  <= K-1, bitwise megastep resume);
#                                  ModelServer + ServingRouter drain
#                                  with zero 5xx
#   tests/test_elastic.py        — device loss mid-run -> survivor-
#                                  mesh recovery from the host-RAM
#                                  snapshot ring (no steps lost beyond
#                                  the last snapshot); the same storm
#                                  with zero=True ZeRO-sharded
#                                  optimizer state (8->4 survivors
#                                  re-shard the moments, bitwise vs a
#                                  piecewise reference); injected
#                                  straggler -> straggler_detected_total
#   tests/test_data_defense.py   — bad-data storms: seeded
#                                  PoisonIterator feeds K corrupt of N
#                                  batches -> exactly K quarantines by
#                                  reason and final params bitwise the
#                                  clean run over the N-K survivors
#                                  (both engines + distributed trainer
#                                  with prefetch); statistical-guard
#                                  spike trips with checkpointed EWMA
#                                  + skipped-batch ledger (bitwise
#                                  resume); continual trainer dies
#                                  between publishes mid-quarantine
#                                  and resumes bitwise off the
#                                  manifest's data ledger
#   tests/test_conv_block.py     — Pallas fused-kernel library: seeded
#                                  random conv geometries (channels/
#                                  kernel/stride/padding/activation
#                                  from DL4J_TPU_CHAOS_SEED) — every
#                                  geometry the VMEM gate admits must
#                                  match the XLA reference at kernel
#                                  tolerance; plus the full dispatch/
#                                  trajectory/AOT-refusal suite rides
#                                  along (fast, CPU interpret mode)
#   tests/test_control_plane.py  — cross-host control plane: lease
#                                  heartbeats through seeded drop /
#                                  delay / partition storms (drops
#                                  survive the retry envelope, delays
#                                  land in control_rtt_ms, a hard
#                                  partition concludes coordinator
#                                  lost -> emergency checkpoint +
#                                  exit 75); then the real thing —
#                                  two jax.distributed processes,
#                                  rank 1 SIGKILLed mid-step, the
#                                  survivor rolls back to the newest
#                                  snapshot, re-forms a 1-process
#                                  mesh, and finishes bitwise equal
#                                  to a piecewise reference, with
#                                  ZeRO off and on (sharded moments
#                                  gathered + re-sharded)
#   tests/test_async_checkpoint.py — write-behind sharded checkpoints:
#                                  a control-channel partition DURING
#                                  the two-phase commit barrier (both
#                                  hosts abort, agree on the previous
#                                  committed step, torn dir GC'd);
#                                  SIGKILL swept across the async
#                                  write's phases single-process
#                                  (restore lands the newest committed
#                                  step, resume bitwise equal to the
#                                  uninterrupted reference); the real
#                                  2-process sharded storm, ZeRO off
#                                  and on (rank 1 dies right after
#                                  enqueuing its save — the commit
#                                  either lands whole or aborts, and
#                                  the restored shards merge bitwise
#                                  onto a 1-device mesh)
#   tests/test_autotune.py       — kernel tuning cache: a seeded storm
#                                  mangles persisted entries (truncate,
#                                  garbage bytes, flipped fingerprint,
#                                  infeasible config, deleted file)
#                                  between resolves — every mangled
#                                  read must degrade to the divisor
#                                  heuristic (counted by reason in
#                                  tuner_fallback_total), never crash,
#                                  never dispatch a mangled config;
#                                  dispatch outputs stay bitwise equal
#                                  to tuning off throughout
#   tests/test_embeddings.py     — sharded embeddings: a ShardedWord2Vec
#                                  run on the 8-device mesh is killed
#                                  with os._exit(137) at a seed-derived
#                                  step mid-epoch (no cleanup, no
#                                  flush); a second process restores the
#                                  last write-behind checkpoint on ONE
#                                  device and finishes — final tables
#                                  bitwise equal to an uninterrupted
#                                  run (the canonical-host-rows +
#                                  mesh-independent-update contract)
STORMS=(
    tests/test_resilience.py
    tests/test_serving.py
    tests/test_batching.py
    tests/test_input_pipeline.py
    tests/test_compile.py
    tests/test_fleet.py
    tests/test_loop.py
    tests/test_preemption.py
    tests/test_elastic.py
    tests/test_data_defense.py
    tests/test_conv_block.py
    tests/test_autotune.py
    tests/test_profiler.py
    tests/test_control_plane.py
    tests/test_async_checkpoint.py
    tests/test_embeddings.py
)

declare -a names rcs
failed=0
for storm in "${STORMS[@]}"; do
    echo
    echo "=== storm: ${storm} ==="
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m pytest "${storm}" \
        -q -m chaos \
        -o faulthandler_timeout=300 \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
    rc=$?
    # pytest rc 5 = "no tests collected" (e.g. -k filtered a suite
    # to nothing): not a storm failure
    if [ "$rc" -eq 5 ]; then rc=0; fi
    names+=("${storm}")
    rcs+=("${rc}")
    if [ "$rc" -ne 0 ]; then failed=1; fi
done

echo
echo "=== chaos storm summary (seed ${DL4J_TPU_CHAOS_SEED}) ==="
for i in "${!names[@]}"; do
    if [ "${rcs[$i]}" -eq 0 ]; then
        echo "  PASS  ${names[$i]}"
    else
        echo "  FAIL  ${names[$i]} (exit ${rcs[$i]})"
    fi
done
exit "${failed}"
