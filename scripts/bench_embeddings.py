#!/usr/bin/env python
"""Sharded-embeddings smoke benchmark (CPU, seeded, seconds).

A/Bs the ``embeddings/`` subsystem against its dense single-device
equivalents on an 8-virtual-device mesh and prints ONE JSON line::

    {"vocab": ..., "dim": ..., "batch": ...,
     "residency": {"shard_bytes": ..., "replicated_bytes": ...,
                   "bytes_per_device_ratio": ...},
     "sparse_update": {"sparse_steps_per_s": ...,
                       "dense_steps_per_s": ..., "speedup": ...,
                       "rows_touched": ..., "bitwise_match": true},
     "fused_step": {"sharded_steps_per_s": ...,
                    "single_steps_per_s": ..., "loss_parity": true},
     "windows": ...}

The acceptance gates this makes falsifiable on CPU:

- ``bytes_per_device_ratio`` <= 0.15: one device holds ~1/8 of the
  table (the capacity claim — the largest trainable vocabulary
  scales with the mesh instead of one device's HBM);
- ``sparse_update.bitwise_match``: the deduped segment-sum +
  owner-side scatter produces bit-identical rows to a dense
  ``[V, D]``-cotangent SGD step — sparsity changes the cost shape,
  never the bits;
- ``sparse_update.speedup`` > 1 at this vocab: per-step update cost
  scales with the unique rows in the batch, not with ``V`` (the
  dense step materializes and subtracts a full ``[V, D]`` array);
- ``fused_step.loss_parity``: the sharded collective-lookup fused
  skip-gram/NS step computes the same loss as the single-device
  reference step (allclose; reduction orders differ across the
  psum). Sharded steps/sec is reported honestly — on a CPU host the
  8-way collective exchange is overhead, the win is capacity; real
  TPU meshes get the ICI bandwidth this shape is designed for.

Windows are interleaved A/B best-of-N (host noise only ever slows a
run). Runnable standalone (``python scripts/bench_embeddings.py``)
or from ``bench.py``'s ``embeddings`` section under
``BENCH_BUDGET_S``.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _timed_steps(fn, n_steps: int) -> float:
    """Wall seconds for n_steps sequential calls of fn (each call must
    block on its own result)."""
    t0 = time.perf_counter()
    for _ in range(n_steps):
        fn()
    return time.perf_counter() - t0


def bench_sparse_vs_dense_update(vocab, dim, batch, steps, windows,
                                 deadline):
    """Per-step wall of the deduped sparse row update vs a dense
    [V, D]-cotangent SGD step, interleaved, plus the bitwise gate."""
    from deeplearning4j_tpu.embeddings import sparse
    from deeplearning4j_tpu.embeddings.table import (
        ShardedEmbeddingTable,
    )

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, batch).astype(np.int32)
    grads = rng.randn(batch, dim).astype(np.float32)
    lr = 0.05

    t = ShardedEmbeddingTable.zeros(vocab, dim)
    rows0 = t.to_host()

    @jax.jit
    def dense_step(table, ids, grads):
        # the cost shape the subsystem exists to avoid: a full [V, D]
        # cotangent materialized and subtracted every step
        cot = jnp.zeros_like(table).at[ids].add(grads)
        return table - lr * cot

    dense_table = jnp.asarray(rows0)
    jids, jgrads = jnp.asarray(ids), jnp.asarray(grads)

    # one warm-up + bitwise gate on the FIRST step of each path
    touched = t.apply_sparse_grads(ids, grads, lr)
    dense_table = dense_step(dense_table, jids, jgrads)
    dense_table.block_until_ready()
    bitwise = bool(
        np.array_equal(t.to_host(), np.asarray(dense_table)[:vocab])
    )

    best_sparse = best_dense = float("inf")
    done = 0
    for _ in range(windows):
        if time.monotonic() > deadline:
            break
        best_sparse = min(best_sparse, _timed_steps(
            lambda: t.apply_sparse_grads(ids, grads, lr), steps))

        def one_dense():
            nonlocal dense_table
            dense_table = dense_step(dense_table, jids, jgrads)
            dense_table.block_until_ready()

        best_dense = min(best_dense, _timed_steps(one_dense, steps))
        done += 1
    return {
        "sparse_steps_per_s": round(steps / best_sparse, 2),
        "dense_steps_per_s": round(steps / best_dense, 2),
        "speedup": round(best_dense / best_sparse, 3),
        "rows_touched": int(touched),
        "bitwise_match": bitwise,
        "windows_completed": done,
    }


def bench_fused_step(vocab, dim, batch, negatives, steps, windows,
                     deadline):
    """Throughput of the fused sharded skip-gram/NS step vs the jitted
    single-device reference step, same seeded batch; parity gate on
    the loss."""
    from deeplearning4j_tpu.embeddings.table import (
        ShardedEmbeddingTable,
        _build_sg_ns_step,
    )
    from deeplearning4j_tpu.nlp.word2vec import _ns_step_raw

    rng = np.random.RandomState(1)
    centers = jnp.asarray(rng.randint(0, vocab, batch), jnp.int32)
    contexts = jnp.asarray(rng.randint(0, vocab, batch), jnp.int32)
    negs = jnp.asarray(
        rng.randint(0, vocab, (batch, negatives)), jnp.int32
    )
    mask = jnp.ones(batch, jnp.float32)
    alpha = jnp.float32(0.025)

    rows0 = ((np.random.RandomState(2).rand(vocab, dim) - 0.5)
             / dim).astype(np.float32)
    s0 = ShardedEmbeddingTable.from_rows(rows0)
    s1 = ShardedEmbeddingTable.zeros(vocab, dim)
    step_fn = _build_sg_ns_step(s0.mesh)

    ref_step = jax.jit(_ns_step_raw, static_argnums=(7,))
    r0, r1 = jnp.asarray(rows0), jnp.zeros((vocab, dim), jnp.float32)

    # warm-up + loss parity on step 1
    a0, a1, sh_loss, _ = step_fn(s0.table, s1.table, centers, contexts,
                                 negs, mask, alpha)
    r0, r1, ref_loss = ref_step(r0, r1, centers, contexts, negs, mask,
                                alpha, False)
    parity = bool(np.allclose(float(sh_loss), float(ref_loss),
                              atol=1e-6))
    state = {"t": (a0, a1), "r": (r0, r1)}

    def one_sharded():
        t0, t1 = state["t"]
        t0, t1, loss, _ = step_fn(t0, t1, centers, contexts, negs,
                                  mask, alpha)
        loss.block_until_ready()
        state["t"] = (t0, t1)

    def one_single():
        t0, t1 = state["r"]
        t0, t1, loss = ref_step(t0, t1, centers, contexts, negs, mask,
                                alpha, False)
        loss.block_until_ready()
        state["r"] = (t0, t1)

    best_sh = best_single = float("inf")
    done = 0
    for _ in range(windows):
        if time.monotonic() > deadline:
            break
        best_sh = min(best_sh, _timed_steps(one_sharded, steps))
        best_single = min(best_single, _timed_steps(one_single, steps))
        done += 1
    return {
        "sharded_steps_per_s": round(steps / best_sh, 2),
        "single_steps_per_s": round(steps / best_single, 2),
        "loss_parity": parity,
        "windows_completed": done,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--vocab", type=int, default=65536)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--negatives", type=int, default=5)
    ap.add_argument("--steps", type=int, default=8,
                    help="steps per timing window")
    ap.add_argument("--windows", type=int, default=3,
                    help="interleaved best-of windows")
    ap.add_argument("--fused-vocab", type=int, default=4096,
                    help="vocab for the fused-step A/B (the dense "
                    "reference must also fit comfortably)")
    ap.add_argument("--budget-s", type=float, default=0,
                    help="wall budget; 0 = unbounded")
    args = ap.parse_args()

    deadline = (time.monotonic() + args.budget_s if args.budget_s
                else float("inf"))

    from deeplearning4j_tpu.embeddings.table import (
        ShardedEmbeddingTable,
    )

    t = ShardedEmbeddingTable.zeros(args.vocab, args.dim)
    n_dev = t.n_shards
    residency = {
        "shard_bytes": t.shard_bytes(),
        "replicated_bytes": t.replicated_bytes(),
        "bytes_per_device_ratio": round(
            t.shard_bytes() / t.replicated_bytes(), 4
        ),
        "devices": n_dev,
    }
    del t

    doc = {
        "vocab": args.vocab, "dim": args.dim, "batch": args.batch,
        "windows": args.windows,
        "residency": residency,
        "sparse_update": bench_sparse_vs_dense_update(
            args.vocab, args.dim, args.batch, args.steps,
            args.windows, deadline,
        ),
        "fused_step": bench_fused_step(
            args.fused_vocab, args.dim, args.batch, args.negatives,
            args.steps, args.windows, deadline,
        ),
    }
    ok = (
        doc["residency"]["bytes_per_device_ratio"] <= 1.0 / n_dev + 0.02
        and doc["sparse_update"]["bitwise_match"]
        and doc["fused_step"]["loss_parity"]
    )
    doc["embeddings_ok"] = bool(ok)
    print(json.dumps(doc))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
