"""ResNet-50 BN/residual bandwidth-roofline model (VERDICT r5 #1):
sums the train-mode memory traffic of every non-conv pass over the
real zoo shapes and compares the HBM-roofline time against the
measured loop-fusion share of the step trace.

Pass model per BN layer over activation bytes S (bf16):
  fwd : stats one-pass read (S) + apply read+write (2S)        = 3S
  bwd : dy+x multi-output reductions (2S) + dx read dy,x,
        write dx (3S)                                          = 5S
Residual adds (per bottleneck): read a + read b + write (3S_out),
backward re-read (dy fan-out is free — same dy feeds both).
Maxpool bwd (select-and-scatter) and the loss tail are excluded
(measured separately in the trace).

Usage: python scripts/resnet_roofline.py [batch]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

HBM_GBPS = 819.0  # v5e


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.zoo import resnet50

    conf = resnet50(dtype="bfloat16", learning_rate=0.01)
    it = InputType.convolutional(224, 224, 3)
    # walk the graph in topo order, tracking each vertex's output type
    types = {}
    bn_bytes = 0.0
    res_bytes = 0.0
    n_bn = 0
    n_add = 0
    for name in conf.topological_order():
        v = conf.vertices[name]
        ins = conf.vertex_inputs.get(name, ())
        in_t = types[ins[0]] if ins and ins[0] in types else it
        lc = getattr(v, "layer_conf", None)
        out_t = lc.output_type(in_t) if lc is not None else in_t
        types[name] = out_t
        kind = type(lc).__name__ if lc is not None else type(v).__name__
        if kind == "BatchNormalization":
            s = (batch * out_t.channels * out_t.height * out_t.width
                 * 2)  # bf16
            bn_bytes += 8 * s
            n_bn += 1
        elif "ElementWise" in kind:
            s = (batch * out_t.channels * out_t.height * out_t.width
                 * 2)
            # fwd read+read+write, bwd: dy read once, two writes fuse
            # into consumers -> count 3S fwd + 2S bwd
            res_bytes += 5 * s
            n_add += 1
    total = bn_bytes + res_bytes
    t_ms = total / (HBM_GBPS * 1e9) * 1e3
    print(f"batch {batch}: {n_bn} BN layers, {n_add} residual adds")
    print(f"BN traffic       {bn_bytes / 1e9:7.2f} GB")
    print(f"residual traffic {res_bytes / 1e9:7.2f} GB")
    print(f"total            {total / 1e9:7.2f} GB "
          f"-> {t_ms:.2f} ms at {HBM_GBPS:.0f} GB/s")


if __name__ == "__main__":
    main()
