"""Perf regression gate: compare the newest two BENCH_*.json rounds.

Each bench round writes ``BENCH_rNN.json`` with a ``parsed.configs``
map of section -> {value, unit, mfu, ...}. This gate diffs the two
newest rounds section-by-section and fails (exit 1) only when a
section's headline ``value`` (a throughput: bigger is better) fell by
more than the tolerance band — generous by default because CPU CI
timings are noisy and a bench round may be budget-truncated.

Tolerant by design: fewer than two rounds, unparsed rounds (rc != 0 /
timeout), sections missing from either side, or error-marked sections
all pass with a note — the gate only ever fails on evidence, never on
absence of it.

Knobs: ``DL4J_TPU_PERF_GATE_TOL`` (fractional drop allowed, default
0.30), ``DL4J_TPU_PERF_GATE_DIR`` (where the BENCH files live,
default repo root).

Usage: python scripts/perf_gate.py [dir]
"""
import glob
import json
import os
import re
import sys

DEFAULT_TOL = 0.30


def find_rounds(d):
    """BENCH_*.json sorted by round number, oldest first."""
    out = []
    for p in glob.glob(os.path.join(d, "BENCH_*.json")):
        m = re.search(r"BENCH_r?(\d+)\.json$", os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def load_configs(path):
    """section -> numeric headline value, or None when the round has
    no usable parse (timeout, truncated run)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_gate: unreadable {path}: {e}")
        return None
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        return None
    configs = parsed.get("configs")
    if not isinstance(configs, dict):
        return None
    vals = {}
    for name, sec in configs.items():
        if not isinstance(sec, dict) or "error" in sec:
            continue
        v = sec.get("value")
        if isinstance(v, (int, float)) and v > 0:
            vals[name] = float(v)
        # kernel_autotune: gate each kernel's tuned-vs-heuristic
        # delta as a ratio (1.0 = heuristic parity; bigger is
        # better, same direction as every other section)
        auto = sec.get("autotune")
        kernels = (auto.get("kernels")
                   if isinstance(auto, dict) else None)
        if isinstance(kernels, dict):
            for sub, info in sorted(kernels.items()):
                d = (info.get("tuned_delta")
                     if isinstance(info, dict) else None)
                if isinstance(d, (int, float)):
                    vals[f"{name}.{sub}.tuned"] = \
                        1.0 + max(0.0, float(d))
    return vals or None


def main(argv):
    d = (argv[0] if argv
         else os.environ.get("DL4J_TPU_PERF_GATE_DIR") or ".")
    tol = float(os.environ.get("DL4J_TPU_PERF_GATE_TOL",
                               DEFAULT_TOL))
    rounds = find_rounds(d)
    if not rounds:
        # first run in a fresh checkout: nothing has benched yet, so
        # there is no baseline to regress against — explicitly pass
        print(f"perf_gate: no bench rounds in {d!r} — no baseline "
              "yet, nothing to gate; pass")
        return 0
    if len(rounds) < 2:
        print(f"perf_gate: {len(rounds)} bench round(s) in {d!r}; "
              "need 2 to compare — pass")
        return 0
    new_path, old_path = rounds[-1], rounds[-2]
    new = load_configs(new_path)
    # walk back past unusable rounds so one truncated run doesn't
    # blind the gate forever
    old = None
    for p in reversed(rounds[:-1]):
        old = load_configs(p)
        if old is not None:
            old_path = p
            break
    if new is None or old is None:
        which = new_path if new is None else old_path
        print(f"perf_gate: no usable parse in {which}; pass "
              "(nothing to compare)")
        return 0
    print(f"perf_gate: {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} (tolerance -{tol:.0%})")
    regressions = []
    for name in sorted(set(new) & set(old)):
        ratio = new[name] / old[name]
        flag = ""
        if ratio < 1.0 - tol:
            flag = "  REGRESSION"
            regressions.append((name, old[name], new[name], ratio))
        print(f"  {name:24s} {old[name]:14.1f} -> {new[name]:14.1f} "
              f"({ratio:6.2%}){flag}")
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    if only_old:
        print(f"  (sections gone in new round, not gated: "
              f"{', '.join(only_old)})")
    if only_new:
        print(f"  (new sections, no baseline: {', '.join(only_new)})")
    if regressions:
        print(f"perf_gate: FAIL — {len(regressions)} section(s) "
              f"regressed beyond -{tol:.0%}")
        return 1
    print("perf_gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
