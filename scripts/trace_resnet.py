"""Capture a jax profiler trace of the exact bench ResNet-50 fit
window (HBM-resident batches, scan-fused steps) and print the leaf-op
attribution via parse_trace. Usage:
  python scripts/trace_resnet.py [outdir]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else \
        "artifacts/resnet50_trace_r5"
    import jax

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo import resnet50
    from bench import _to_hbm

    batch, chunk = 128, 2
    g = ComputationGraph(
        resnet50(dtype="bfloat16", learning_rate=0.01)
    ).init()
    g.scan_chunk = chunk
    rng = np.random.RandomState(0)
    batches = _to_hbm([
        DataSet(
            features=rng.randint(0, 256, (batch, 3, 224, 224),
                                 dtype=np.uint8),
            labels=np.eye(1000, dtype=np.uint8)[
                rng.randint(0, 1000, batch)
            ],
        )
        for _ in range(chunk)
    ])
    g.fit(batches, epochs=1)  # compile
    _ = float(g.score_value)
    jax.profiler.start_trace(outdir)
    g.fit(batches, epochs=3)
    _ = float(g.score_value)
    jax.profiler.stop_trace()
    print("trace written to", outdir)


if __name__ == "__main__":
    main()
