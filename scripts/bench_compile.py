#!/usr/bin/env python
"""Cold-start vs warm-start serving boot A/B (CPU, seeded, ~1 min).

The measurement gate for the compile-artifact subsystem
(``deeplearning4j_tpu/compile/``): three child processes boot the
SAME serving tier from the SAME checkpoint and the A/B isolates what
each tier of compile reuse buys —

- ``cold``: empty persistent cache, no AOT — every ladder bucket
  pays a real XLA compile at warmup (the pre-subsystem world);
- ``warm``: the persistent cache the cold boot just populated —
  warmup compiles become disk reads (tier 1);
- ``aot``: the checkpoint's bundled AOT-exported executables —
  warmup *deserializes* the bucket ladder; the child performs ZERO
  XLA backend compiles, counter-asserted from jax's own compile
  instrumentation (tier 2).

Each child reports boot-to-ready seconds (CheckpointManager restore +
``ModelServer.start()`` warmup, python/jax import time excluded and
reported separately) and first-predict latency. Prints ONE JSON
line::

    {"cold": {"boot_to_ready_s": ..., "first_predict_ms": ...,
              "backend_compiles": ..., "compile_seconds": ...},
     "warm": {..., "cache_hits": ...},
     "aot":  {..., "aot_buckets": ...},
     "speedup_boot_warm": ..., "speedup_boot_aot": ...,
     "zero_compile_warm_restart": true}

Acceptance gates: ``zero_compile_warm_restart`` (the aot child's
``backend_compiles == 0``) and ``speedup_boot_aot > 1`` (materially
lower boot-to-ready than cold).

Runnable standalone (``python scripts/bench_compile.py``) or via
``bench.py``'s ``aot_compile`` section.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_T0 = time.perf_counter()  # child mode: process-start reference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_IN = 48
MAX_BATCH = 16  # ladder 1,2,4,8,16 -> 5 bucket executables


def _make_net(seed=0):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.1)
        .list()
        .layer(DenseLayer(n_in=N_IN, n_out=512, activation="tanh"))
        .layer(DenseLayer(n_in=512, n_out=512, activation="tanh"))
        .layer(OutputLayer(n_out=8))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _prepare(ckpt_dir: str, seed: int) -> None:
    """Save the checkpoint + its AOT bundle. Runs in a child with a
    PRIVATE cache dir so exporting (which compiles) cannot pre-warm
    the shared cache the cold measurement must find empty."""
    from deeplearning4j_tpu.compile.aot import export_serving_bundle
    from deeplearning4j_tpu.resilience.checkpoint import (
        CheckpointManager,
    )
    from deeplearning4j_tpu.serving.batcher import BucketLadder

    net = _make_net(seed)
    buckets = BucketLadder(None, MAX_BATCH).buckets
    bundle = export_serving_bundle(net, buckets)
    CheckpointManager(ckpt_dir).save(net, artifacts=bundle)
    print(json.dumps({"prepared": sorted(len(v) for v in
                                         bundle.values())}))


def _serve(ckpt_dir: str, mode: str, seed: int) -> None:
    """Boot the serving tier once and print the measurements. The
    persistent-cache dir comes from DL4J_TPU_COMPILE_CACHE_DIR (set
    by the parent); ``mode`` gates AOT install."""
    import numpy as np

    from deeplearning4j_tpu.compile.persistent import cache_stats
    from deeplearning4j_tpu.resilience.checkpoint import (
        CheckpointManager,
    )
    from deeplearning4j_tpu.serving.server import ModelServer

    import_s = time.perf_counter() - _T0  # python+jax+framework
    mgr = CheckpointManager(ckpt_dir)
    t0 = time.perf_counter()
    srv = ModelServer(
        checkpoint_manager=mgr, max_batch_size=MAX_BATCH,
        aot=(mode == "aot"),
    ).start()
    code, _ = srv.readiness()
    boot_s = time.perf_counter() - t0
    try:
        feats = np.random.RandomState(seed).rand(
            3, N_IN
        ).astype(np.float32)
        t1 = time.perf_counter()
        pcode, _, _ = srv.submit(feats)
        first_ms = (time.perf_counter() - t1) * 1000.0
        snap = srv.metrics_snapshot()
    finally:
        srv.stop(drain_timeout=1)
    stats = cache_stats()
    print(json.dumps({
        "mode": mode,
        "ready_code": code,
        "predict_code": pcode,
        "import_s": round(import_s, 3),
        "boot_to_ready_s": round(boot_s, 3),
        "first_predict_ms": round(first_ms, 3),
        "backend_compiles": stats["backend_compiles"],
        "compile_seconds": stats["compile_seconds"],
        "cache_hits": stats["hits"],
        "cache_misses": stats["misses"],
        "aot_buckets": snap["compile"]["aot_buckets_installed"],
        "xla_compiles_total": snap["xla_compiles_total"],
        "post_warmup_compiles_total":
            snap["post_warmup_compiles_total"],
    }), flush=True)


def _spawn(argv, cache_dir: str, timeout: float) -> dict:
    env = dict(os.environ)
    env["DL4J_TPU_COMPILE_CACHE_DIR"] = cache_dir
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + argv,
        capture_output=True, text=True, env=env, timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child {argv} failed: {out.stderr[-2000:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def run(seed=0, child_timeout=120, keep_workdir=False) -> dict:
    work = tempfile.mkdtemp(prefix="dl4j_bench_compile_")
    ckpt = os.path.join(work, "ckpt")
    shared = os.path.join(work, "cache-shared")
    prep = os.path.join(work, "cache-prepare")
    try:
        _spawn(["--prepare", "--ckpt", ckpt, "--seed", str(seed)],
               prep, child_timeout)
        fields = ("boot_to_ready_s", "first_predict_ms", "import_s",
                  "backend_compiles", "compile_seconds", "cache_hits",
                  "cache_misses", "aot_buckets",
                  "post_warmup_compiles_total")
        out = {}
        # run order IS the experiment: cold populates the shared
        # cache, warm re-reads it, aot skips the compiler entirely
        for name, mode in (("cold", "jit"), ("warm", "jit"),
                           ("aot", "aot")):
            r = _spawn(
                ["--serve", "--ckpt", ckpt, "--mode", mode,
                 "--seed", str(seed)],
                shared, child_timeout,
            )
            if r.get("ready_code") != 200 or r.get(
                    "predict_code") != 200:
                raise RuntimeError(f"{name} boot unhealthy: {r}")
            out[name] = {k: r[k] for k in fields}
        out["speedup_boot_warm"] = round(
            out["cold"]["boot_to_ready_s"]
            / max(out["warm"]["boot_to_ready_s"], 1e-9), 2
        )
        out["speedup_boot_aot"] = round(
            out["cold"]["boot_to_ready_s"]
            / max(out["aot"]["boot_to_ready_s"], 1e-9), 2
        )
        out["zero_compile_warm_restart"] = (
            out["aot"]["backend_compiles"] == 0
        )
        out["gates"] = ("zero_compile_warm_restart and "
                        "speedup_boot_aot > 1")
        return out
    finally:
        if not keep_workdir:
            shutil.rmtree(work, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    ap.add_argument("--prepare", action="store_true")
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--ckpt")
    ap.add_argument("--mode", choices=("jit", "aot"), default="jit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--child-timeout", type=float, default=120)
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()
    if args.prepare:
        _prepare(args.ckpt, args.seed)
        return
    if args.serve:
        _serve(args.ckpt, args.mode, args.seed)
        return
    print(json.dumps(run(
        seed=args.seed, child_timeout=args.child_timeout,
        keep_workdir=args.keep_workdir,
    )))


if __name__ == "__main__":
    main()
