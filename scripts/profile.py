"""One profiling entry point (supersedes profile_dp.py, profile_dp2.py,
profile_resnet.py, resnet_roofline.py, trace_resnet.py — all folded in
here as subcommands). Every MFU number is computed through the
hardware-truth cost model (``observability.profiler.CostModel``: XLA's
own flops/bytes for the exact compiled step), never bespoke math.

Usage:
  python scripts/profile.py hlo       [--skip-trace]    # step HLO + MFU
  python scripts/profile.py trace     [outdir]          # fit-window trace
  python scripts/profile.py roofline  [batch] [--write] # analytic BN/residual roofline
  python scripts/profile.py dp                          # dp_scaling decomposition
  python scripts/profile.py dp2                         # dp step-composition sweep

Knobs: ``RN_BATCH`` (hlo batch, default 128), ``DL4J_TPU_PEAK_FLOPS``
/ ``DL4J_TPU_PEAK_BYTES_PER_SEC`` (state the roofline on CPU).
"""
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


# -- hlo: optimized step HLO + cost-model MFU ---------------------------

def cmd_hlo(argv):
    """Dump the optimized HLO of the exact bench train step (layouts,
    transpose/copy counts, dtype mix), time the step, and report MFU
    from the step's own XLA cost analysis."""
    import jax

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.observability import profiler
    from deeplearning4j_tpu.zoo import resnet50

    batch = int(os.environ.get("RN_BATCH", "128"))
    g = ComputationGraph(
        resnet50(dtype="bfloat16", learning_rate=0.01)
    ).init()
    g.scan_chunk = 1
    rng = np.random.RandomState(0)
    ds = DataSet(
        features=rng.randint(0, 256, (batch, 3, 224, 224),
                             dtype=np.uint8),
        labels=np.eye(1000, dtype=np.uint8)[
            rng.randint(0, 1000, batch)
        ],
    )
    g.fit_minibatch(ds)  # compile + 1 step
    _ = float(g.score_value)
    step_fn = g._jit_step
    if step_fn is None:
        print("no _jit_step; falling back to timing only")
    else:
        import jax.numpy as jnp

        dtype = g._dtype()
        inputs = [jnp.asarray(ds.features, dtype)]
        labels = [jnp.asarray(ds.labels, dtype)]
        lrs = {
            k: jnp.asarray(v, jnp.float32)
            for k, v in g.updater_def.scheduled_lrs(
                g.iteration_count
            ).items()
        }
        t = jnp.asarray(g.iteration_count + 1, jnp.float32)
        key = jax.random.fold_in(g._base_key, g.iteration_count)
        try:
            txt = step_fn.lower(
                g.params, g.updater_state, g.state, inputs, labels,
                None, None, lrs, t, key,
            ).compile().as_text()
        except Exception as e:
            txt = None
            print("HLO lowering failed:", repr(e))
        if txt:
            out = os.path.join("artifacts", "resnet50_hlo.txt")
            os.makedirs("artifacts", exist_ok=True)
            with open(out, "w") as f:
                f.write(txt)
            ops = re.findall(r"^\s*%?\S+ = (\S+?)\(", txt, re.M)
            from collections import Counter

            c = Counter(
                re.sub(r"\..*", "", re.sub(r"\(.*", "", o))
                for o in ops
            )
            interesting = {
                k: v for k, v in c.items()
                if any(s in k for s in (
                    "transpose", "copy", "convolution", "fusion",
                    "all-reduce", "reduce", "dot",
                ))
            }
            print("HLO op histogram (interesting):", interesting)
            convs = re.findall(
                r"= (\S+)\[([^\]]*)\]\{([^}]*)\} convolution", txt
            )
            print("conv output dtype/shape/layout (first 5):",
                  convs[:5])
            print("HLO written to", out)

    # step timing + hardware-truth MFU
    for _ in range(2):
        g.fit_minibatch(ds)
    _ = float(g.score_value)
    times = []
    for _ in range(6):
        t0 = time.perf_counter()
        g.fit_minibatch(ds)
        _ = float(g.score_value)
        times.append(time.perf_counter() - t0)
    step_s = min(times)
    cm = profiler.train_step_cost_model(g, ds)
    peak, kind = profiler.peak_flops()
    peak_bw, _ = profiler.peak_bytes_per_sec()
    got = cm.achieved(step_s, peak)
    print(f"step {step_s * 1000:.1f} ms  batch {batch}  "
          f"{batch / step_s:.1f} ex/s")
    print(f"cost model {cm.key}: {cm.flops / 1e9:.1f} GFLOP, "
          f"{cm.bytes_accessed / 1e9:.2f} GB, "
          f"AI {cm.arithmetic_intensity:.1f} flop/byte")
    if got["mfu"] is not None:
        print(f"MFU {got['mfu']:.4f} against {kind} peak "
              f"{peak / 1e12:.1f} TFLOP/s "
              f"(roofline class: "
              f"{profiler.ROOFLINE_NAMES[cm.roofline_class(peak, peak_bw)]})")
    else:
        print("MFU undefined: no peak FLOP/s for this device "
              "(set DL4J_TPU_PEAK_FLOPS)")

    if "--skip-trace" not in argv:
        trace_dir = os.path.join("artifacts", "resnet50_trace_hlo")
        jax.profiler.start_trace(trace_dir)
        for _ in range(3):
            g.fit_minibatch(ds)
        _ = float(g.score_value)
        jax.profiler.stop_trace()
        print("trace written to", trace_dir)


# -- trace: fit-window profiler capture ---------------------------------

def cmd_trace(argv):
    """Capture a jax profiler trace of the exact bench ResNet-50 fit
    window (HBM-resident batches, scan-fused steps); parse with
    scripts/parse_trace.py."""
    outdir = argv[0] if argv else "artifacts/resnet50_trace_r6"
    import jax

    from bench import _to_hbm
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo import resnet50

    batch, chunk = 128, 2
    g = ComputationGraph(
        resnet50(dtype="bfloat16", learning_rate=0.01)
    ).init()
    g.scan_chunk = chunk
    rng = np.random.RandomState(0)
    batches = _to_hbm([
        DataSet(
            features=rng.randint(0, 256, (batch, 3, 224, 224),
                                 dtype=np.uint8),
            labels=np.eye(1000, dtype=np.uint8)[
                rng.randint(0, 1000, batch)
            ],
        )
        for _ in range(chunk)
    ])
    g.fit(batches, epochs=1)  # compile
    _ = float(g.score_value)
    jax.profiler.start_trace(outdir)
    g.fit(batches, epochs=3)
    _ = float(g.score_value)
    jax.profiler.stop_trace()
    print("trace written to", outdir)


# -- roofline: analytic BN/residual traffic model -----------------------

ROOFLINE_ARTIFACT = os.path.join("artifacts",
                                 "resnet50_roofline_r6.md")


def roofline_model(batch: int) -> dict:
    """Train-mode memory traffic of every non-conv pass over the real
    zoo shapes. Pass model per BN layer over activation bytes S
    (bf16): fwd 1-read stats + read/write apply (3S); bwd dy+x
    multi-output reductions (2S) + dx read-read-write (3S). Residual
    adds: 5S. Maxpool bwd and the loss tail are excluded (measured
    separately in the trace)."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.zoo import resnet50

    conf = resnet50(dtype="bfloat16", learning_rate=0.01)
    it = InputType.convolutional(224, 224, 3)
    types = {}
    bn_bytes = 0.0
    res_bytes = 0.0
    n_bn = 0
    n_add = 0
    for name in conf.topological_order():
        v = conf.vertices[name]
        ins = conf.vertex_inputs.get(name, ())
        in_t = types[ins[0]] if ins and ins[0] in types else it
        lc = getattr(v, "layer_conf", None)
        out_t = lc.output_type(in_t) if lc is not None else in_t
        types[name] = out_t
        kind = (type(lc).__name__ if lc is not None
                else type(v).__name__)
        if kind == "BatchNormalization":
            s = (batch * out_t.channels * out_t.height * out_t.width
                 * 2)  # bf16
            bn_bytes += 8 * s
            n_bn += 1
        elif "ElementWise" in kind:
            s = (batch * out_t.channels * out_t.height * out_t.width
                 * 2)
            res_bytes += 5 * s
            n_add += 1
    return {"batch": batch, "n_bn": n_bn, "n_add": n_add,
            "bn_bytes": bn_bytes, "res_bytes": res_bytes,
            "total_bytes": bn_bytes + res_bytes}


def cmd_roofline(argv):
    from deeplearning4j_tpu.observability import profiler

    args = [a for a in argv if not a.startswith("--")]
    batch = int(args[0]) if args else 128
    peak_bw, bw_kind = profiler.peak_bytes_per_sec()
    if peak_bw is None:
        peak_bw, bw_kind = 819e9, "assumed v5e"
    m = roofline_model(batch)
    t_ms = m["total_bytes"] / peak_bw * 1e3
    lines = [
        f"batch {batch}: {m['n_bn']} BN layers, "
        f"{m['n_add']} residual adds",
        f"BN traffic       {m['bn_bytes'] / 1e9:7.2f} GB",
        f"residual traffic {m['res_bytes'] / 1e9:7.2f} GB",
        f"total            {m['total_bytes'] / 1e9:7.2f} GB "
        f"-> {t_ms:.2f} ms at {peak_bw / 1e9:.0f} GB/s ({bw_kind})",
    ]
    print("\n".join(lines))
    if "--write" in argv:
        os.makedirs("artifacts", exist_ok=True)
        with open(ROOFLINE_ARTIFACT, "w") as f:
            f.write(
                "# ResNet-50 non-conv roofline (regenerated by "
                "`scripts/profile.py roofline`)\n\n"
                "Analytic HBM floor of the non-conv passes over the "
                "real zoo shapes.\nPass model per BN layer over "
                "activation bytes S (bf16): fwd 1-read\nstats + "
                "read/write apply (3S); bwd dy+x multi-output "
                "reductions (2S)\n+ dx read-read-write (3S); "
                "residual adds 5S. Measured context and\nthe "
                "fusion-share argument live in "
                "`resnet50_roofline_r5.md`.\n\n```\n"
                + "\n".join(lines) + "\n```\n"
            )
        print("written to", ROOFLINE_ARTIFACT)


# -- dp / dp2: data-parallel scaling attribution ------------------------
# Both run their measurements in child processes on an 8-device
# virtual CPU mesh (XLA_FLAGS host platform device count), so the
# parent's jax is never initialized with the wrong topology.

_DP_CHILD = r"""
import json, os, time
import numpy as np
from __graft_entry__ import _ensure_devices
_ensure_devices(8)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.compat import shard_map_compat
shard_map = shard_map_compat()
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import build_mesh
from deeplearning4j_tpu.zoo import resnet50

n = int(os.environ["DP_DEVICES"])
b = int(os.environ["DP_BATCH"])
steps = int(os.environ.get("DP_STEPS", "3"))
what = os.environ["DP_WHAT"]  # step | fwdbwd | pmean | update

conf = resnet50(height=32, width=32, channels=3, n_classes=10,
                cifar_stem=True, learning_rate=0.01)
net = ComputationGraph(conf).init()
mesh = build_mesh(data=n, model=1, devices=jax.devices()[:n])
updater = net.updater_def
rep_sh = NamedSharding(mesh, P())
dp_sh = NamedSharding(mesh, P("data"))

params = jax.device_put(net.params, rep_sh)
upd = jax.tree_util.tree_map(lambda a: jax.device_put(a, rep_sh),
                             net.updater_state)
state = jax.tree_util.tree_map(lambda a: jax.device_put(a, rep_sh),
                               net.state)
rng = jax.random.PRNGKey(0)
lrs = {k: jnp.asarray(v, jnp.float32)
       for k, v in updater.scheduled_lrs(0).items()}
t = jnp.asarray(1.0, jnp.float32)
rs = np.random.RandomState(0)
x = jax.device_put(rs.rand(b, 3, 32, 32).astype(np.float32), dp_sh)
y = jax.device_put(
    np.eye(10, dtype=np.float32)[rs.randint(0, 10, b)], dp_sh)

rep = P(); dp = P("data")

def time_fn(fn, args):
    out = fn(*args)          # compile + 1 run
    jax.block_until_ready(out)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times)

if what == "step":
    def step(params, upd, state, x, y, lrs, t, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = jax.lax.pmean(grads, "data")
        score = jax.lax.pmean(score, "data")
        new_params, new_upd = updater.update(grads, upd, params, lrs, t)
        new_state = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), new_state)
        return new_params, new_upd, new_state, score
    f = jax.jit(shard_map(step, mesh=mesh,
                          in_specs=(rep, rep, rep, dp, dp, rep, rep, rep),
                          out_specs=(rep, rep, rep, rep),
                          check_rep=False))
    sec = time_fn(f, (params, upd, state, x, y, lrs, t, rng))
elif what == "fwdbwd":
    def step(params, state, x, y, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return grads, new_state, score
    f = jax.jit(shard_map(step, mesh=mesh,
                          in_specs=(rep, rep, dp, dp, rep),
                          out_specs=(rep, rep, rep),
                          check_rep=False))
    sec = time_fn(f, (params, state, x, y, rng))
elif what == "pmean":
    def red(g, s):
        g = jax.lax.pmean(g, "data")
        s = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), s)
        return g, s
    f = jax.jit(shard_map(red, mesh=mesh, in_specs=(rep, rep),
                          out_specs=(rep, rep), check_rep=False))
    sec = time_fn(f, (params, state))
elif what == "update":
    def up(g, upd, params, lrs, t):
        return updater.update(g, upd, params, lrs, t)
    f = jax.jit(shard_map(up, mesh=mesh,
                          in_specs=(rep, rep, rep, rep, rep),
                          out_specs=(rep, rep), check_rep=False))
    sec = time_fn(f, (params, upd, params, lrs, t))
print(json.dumps({"what": what, "devices": n, "batch": b,
                  "sec": sec}))
"""

_DP2_CHILD = r"""
import json, os, time
import numpy as np
from __graft_entry__ import _ensure_devices
_ensure_devices(8)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.compat import shard_map_compat
shard_map = shard_map_compat()
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import build_mesh
from deeplearning4j_tpu.zoo import resnet50

n = int(os.environ["DP_DEVICES"])
b = int(os.environ["DP_BATCH"])
steps = int(os.environ.get("DP_STEPS", "3"))
variant = os.environ["DP_VARIANT"]

conf = resnet50(height=32, width=32, channels=3, n_classes=10,
                cifar_stem=True, learning_rate=0.01)
net = ComputationGraph(conf).init()
mesh = build_mesh(data=n, model=1, devices=jax.devices()[:n])
updater = net.updater_def
rep_sh = NamedSharding(mesh, P())
dp_sh = NamedSharding(mesh, P("data"))

def place(tree, sh):
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

rng = jax.random.PRNGKey(0)
lrs = {k: jnp.asarray(v, jnp.float32)
       for k, v in updater.scheduled_lrs(0).items()}
t = jnp.asarray(1.0, jnp.float32)
rs = np.random.RandomState(0)
x_h = rs.rand(b, 3, 32, 32).astype(np.float32)
y_h = np.eye(10, dtype=np.float32)[rs.randint(0, 10, b)]

rep = P(); dp = P("data")

def flat_pmean(tree, axis):
    # ONE fused all-reduce: DDP-style gradient bucketing
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate(
        [l.astype(jnp.float32).ravel() for l in leaves])
    flat = jax.lax.pmean(flat, axis)
    out, off = [], 0
    for l, s in zip(leaves, sizes):
        out.append(flat[off:off + s].reshape(l.shape).astype(l.dtype))
        off += s
    return jax.tree_util.tree_unflatten(treedef, out)

def make_step(state_mode, joint, flat):
    def step(params, upd, state, x, y, lrs, t, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if flat:
            red = (grads, score, new_state if state_mode == "pmean"
                   else None)
            grads, score, red_state = flat_pmean(red, "data")
            if state_mode == "pmean":
                new_state = red_state
        elif joint:
            to_red = (grads, score, new_state if state_mode == "pmean"
                      else None)
            grads, score, red_state = jax.lax.pmean(to_red, "data")
            if state_mode == "pmean":
                new_state = red_state
        else:
            grads = jax.lax.pmean(grads, "data")
            score = jax.lax.pmean(score, "data")
            if state_mode == "pmean":
                new_state = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "data"), new_state)
        new_params, new_upd = updater.update(grads, upd, params, lrs, t)
        return new_params, new_upd, new_state, score
    return step

def build(variant):
    donate = "donate" in variant
    state_mode = "local" if "nostate" in variant else "pmean"
    joint = "joint" in variant
    flat = "flat" in variant
    if variant.startswith("gspmd"):
        def step(params, upd, state, x, y, lrs, t, rng):
            def loss_fn(p):
                s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                        train=True, fmasks=None)
                return s, ns
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_upd = updater.update(
                grads, upd, params, lrs, t)
            return new_params, new_upd, new_state, score
        return jax.jit(
            step,
            in_shardings=(rep_sh, rep_sh, rep_sh, dp_sh, dp_sh,
                          None, None, None),
            out_shardings=(rep_sh, rep_sh, rep_sh, rep_sh),
            donate_argnums=(0, 1, 2) if donate else (),
        )
    f = shard_map(make_step(state_mode, joint, flat), mesh=mesh,
                  in_specs=(rep, rep, rep, dp, dp, rep, rep, rep),
                  out_specs=(rep, rep, rep, rep), check_rep=False)
    return jax.jit(f, donate_argnums=(0, 1, 2) if donate else ())

f = build(variant)
# host-side master copies: donation deletes the placed device arrays,
# so each iteration re-places from host
params_h = jax.tree_util.tree_map(np.asarray, net.params)
upd_h = jax.tree_util.tree_map(np.asarray, net.updater_state)
state_h = jax.tree_util.tree_map(np.asarray, net.state)
times = []
for it in range(steps + 1):
    params = place(params_h, rep_sh)
    upd = place(upd_h, rep_sh)
    state = place(state_h, rep_sh)
    x = jax.device_put(x_h, dp_sh); y = jax.device_put(y_h, dp_sh)
    jax.block_until_ready((params, upd, state, x, y))
    t0 = time.perf_counter()
    out = f(params, upd, state, x, y, lrs, t, rng)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    if it > 0:  # first = compile
        times.append(dt)
    del out
print(json.dumps({"variant": variant, "devices": n, "batch": b,
                  "sec": min(times)}))
"""


def _run_child(child_src, tag, extra_env, steps=3):
    env = dict(os.environ)
    env.update({
        "JAX_COMPILATION_CACHE_DIR": "/tmp/deeplearning4j_tpu_jax_cache",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"
                      ).strip(),
        "DP_STEPS": str(steps),
        "PYTHONPATH": REPO,
    })
    env.update(extra_env)
    t0 = time.time()
    out = subprocess.run([sys.executable, "-c", child_src], env=env,
                         capture_output=True, text=True, timeout=3600)
    wall = time.time() - t0
    if out.returncode != 0:
        return {**tag, "error": out.stderr[-1500:],
                "wall": round(wall, 1)}
    r = json.loads(out.stdout.strip().splitlines()[-1])
    r["wall"] = round(wall, 1)
    return r


def cmd_dp(argv):
    """Attribute dp_scaling overhead: full step vs collectives alone
    vs updater alone, n=1 vs n=8 on the virtual mesh."""
    results = []
    for what, n, b in [
        ("step", 1, 8), ("step", 8, 64),
        ("fwdbwd", 1, 8), ("fwdbwd", 8, 64),
        ("pmean", 8, 64),
        ("update", 1, 8), ("update", 8, 64),
    ]:
        r = _run_child(
            _DP_CHILD, {"what": what, "devices": n, "batch": b},
            {"DP_DEVICES": str(n), "DP_BATCH": str(b),
             "DP_WHAT": what},
        )
        results.append(r)
        print(json.dumps(r), flush=True)
    print(json.dumps({"all": results}))


def cmd_dp2(argv):
    """Sweep step compositions: donation, state-pmean placement,
    joint-vs-split pmean, GSPMD vs shard_map."""
    for variant, n, b in [
        ("plain", 8, 64),
        ("donate", 8, 64),
        ("flat", 8, 64),
        ("flat_donate", 8, 64),
        ("joint", 8, 64),
        ("nostate", 8, 64),
        ("gspmd_donate", 8, 64),
        ("donate", 1, 8),
        ("flat_donate", 1, 8),
    ]:
        print(json.dumps(_run_child(
            _DP2_CHILD, {"variant": variant, "devices": n, "batch": b},
            {"DP_DEVICES": str(n), "DP_BATCH": str(b),
             "DP_VARIANT": variant},
        )), flush=True)


COMMANDS = {
    "hlo": cmd_hlo,
    "trace": cmd_trace,
    "roofline": cmd_roofline,
    "dp": cmd_dp,
    "dp2": cmd_dp2,
}


def main():
    if len(sys.argv) < 2 or sys.argv[1] not in COMMANDS:
        print(__doc__)
        return 2
    return COMMANDS[sys.argv[1]](sys.argv[2:]) or 0


if __name__ == "__main__":
    sys.exit(main())
