"""A/B timing of the ResNet-50 bench step for BN-pass experiments
(VERDICT r5 #1). Times the same HBM-resident scan-fused fit window as
``bench.bench_resnet50`` and prints sec/step + MFU, so BN changes can
be iterated quickly on the chip.

Usage: python scripts/bn_ab.py [label]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    label = sys.argv[1] if len(sys.argv) > 1 else "run"
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.util.flops import (
        device_peak_flops,
        train_step_cost,
    )
    from deeplearning4j_tpu.zoo import resnet50
    from bench import _to_hbm

    batch = int(os.environ.get("RN_BATCH", "128"))
    chunk = int(os.environ.get("RN_CHUNK", "2"))
    epochs = int(os.environ.get("RN_EPOCHS", "8"))
    g = ComputationGraph(
        resnet50(dtype="bfloat16", learning_rate=0.01)
    ).init()
    g.scan_chunk = chunk
    rng = np.random.RandomState(0)
    batches = _to_hbm([
        DataSet(
            features=rng.randint(
                0, 256, (batch, 3, 224, 224), dtype=np.uint8
            ),
            labels=np.eye(1000, dtype=np.uint8)[
                rng.randint(0, 1000, batch)
            ],
        )
        for _ in range(chunk)
    ])
    flops_ex = train_step_cost(g, batches[0])["flops_per_example"]
    g.fit(batches, epochs=1)
    _ = float(g.score_value)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        g.fit(batches, epochs=epochs)
        _ = float(g.score_value)
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    n_ex = epochs * chunk * batch
    rate = n_ex / best
    peak, _kind = device_peak_flops()
    mfu = flops_ex * rate / peak
    print(f"[{label}] {rate:.1f} ex/s  "
          f"{best / (epochs * chunk) * 1000:.2f} ms/step  MFU {mfu:.4f}")


if __name__ == "__main__":
    main()
