#!/usr/bin/env python
"""Budget-boxed end-to-end continuous-learning loop demo.

One process exercises the whole production story under live traffic:

1. **train → publish**: a ``ContinualTrainer`` fits a stream and
   publishes versioned checkpoints with AOT serving bundles attached;
2. **serve**: a ``ModelServer`` boots from the newest checkpoint
   (deserializing the bundle) while closed-loop traffic threads drive
   ``/predict`` continuously — every admitted request must be
   answered (zero dropped in-flight requests, end to end);
3. **shadow → canary → promote**: the ``Promoter`` discovers the next
   published version, mirrors live traffic to it, clears the gates,
   and swaps it in via the canary-validated hot reload — with a
   **simulated SIGKILL landing right after the ``canarying`` journal
   write**; a fresh promoter recovers from the journal and rolls the
   half-applied promotion forward;
4. **inject regression → auto-rollback**: a candidate carrying a
   dead-feature time bomb (identical outputs on current traffic,
   divergent once the traffic distribution shifts) sails through
   shadowing, gets promoted — then the traffic shifts, probation
   catches the divergence against the previous version's retained
   snapshot, and the promoter rolls back with ZERO XLA compiles
   (counter-asserted: the snapshot still carries its executables);
5. **quarantine**: a corrupt candidate checkpoint is quarantined
   while the live version keeps serving.

Prints ONE JSON verdict line (always — a budget overrun or crash
prints a partial verdict with ``"pass": false``). Knobs:
``LOOP_BUDGET_S`` (default 240), ``LOOP_SEED`` (default 7).
"""

import json
import os
import signal
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BUDGET_S = int(os.environ.get("LOOP_BUDGET_S", "240"))
SEED = int(os.environ.get("LOOP_SEED", "7"))
BUCKETS = (1, 2, 4, 8)
DEAD_FEATURE = 3  # zero in baseline traffic; the regression flips it


def build_net(seed=SEED):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.Builder()
        .seed(seed).learning_rate(0.01).updater("ADAM")
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def stream(rng, n, batch=8):
    from deeplearning4j_tpu.datasets.api import (
        DataSet, ListDataSetIterator,
    )

    out = []
    for _ in range(n):
        x = rng.randn(batch, 4).astype(np.float32)
        x[:, DEAD_FEATURE] = 0.0  # training matches baseline traffic
        y = np.eye(3)[rng.randint(0, 3, batch)].astype(np.float32)
        out.append(DataSet(features=x, labels=y))
    return ListDataSetIterator(out)


class Traffic:
    """Closed-loop in-process load: N threads submitting seeded
    2-row predicts; ``shifted`` flips the dead feature live."""

    def __init__(self, server, threads=3):
        self.server = server
        self.shifted = False
        self.codes = {}
        self.dropped = 0  # submit() raised / returned nothing
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._loop, args=(i,), daemon=True)
            for i in range(threads)
        ]

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def _loop(self, i):
        rng = np.random.RandomState(SEED * 100 + i)
        while not self._stop.is_set():
            feats = rng.randn(2, 4).astype(np.float32)
            feats[:, DEAD_FEATURE] = (
                rng.randn(2).astype(np.float32) * 8.0
                if self.shifted else 0.0
            )
            try:
                code, body, _ = self.server.submit(feats)
                if not isinstance(code, int):
                    raise RuntimeError("no status")
            except Exception:
                code = -1
            with self._lock:
                if code == -1:
                    self.dropped += 1
                else:
                    self.codes[code] = self.codes.get(code, 0) + 1
            time.sleep(0.002)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def snapshot(self):
        with self._lock:
            return dict(self.codes), self.dropped


def wait_for(pred, timeout, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


def main() -> dict:
    from deeplearning4j_tpu.loop import (
        ContinualTrainer,
        Promoter,
        PromotionGates,
        PromotionJournal,
        SimulatedKill,
    )
    from deeplearning4j_tpu.resilience import CheckpointManager
    from deeplearning4j_tpu.serving.server import ModelServer

    verdict = {"pass": False, "phases": []}
    rng = np.random.RandomState(SEED)
    workdir = tempfile.mkdtemp(prefix="dl4j-loop-")
    manager = CheckpointManager(workdir, keep_last=4)
    journal = PromotionJournal(os.path.join(workdir,
                                            "promotion-journal.json"))
    net = build_net()
    trainer = ContinualTrainer(
        net, manager, publish_every=4, aot_buckets=BUCKETS,
        journal=journal,
    )

    # phase 1: train + publish v1/v2 (steps 4, 8), AOT attached
    t0 = time.monotonic()
    trainer.run(stream(rng, 8))
    verdict["phases"].append({"train_publish": manager.list_steps(),
                              "s": round(time.monotonic() - t0, 2)})

    server = ModelServer(
        checkpoint_manager=manager, workers=2, queue_depth=32,
        max_batch_size=max(BUCKETS), aot=True,
    ).start()
    gates = PromotionGates(
        min_shadow_requests=16, min_agreement=0.5,
        probation_requests=16, probation_min_agreement=0.8,
        probation_min_seconds=2.0, max_error_rate=0.02,
    )
    traffic = Traffic(server).start()
    try:
        promoter = Promoter(server, manager, journal, gates=gates,
                            seed=SEED)
        promoter.recover()

        # phase 2: good candidate (step 12) + SIGKILL mid-promotion
        trainer.run(stream(rng, 4))
        promoter.fail_after_journal = "canarying"
        killed = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                promoter.poll()
            except SimulatedKill:
                killed = True
                break
            time.sleep(0.05)
        # the "fresh process": a new promoter over the same journal
        promoter2 = Promoter(server, manager, journal, gates=gates,
                             seed=SEED)
        promoter2.recover()  # rolls the half-applied promotion forward
        sealed = wait_for(
            lambda: (promoter2.poll() == "promoted"
                     and not journal.read().get("probation")), 60,
        )
        snap = promoter2.snapshot()
        verdict["phases"].append({
            "promotion": {
                "sigkill_injected": killed,
                "recovered_and_sealed": sealed,
                "promoted_step": snap["promoted_step"],
                "journal_recoveries": snap["journal_recoveries"],
            }
        })

        # phase 3: the regression candidate — identical on today's
        # traffic (dead feature is zero), divergent once it shifts
        bomb_src, info = manager.restore_latest(load_updater=False)
        w = np.array(bomb_src.params["0"]["W"])
        w[DEAD_FEATURE, :] = np.where(
            np.arange(w.shape[1]) % 2 == 0, 40.0, -40.0
        )
        bomb_src.params["0"]["W"] = w
        bomb_src.iteration_count = info.step + 1
        manager.save(bomb_src)
        bomb_step = info.step + 1

        promoted_bomb = wait_for(
            lambda: (promoter2.poll() == "promoted"
                     and journal.read().get("promoted_step")
                     == bomb_step), 60,
        )
        compiles_before = server.metrics.get("xla_compiles_total")
        traffic.shifted = True  # the distribution shift goes live
        rolled_back = wait_for(
            lambda: promoter2.poll() == "rolled_back", 60,
        )
        time.sleep(0.5)  # post-rollback traffic on the old snapshot
        compiles_after = server.metrics.get("xla_compiles_total")
        feats = np.zeros((2, 4), np.float32)
        code, body, _ = server.submit(feats)
        snap = promoter2.snapshot()
        verdict["phases"].append({
            "rollback": {
                "bomb_promoted": promoted_bomb,
                "rolled_back": rolled_back,
                "serving_after": code == 200,
                "promoted_step_after": snap["promoted_step"],
                "xla_compiles_during_rollback":
                    compiles_after - compiles_before,
                "rollbacks": snap["rollbacks"],
            }
        })
        traffic.shifted = False

        # phase 4: corrupt candidate → quarantine, live unaffected
        trainer.run(stream(rng, 4))
        bad = manager.available()[-1]
        zpath = os.path.join(workdir, bad.file)
        with open(zpath, "r+b") as f:
            f.write(b"corrupt!")
        q = wait_for(lambda: promoter2.poll() == "quarantined", 30)
        code, _, _ = server.submit(np.zeros((1, 4), np.float32))
        snap = promoter2.snapshot()
        verdict["phases"].append({
            "quarantine": {"quarantined": q,
                           "still_serving": code == 200,
                           "count": snap["quarantined"]},
        })

        traffic.stop()
        codes, dropped = traffic.snapshot()
        metrics = server.metrics_snapshot()
        verdict["requests"] = {
            "codes": codes,
            "dropped": dropped,
            "server_errors": metrics["server_error_total"],
            "deadline_timeouts": metrics["deadline_timeout_total"],
        }
        verdict["loop"] = {
            "promotions": snap["promotions"],
            "rollbacks": snap["rollbacks"],
            "rejected": snap["rejected"],
            "quarantined": snap["quarantined"],
            "journal_recoveries": snap["journal_recoveries"],
            "reload_skipped": metrics["reload_skipped_total"],
            "journal_state": journal.read().get("state"),
        }
        ok_codes = all(c == 200 for c in codes)
        verdict["pass"] = bool(
            killed and sealed
            and promoted_bomb and rolled_back and q
            and snap["promotions"] >= 2
            and snap["rollbacks"] >= 1
            and snap["journal_recoveries"] >= 1
            and dropped == 0 and ok_codes
            and metrics["server_error_total"] == 0
            and verdict["phases"][2]["rollback"]
                ["xla_compiles_during_rollback"] == 0
        )
    finally:
        try:
            traffic.stop()
        except Exception:
            pass
        server.stop(drain_timeout=2)
    return verdict


if __name__ == "__main__":
    verdict = {"pass": False, "error": "budget exceeded",
               "budget_s": BUDGET_S}

    def _alarm(signum, frame):
        raise TimeoutError("loop demo budget exceeded")

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(BUDGET_S)
    try:
        verdict = main()
    except TimeoutError:
        pass
    except Exception as e:  # partial verdict, never a bare trace
        verdict = {"pass": False,
                   "error": f"{type(e).__name__}: {e}"}
    finally:
        signal.alarm(0)
        print(json.dumps(verdict, default=str))
    sys.exit(0 if verdict.get("pass") else 1)
