# -*- coding: utf-8 -*-
"""Generate the scaled Japanese lexicon TSV
(``deeplearning4j_tpu/nlp/data/ja_lexicon.tsv``) from base word lists
+ conjugation paradigms (VERDICT r5 #10: grow the mini lexicon into
thousands of entries THROUGH the existing entry format, the way
Kuromoji compiles IPADIC into its dictionary files — here the source
is hand-authored base vocabulary expanded by standard godan/ichidan/
i-adjective conjugation, which is plain linguistic data).

Deterministic: re-running reproduces the file byte-for-byte.
Usage: python scripts/gen_ja_lexicon.py
"""
import os
import sys

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deeplearning4j_tpu", "nlp", "data", "ja_lexicon.tsv",
)

# class names must match japanese.py _CLASS_NAMES
N, V, VSTEM, ADJ, ADV, PRON = "noun", "verb", "verb-stem", "adjective", "adverb", "pronoun"

# -- base vocabulary --------------------------------------------------------
# godan verbs: (stem-without-final-kana, final kana row key, gloss row)
GODAN = [
    ("会", "う"), ("合", "う"), ("買", "う"), ("歌", "う"), ("払", "う"),
    ("笑", "う"), ("習", "う"), ("洗", "う"), ("違", "う"), ("向か", "う"),
    ("手伝", "う"), ("もら", "う"), ("言", "う"), ("書", "く"), ("歩", "く"),
    ("働", "く"), ("聞", "く"), ("着", "く"), ("開", "く"), ("泣", "く"),
    ("引", "く"), ("弾", "く"), ("吹", "く"), ("乾", "く"), ("招", "く"),
    ("泳", "ぐ"), ("急", "ぐ"), ("脱", "ぐ"), ("騒", "ぐ"), ("稼", "ぐ"),
    ("話", "す"), ("出", "す"), ("消", "す"), ("押", "す"), ("貸", "す"),
    ("返", "す"), ("渡", "す"), ("直", "す"), ("探", "す"), ("試", "す"),
    ("示", "す"), ("移", "す"), ("残", "す"), ("倒", "す"), ("立", "つ"),
    ("持", "つ"), ("勝", "つ"), ("打", "つ"), ("育", "つ"), ("死", "ぬ"),
    ("遊", "ぶ"), ("呼", "ぶ"), ("飛", "ぶ"), ("選", "ぶ"), ("運", "ぶ"),
    ("学", "ぶ"), ("並", "ぶ"), ("喜", "ぶ"), ("読", "む"), ("飲", "む"),
    ("休", "む"), ("住", "む"), ("進", "む"), ("頼", "む"), ("包", "む"),
    ("盗", "む"), ("悩", "む"), ("楽し", "む"), ("込", "む"), ("踏", "む"),
    ("作", "る"), ("乗", "る"), ("売", "る"), ("取", "る"), ("送", "る"),
    ("帰", "る"), ("入", "る"), ("走", "る"), ("知", "る"), ("切", "る"),
    ("降", "る"), ("触", "る"), ("曲が", "る"), ("始ま", "る"), ("終わ", "る"),
    ("変わ", "る"), ("止ま", "る"), ("集ま", "る"), ("決ま", "る"), ("困", "る"),
    ("頑張", "る"), ("座", "る"), ("登", "る"), ("戻", "る"), ("配", "る"),
    ("断", "る"), ("祈", "る"), ("踊", "る"), ("怒", "る"), ("謝", "る"),
]
# ichidan verbs (drop る for the stem)
ICHIDAN = [
    "食べる", "見せる", "開ける", "閉める", "教える", "覚える", "考える",
    "答える", "伝える", "変える", "加える", "迎える", "数える", "植える",
    "起きる", "借りる", "降りる", "浴びる", "信じる", "感じる", "生きる",
    "見る", "居る", "似る", "煮る", "干る", "射る", "鋳る", "率いる",
    "過ぎる", "できる", "着る", "出る", "寝る", "入れる", "忘れる",
    "疲れる", "晴れる", "流れる", "倒れる", "生まれる", "別れる", "遅れる",
    "続ける", "見つける", "付ける", "届ける", "避ける", "受ける", "助ける",
    "投げる", "逃げる", "曲げる", "上げる", "下げる", "挙げる", "捨てる",
    "育てる", "建てる", "止める", "集める", "決める", "始める", "眺める",
    "褒める", "辞める", "調べる", "比べる", "並べる", "食べさせる",
]
# i-adjectives
I_ADJ = [
    "長い", "短い", "強い", "弱い", "早い", "遅い", "近い", "遠い",
    "多い", "少ない", "広い", "狭い", "重い", "軽い", "暑い", "寒い",
    "暖かい", "涼しい", "熱い", "冷たい", "甘い", "辛い", "苦い",
    "美味しい", "不味い", "楽しい", "悲しい", "嬉しい", "寂しい",
    "難しい", "易しい", "優しい", "厳しい", "忙しい", "珍しい",
    "美しい", "汚い", "危ない", "安い", "若い", "古い", "明るい",
    "暗い", "白い", "黒い", "赤い", "青い", "丸い", "細い", "太い",
]
# common nouns (kanji compounds and basics)
NOUNS = [
    "時計", "手紙", "写真", "映画", "音楽", "料理", "野菜", "果物",
    "朝食", "昼食", "夕食", "食事", "部屋", "建物", "病院", "銀行",
    "駅前", "空港", "道路", "地図", "旅行", "計画", "予定", "約束",
    "質問", "答え", "問題", "宿題", "試験", "授業", "教室", "黒板",
    "辞書", "新聞", "雑誌", "番組", "電話", "電気", "機械", "技術",
    "科学", "数学", "歴史", "文化", "社会", "経済", "政治", "法律",
    "国際", "情報", "通信", "計算", "記憶", "学習", "研究", "開発",
    "設計", "実験", "結果", "理由", "原因", "目的", "方法", "手段",
    "性能", "速度", "距離", "重さ", "高さ", "深さ", "温度", "天気",
    "天気予報", "気温", "季節", "春", "夏", "秋", "冬", "朝", "昼",
    "夜", "夕方", "午前", "午後", "週末", "平日", "毎日", "毎週",
    "毎月", "毎年", "来週", "来月", "来年", "先週", "先月", "去年",
    "今年", "今月", "今週", "最近", "将来", "未来", "過去", "現在",
    "家族", "両親", "父親", "母親", "兄弟", "姉妹", "子供", "大人",
    "友達", "友人", "知人", "隣人", "彼氏", "彼女ら", "自分", "相手",
    "皆さん", "男性", "女性", "少年", "少女", "赤ちゃん", "名前",
    "住所", "番号", "年齢", "誕生日", "記念日", "祭り", "祝日",
    "休み", "休暇", "仕事場", "職場", "会議", "会話", "相談", "説明",
    "紹介", "招待", "連絡", "報告", "準備", "練習", "運動", "散歩",
    "買い物", "洗濯", "掃除", "料金", "値段", "お金", "財布", "切符",
    "荷物", "鞄", "傘", "帽子", "眼鏡", "靴", "服", "洋服", "着物",
    "椅子", "机", "窓", "扉", "壁", "床", "屋根", "庭", "公園",
    "図書館", "美術館", "博物館", "動物園", "植物", "動物", "鳥",
    "魚", "馬", "牛", "豚", "羊", "象", "熊", "兎", "亀", "虫",
    "花", "桜", "松", "竹", "梅", "森", "林", "山", "川", "海",
    "湖", "島", "空", "星", "雲", "雨", "雪", "風", "嵐", "地震",
    "火事", "事故", "事件", "警察", "消防", "救急車", "病気", "怪我",
    "薬", "医者", "看護師", "患者", "健康", "体", "頭", "顔", "目",
    "耳", "鼻", "口", "歯", "手", "足", "指", "心", "声", "涙",
    "笑顔", "気持ち", "気分", "感情", "考え", "意見", "夢", "希望",
    "心配", "安心", "興味", "趣味", "特技", "才能", "努力", "成功",
    "失敗", "経験", "知識", "能力", "力", "元気", "勇気", "自由",
    "平和", "戦争", "国", "都市", "町", "村", "地方", "外国",
    "外国人", "言葉", "文字", "漢字", "平仮名", "片仮名", "発音",
    "文法", "翻訳", "通訳", "小説", "物語", "詩", "絵", "歌", "踊り",
    "劇", "芝居", "遊び", "玩具", "人形", "箱", "紙", "鉛筆", "消しゴム",
    "鋏", "糊", "定規", "筆", "墨", "印鑑", "鍵", "道具", "材料",
    "製品", "商品", "工場", "農場", "畑", "田んぼ", "米", "麦", "豆",
    "卵", "牛乳", "肉", "魚介", "塩", "砂糖", "醤油", "味噌", "酢",
    "油", "茶", "お茶", "珈琲", "紅茶", "酒", "ビール", "葡萄酒",
]
# katakana loanwords
KATAKANA = [
    "コンピュータ", "コンピューター", "インターネット", "メール",
    "ソフトウェア", "ハードウェア", "プログラム", "データ", "ファイル",
    "システム", "ネットワーク", "サーバー", "クラウド", "アプリ",
    "スマートフォン", "テレビ", "ラジオ", "カメラ", "ビデオ",
    "ニュース", "スポーツ", "サッカー", "テニス", "ゴルフ", "スキー",
    "プール", "ホテル", "レストラン", "メニュー", "サービス",
    "コーヒー", "ジュース", "ミルク", "パン", "ケーキ", "チーズ",
    "サラダ", "スープ", "ライス", "バス", "タクシー", "トラック",
    "エレベーター", "エスカレーター", "ドア", "テーブル", "ベッド",
    "ソファ", "カーテン", "シャワー", "トイレ", "キッチン", "ガラス",
    "プラスチック", "エネルギー", "ガソリン", "バッテリー", "ロボット",
    "デザイン", "プロジェクト", "チーム", "リーダー", "メンバー",
    "パーティー", "コンサート", "チケット", "ゲーム", "テスト",
    "クラス", "ノート", "ペン", "ペーパー", "カード", "プレゼント",
]
# na-adjectives / adverbs (single-class entries)
NA_ADJ = [
    "静か", "賑やか", "綺麗", "便利", "不便", "簡単", "複雑", "大切",
    "大事", "重要", "必要", "十分", "有名", "元気", "丁寧", "親切",
    "真面目", "熱心", "自然", "安全", "危険", "特別", "普通", "変",
]
ADVERBS = [
    "とても", "すごく", "かなり", "少し", "ちょっと", "たくさん",
    "いつも", "時々", "たまに", "よく", "もう", "まだ", "すぐ",
    "ゆっくり", "はっきり", "しっかり", "きっと", "たぶん", "もちろん",
    "やはり", "やっぱり", "つまり", "例えば", "特に", "絶対に",
]

_GODAN_ROWS = {
    "う": ("い", "った", "って", "わ", "お", "え"),
    "く": ("き", "いた", "いて", "か", "こ", "け"),
    "ぐ": ("ぎ", "いだ", "いで", "が", "ご", "げ"),
    "す": ("し", "した", "して", "さ", "そ", "せ"),
    "つ": ("ち", "った", "って", "た", "と", "て"),
    "ぬ": ("に", "んだ", "んで", "な", "の", "ね"),
    "ぶ": ("び", "んだ", "んで", "ば", "ぼ", "べ"),
    "む": ("み", "んだ", "んで", "ま", "も", "め"),
    "る": ("り", "った", "って", "ら", "ろ", "れ"),
}


def conjugate_godan(stem, row):
    base = stem + row
    i, ta, te, a, o, e = _GODAN_ROWS[row]
    return [
        (base, V, "", base),
        (stem + ta, V, "past", base),
        (stem + te, V, "te", base),
        (stem + i, VSTEM, "stem", base),
        (stem + a + "ない", V, "negative", base),
        (stem + a + "なかった", V, "negative-past", base),
        (stem + o + "う", V, "volitional", base),
        (stem + e + "ば", V, "conditional", base),
        (stem + e + "る", V, "potential", base),
    ]


def conjugate_ichidan(base):
    stem = base[:-1]
    return [
        (base, V, "", base),
        (stem + "た", V, "past", base),
        (stem + "て", V, "te", base),
        (stem, VSTEM, "stem", base),
        (stem + "ない", V, "negative", base),
        (stem + "なかった", V, "negative-past", base),
        (stem + "よう", V, "volitional", base),
        (stem + "れば", V, "conditional", base),
        (stem + "られる", V, "potential", base),
    ]


def conjugate_i_adj(base):
    stem = base[:-1]
    return [
        (base, ADJ, "", base),
        (stem + "く", ADJ, "continuative", base),
        (stem + "かった", ADJ, "past", base),
        (stem + "くない", ADJ, "negative", base),
        (stem + "くて", ADJ, "te", base),
        (stem + "ければ", ADJ, "conditional", base),
    ]


def main():
    entries = []  # (surface, cost, class_name, detail, base)

    def add(surface, cls, detail="", base="", cost=None):
        if cost is None:
            # longer surfaces get mildly cheaper per-char cost so the
            # lattice prefers one compound over two fragments, same
            # shape as the hand-set core lexicon
            cost = max(200, 320 - 10 * len(surface))
        entries.append((surface, cost, cls, detail, base or surface))

    for stem, row in GODAN:
        for s, cls, det, base in conjugate_godan(stem, row):
            add(s, cls, det, base, cost=280 if cls == V else 270)
    for base in ICHIDAN:
        for s, cls, det, b in conjugate_ichidan(base):
            add(s, cls, det, b, cost=280 if cls == V else 270)
    for base in I_ADJ:
        for s, cls, det, b in conjugate_i_adj(base):
            add(s, cls, det, b, cost=285)
    for wd in NOUNS:
        add(wd, N)
    for wd in KATAKANA:
        add(wd, N, "loanword")
    for wd in NA_ADJ:
        add(wd, ADJ, "na")
        add(wd + "な", ADJ, "na-attributive", wd, cost=300)
        add(wd + "に", ADV, "na-adverbial", wd, cost=305)
    for wd in ADVERBS:
        add(wd, ADV)

    # dedupe: keep the cheapest entry per (surface, class, detail)
    seen = {}
    for surface, cost, cls, det, base in entries:
        k = (surface, cls, det)
        if k not in seen or cost < seen[k][1]:
            seen[k] = (surface, cost, cls, det, base)
    rows = sorted(seen.values())
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w", encoding="utf-8") as f:
        f.write("# generated by scripts/gen_ja_lexicon.py — "
                "surface\tcost\tclass\tdetail\tbase\n")
        for surface, cost, cls, det, base in rows:
            f.write(f"{surface}\t{cost}\t{cls}\t{det}\t{base}\n")
    print(f"{len(rows)} entries -> {OUT}")


if __name__ == "__main__":
    main()
