#!/usr/bin/env python
"""Static check: the metric signal catalog and the code agree.

Every metric name registered in ``deeplearning4j_tpu/`` (via
``registry.counter/gauge/histogram/summary("name", ...)`` calls, or
via the ``COUNTER_HELP``/``MODEL_COUNTER_HELP``/``COUNTERS`` name
tables in ``serving/metrics.py``) must appear in the ARCHITECTURE.md signal
catalog (the table between the ``metric-catalog`` markers), and vice
versa — so the catalog an operator builds dashboards from cannot
silently drift from what the code actually exports.

Pure AST + text scan: nothing is imported, so this runs in
milliseconds and in any environment (it is part of the
``scripts/run_chaos.sh`` preamble — drift fails loudly before the
chaos suite spends a second).

Exit 0 when in sync; exit 1 with the exact missing names otherwise.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "deeplearning4j_tpu"
DOC = REPO / "docs" / "ARCHITECTURE.md"

REGISTER_METHODS = {"counter", "gauge", "histogram", "summary"}
NAME_TABLE_TARGETS = {"COUNTER_HELP", "COUNTERS",
                      "MODEL_COUNTER_HELP"}
CATALOG_BEGIN = "<!-- metric-catalog:begin -->"
CATALOG_END = "<!-- metric-catalog:end -->"


def registered_names() -> "dict[str, list[str]]":
    """{metric name: [source files]} from the package's AST."""
    out: dict = {}

    def add(name, path):
        out.setdefault(name, []).append(str(path.relative_to(REPO)))

    for path in sorted(PACKAGE.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            # obj.counter("name", ...) and friends
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in REGISTER_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                add(node.args[0].value, path)
            # COUNTER_HELP = {"name": "help", ...} / COUNTERS = (...)
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Name)
                            and tgt.id in NAME_TABLE_TARGETS):
                        continue
                    # dict tables contribute their KEYS (values are
                    # help strings); tuples contribute every element
                    lits = (node.value.keys
                            if isinstance(node.value, ast.Dict)
                            else list(ast.walk(node.value)))
                    for lit in lits:
                        if (isinstance(lit, ast.Constant)
                                and isinstance(lit.value, str)
                                and re.fullmatch(
                                    r"[a-z][a-z0-9_]*", lit.value
                                )):
                            add(lit.value, path)
    return out


def catalog_names() -> "set[str]":
    """Backticked first-column names from the ARCHITECTURE.md signal
    catalog table (between the metric-catalog markers)."""
    text = DOC.read_text()
    try:
        start = text.index(CATALOG_BEGIN)
        end = text.index(CATALOG_END)
    except ValueError:
        print(f"lint_metrics: {DOC} has no "
              f"{CATALOG_BEGIN} .. {CATALOG_END} section",
              file=sys.stderr)
        sys.exit(1)
    names = set()
    for line in text[start:end].splitlines():
        m = re.match(r"\s*\|\s*`([a-zA-Z_:][a-zA-Z0-9_:]*)`", line)
        if m:
            names.add(m.group(1))
    return names


def main() -> int:
    code = registered_names()
    doc = catalog_names()
    missing_in_doc = sorted(set(code) - doc)
    missing_in_code = sorted(doc - set(code))
    if missing_in_doc:
        print("metrics registered in code but MISSING from the "
              "ARCHITECTURE.md signal catalog:", file=sys.stderr)
        for n in missing_in_doc:
            print(f"  {n}  (registered in {', '.join(code[n])})",
                  file=sys.stderr)
    if missing_in_code:
        print("metrics in the ARCHITECTURE.md signal catalog but "
              "NOT registered anywhere in code:", file=sys.stderr)
        for n in missing_in_code:
            print(f"  {n}", file=sys.stderr)
    if missing_in_doc or missing_in_code:
        return 1
    print(f"lint_metrics: {len(code)} metric names in sync with the "
          "signal catalog")
    return 0


if __name__ == "__main__":
    sys.exit(main())
