"""VGG-16/CIFAR profile + A/B harness (VERDICT r5 #5). Times the
bench fit window at selectable batch size, optionally traces it, and
prints ms/step + MFU.

Usage: RN_BATCH=128 python scripts/vgg_ab.py [label] [--trace outdir]
"""
import os
import sys
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    label = sys.argv[1] if len(sys.argv) > 1 else "run"
    import jax

    from bench import _to_hbm, _vgg16_conf
    from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.util.flops import (
        device_peak_flops,
        train_step_cost,
    )

    batch = int(os.environ.get("RN_BATCH", "128"))
    chunk = int(os.environ.get("RN_CHUNK", "4"))
    epochs = int(os.environ.get("RN_EPOCHS", "6"))
    g = ComputationGraph(_vgg16_conf()).init()
    g.scan_chunk = chunk
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        it = CifarDataSetIterator(
            batch, num_examples=batch * chunk, allow_synthetic=True,
            seed=0,
        )
    batches = _to_hbm(list(it))
    flops_ex = train_step_cost(g, batches[0])["flops_per_example"]
    g.fit(batches, epochs=1)
    _ = float(g.score_value)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        g.fit(batches, epochs=epochs)
        _ = float(g.score_value)
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    n_ex = epochs * chunk * batch
    rate = n_ex / best
    peak, _kind = device_peak_flops()
    mfu = flops_ex * rate / peak
    print(f"[{label}] batch={batch} {rate:.1f} ex/s  "
          f"{best / (epochs * chunk) * 1000:.2f} ms/step  MFU {mfu:.4f}")
    if "--trace" in sys.argv:
        outdir = sys.argv[sys.argv.index("--trace") + 1]
        jax.profiler.start_trace(outdir)
        g.fit(batches, epochs=2)
        _ = float(g.score_value)
        jax.profiler.stop_trace()
        print("trace written to", outdir)


if __name__ == "__main__":
    main()
