"""ResNet-50 MFU diagnosis (VERDICT r3 #3): dump the optimized HLO of
the exact bench train step (layouts, transpose/copy counts, dtype mix)
and capture a jax profiler trace of on-chip steps into artifacts/.

Usage: python scripts/profile_resnet.py [--skip-trace]
"""
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo import resnet50

    batch = int(os.environ.get("RN_BATCH", "128"))
    g = ComputationGraph(
        resnet50(dtype="bfloat16", learning_rate=0.01)
    ).init()
    g.scan_chunk = 1
    rng = np.random.RandomState(0)
    ds = DataSet(
        features=rng.randint(0, 256, (batch, 3, 224, 224),
                             dtype=np.uint8),
        labels=np.eye(1000, dtype=np.uint8)[
            rng.randint(0, 1000, batch)
        ],
    )
    # ---- optimized HLO of the single-step program -------------------
    # fit_minibatch compiles the per-step program; reach it through the
    # same builder the engine uses
    g.fit_minibatch(ds)  # compile + 1 step
    _ = float(g.score_value)
    step_fn = g._jit_step
    if step_fn is None:
        print("no _jit_step; falling back to timing only")
    else:
        import jax.numpy as jnp

        dtype = g._dtype()
        inputs = [jnp.asarray(ds.features, dtype)]
        labels = [jnp.asarray(ds.labels, dtype)]
        lrs = {
            k: jnp.asarray(v, jnp.float32)
            for k, v in g.updater_def.scheduled_lrs(
                g.iteration_count
            ).items()
        }
        t = jnp.asarray(g.iteration_count + 1, jnp.float32)
        rng = jax.random.fold_in(g._base_key, g.iteration_count)
        try:
            txt = step_fn.lower(
                g.params, g.updater_state, g.state, inputs, labels,
                None, None, lrs, t, rng,
            ).compile().as_text()
        except Exception as e:
            txt = None
            print("HLO lowering failed:", repr(e))
        if txt:
            out = os.path.join("artifacts", "resnet50_hlo_r4.txt")
            with open(out, "w") as f:
                f.write(txt)
            ops = re.findall(r"^\s*%?\S+ = (\S+?)\(", txt, re.M)
            from collections import Counter

            c = Counter(
                re.sub(r"\..*", "", re.sub(r"\(.*", "", o)) for o in ops
            )
            interesting = {
                k: v for k, v in c.items()
                if any(s in k for s in (
                    "transpose", "copy", "convolution", "fusion",
                    "all-reduce", "reduce", "dot",
                ))
            }
            print("HLO op histogram (interesting):", interesting)
            # operand layouts of convolutions
            convs = re.findall(
                r"= (\S+)\[([^\]]*)\]\{([^}]*)\} convolution", txt
            )
            print("conv output dtype/shape/layout (first 5):",
                  convs[:5])
            print("HLO written to", out)

    # ---- step timing ------------------------------------------------
    for _ in range(2):
        g.fit_minibatch(ds)
    _ = float(g.score_value)
    times = []
    for _ in range(6):
        t0 = time.perf_counter()
        g.fit_minibatch(ds)
        _ = float(g.score_value)
        times.append(time.perf_counter() - t0)
    step_s = min(times)
    from deeplearning4j_tpu.util.flops import (
        device_peak_flops,
        train_step_cost,
    )

    flops = train_step_cost(g, ds)["flops"]
    peak, kind = device_peak_flops()
    mfu = flops / step_s / peak if peak else None
    print(f"step {step_s*1000:.1f} ms  batch {batch}  "
          f"{batch/step_s:.1f} ex/s  MFU {mfu:.3f}" if mfu else step_s)

    # ---- profiler trace ---------------------------------------------
    if "--skip-trace" not in sys.argv:
        trace_dir = os.path.join("artifacts", "resnet50_trace_r4")
        jax.profiler.start_trace(trace_dir)
        for _ in range(3):
            g.fit_minibatch(ds)
        _ = float(g.score_value)
        jax.profiler.stop_trace()
        print("trace written to", trace_dir)


if __name__ == "__main__":
    main()
