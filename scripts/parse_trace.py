"""Aggregate a jax profiler xplane trace into per-op time fractions
(TPU device plane), without tensorboard: parse xplane_pb2 directly
and roll up LEAF event durations on the ``XLA Ops`` line by HLO
category / op name (container events — while wrappers, module/step
spans — are excluded so fractions sum to wall time).

Usage: python scripts/parse_trace.py <trace_dir> [top_n]
"""
import collections
import glob
import sys

from tensorflow.tsl.profiler.protobuf import xplane_pb2


def _is_container(name: str) -> bool:
    return (
        name.startswith(("%while", "jit_"))
        or name.isdigit()
        or name == "?"
    )


def main():
    trace_dir = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    paths = glob.glob(f"{trace_dir}/plugins/profile/*/*.xplane.pb")
    sp = xplane_pb2.XSpace()
    sp.ParseFromString(open(sorted(paths)[-1], "rb").read())
    for plane in sp.planes:
        if "TPU" not in plane.name:
            continue
        meta = {m.id: m.name for m in plane.event_metadata.values()}
        smeta = {m.id: m.name for m in plane.stat_metadata.values()}
        cat_of = {}
        for m in plane.event_metadata.values():
            for st in m.stats:
                if smeta.get(st.metadata_id) == "hlo_category":
                    cat_of[m.id] = st.str_value
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            evs = [
                ev for ev in line.events
                if not _is_container(meta.get(ev.metadata_id, "?"))
            ]
            total = sum(ev.duration_ps for ev in evs)
            if not total:
                continue
            by_op = collections.Counter()
            by_cat = collections.Counter()
            for ev in evs:
                by_op[meta.get(ev.metadata_id, "?")] += ev.duration_ps
                by_cat[
                    cat_of.get(ev.metadata_id, "uncategorized")
                ] += ev.duration_ps
            print(f"== plane: {plane.name}  "
                  f"leaf busy {total/1e9:.1f} ms")
            print("-- by category --")
            for cat, d in by_cat.most_common(12):
                print(f"  {d/total:6.2%}  {cat}")
            print(f"-- top {top_n} ops --")
            for op, d in by_op.most_common(top_n):
                print(f"  {d/total:6.2%}  {op[:100]}")


if __name__ == "__main__":
    main()
