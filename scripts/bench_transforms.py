#!/usr/bin/env python
"""Whole-net transform benchmarks: compile-vs-depth and remat-memory.

Two A/Bs for the ``nn/core.py`` transforms, each measurement in a
FRESH subprocess with the persistent compile cache disabled so every
reported compile is a real XLA compile (a warm cache would make the
scan-over-layers ratio meaningless on the second run):

``compile_vs_depth``
    Wall-clock trace+compile of the jitted train step for a
    homogeneous TransformerBlock stack at depth 4 / 16 / 64, with
    scan-over-layers off vs on. Off: the HLO is O(depth) and XLA's
    optimization passes scale super-linearly with it — this is
    exactly the mode that blew the BENCH r05/r06 budgets. On: the
    block body is traced once under ``lax.scan``, so compile time is
    ~flat in depth. Gate: ``speedup_depth64 >= 2``.

``remat_memory``
    XLA's own memory plan (``compiled.memory_analysis()``: temp
    buffer bytes = the activation working set) for the train step of
    the transformer config, remat off vs on (``full``), plus the
    max batch that fits a fixed activation budget (the remat-off
    working set at the base batch) under each policy — the
    "2x batch at fixed HBM" claim made falsifiable on any backend.
    On backends that report ``memory_stats()`` (TPU) the measured
    peak bytes ride along. Gate: ``batch_ratio >= 1.5`` (or
    equivalently ``temp_bytes_ratio >= 1.5``).

Prints ONE JSON line; runnable standalone or from ``bench.py``'s
``compile_vs_depth`` / ``remat_memory`` sections (PR-5 SIGALRM budget
box + PR-6 compile-stats sidecar ride along in the bench harness).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# every measurement runs in a child with a FRESH, empty compile cache
# dir (and jax's persistent cache left off) — honest cold compiles
_CHILD_ENV_BASE = {
    "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    "JAX_COMPILATION_CACHE_DIR": "",
    "DL4J_TPU_COMPILE_CACHE_DIR": "",
    "PYTHONPATH": REPO,
}

_MEASURE_SRC = r"""
import json, sys, time
import numpy as np

spec = json.loads(sys.argv[1])
from deeplearning4j_tpu.zoo.models import transformer_lm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn import core
import jax.numpy as jnp
import jax

conf = transformer_lm(
    vocab=spec["vocab"], d_model=spec["d_model"],
    n_layers=spec["depth"], n_heads=spec["heads"],
    scan_layers=spec["scan"], remat=spec["remat"],
)
net = MultiLayerNetwork(conf).init()
rng = np.random.RandomState(0)
b, t = spec["batch"], spec["seq"]
x = jnp.asarray(rng.randn(b, spec["vocab"], t).astype(np.float32))
y = jnp.asarray(np.eye(spec["vocab"], dtype=np.float32)[
    rng.randint(0, spec["vocab"], (b, t))
].transpose(0, 2, 1))
lrs = {k: jnp.asarray(v, jnp.float32)
       for k, v in net.updater_def.scheduled_lrs(0).items()}
tt = jnp.asarray(1, jnp.float32)
key = jax.random.fold_in(net._base_key, 0)
step = net._build_step()
t0 = time.perf_counter()
lowered = step.lower(net.params, net.updater_state, net.state,
                     x, y, None, None, lrs, tt, key)
t_trace = time.perf_counter() - t0
t0 = time.perf_counter()
compiled = lowered.compile()
t_compile = time.perf_counter() - t0
out = {"trace_s": round(t_trace, 3), "compile_s": round(t_compile, 3),
       "total_s": round(t_trace + t_compile, 3)}
if spec.get("memory"):
    try:
        ma = compiled.memory_analysis()
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
    except Exception as e:
        out["memory_analysis_error"] = str(e)[:200]
    stats = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        pass
    if "peak_bytes_in_use" in stats:
        out["device_peak_bytes"] = int(stats["peak_bytes_in_use"])
print(json.dumps(out))
"""


def _measure(spec: dict, timeout: float,
             allow_timeout: bool = False) -> dict:
    env = {**os.environ, **_CHILD_ENV_BASE}
    with tempfile.TemporaryDirectory() as d:
        env["XDG_CACHE_HOME"] = d
        try:
            out = subprocess.run(
                [sys.executable, "-c", _MEASURE_SRC,
                 json.dumps(spec)],
                capture_output=True, text=True, timeout=timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            if not allow_timeout:
                raise
            # the measurement IS the finding: compile exceeded the
            # box — report the box as a lower bound
            return {"total_s": round(float(timeout), 1),
                    "timed_out": True}
    if out.returncode != 0:
        raise RuntimeError(
            f"transform measurement failed for {spec}: "
            f"{out.stderr[-1500:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def _base_spec(**over) -> dict:
    spec = {
        "vocab": 13, "d_model": 32, "heads": 2, "seq": 16,
        "batch": 4, "depth": 4, "scan": False, "remat": "none",
    }
    spec.update(over)
    return spec


def compile_vs_depth(depths=(4, 16, 64), budget_s=None) -> dict:
    """Trace+compile wall clock per (depth, scan) — one cold child
    process each. The deepest scan-OFF measurement gets the lion's
    share of the budget (the O(depth) HLO is exactly what compiles
    slowly); if even that box overruns, the box itself is reported
    as a LOWER BOUND and the speedup becomes '>='."""
    budget = float(budget_s or 540.0)
    t0 = time.monotonic()

    def left():
        return max(40.0, budget - (time.monotonic() - t0))

    per = {}
    shallow_box = max(40.0, budget / 10.0)
    for depth in depths:
        deepest = depth == max(depths)
        row = {}
        # scan-on first: it is cheap at every depth and must land
        row["scan_on"] = _measure(
            _base_spec(depth=depth, scan=True), shallow_box
        )
        off_box = (
            min(left() - shallow_box / 2, 300.0)
            if deepest else shallow_box
        )
        row["scan_off"] = _measure(
            _base_spec(depth=depth, scan=False), off_box,
            allow_timeout=True,
        )
        row["speedup"] = round(
            row["scan_off"]["total_s"]
            / max(row["scan_on"]["total_s"], 1e-9), 2,
        )
        if row["scan_off"].get("timed_out"):
            row["speedup_is_lower_bound"] = True
        per[f"depth_{depth}"] = row
    deepest_key = f"depth_{max(depths)}"
    return {
        "model": "transformer_lm (homogeneous TransformerBlock stack)",
        "measured": "trace+compile wall of the jitted train step, "
                    "cold process, compile cache disabled",
        "depths": list(depths),
        **per,
        "speedup_depth_max": per[deepest_key]["speedup"],
        "gate": "speedup >= 2 at the deepest stack",
    }


def remat_memory(base_batch=16, budget_s=None) -> dict:
    """Activation working set (XLA temp bytes) and max-fitting batch
    at a fixed activation budget, remat off vs full."""
    timeout = 280.0
    if budget_s:
        timeout = max(40.0, budget_s / 12.0)
    spec = dict(d_model=64, seq=32, depth=4, memory=True)
    off = _measure(
        _base_spec(batch=base_batch, **spec), timeout
    )
    on = _measure(
        _base_spec(batch=base_batch, remat="full", **spec), timeout
    )
    out = {
        "model": "transformer_lm d_model=64 depth=4 seq=32",
        "measured": "XLA memory_analysis temp bytes (activation "
                    "working set) of the train step; device peak "
                    "bytes when the backend reports memory_stats()",
        "base_batch": base_batch,
        "remat_off": off,
        "remat_on": on,
    }
    if "temp_bytes" in off and "temp_bytes" in on:
        out["temp_bytes_ratio"] = round(
            off["temp_bytes"] / max(on["temp_bytes"], 1), 2
        )
        # max batch under the remat-off working set at base_batch:
        # double until it no longer fits, for each policy
        budget = off["temp_bytes"]

        def max_batch(remat):
            fit = base_batch
            b = base_batch * 2
            while b <= base_batch * 16:
                m = _measure(
                    _base_spec(batch=b, remat=remat, **spec), timeout
                )
                if m.get("temp_bytes", budget + 1) > budget:
                    break
                fit = b
                b *= 2
            return fit

        out["max_batch_off"] = base_batch  # the budget definition
        out["max_batch_on"] = max_batch("full")
        out["batch_ratio"] = round(
            out["max_batch_on"] / out["max_batch_off"], 2
        )
    out["gate"] = ("batch_ratio >= 1.5 (>= 1.5x larger batch at the "
                   "remat-off activation budget)")
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--section", default="both",
                    choices=("compile_vs_depth", "remat_memory",
                             "both"))
    ap.add_argument("--budget-s", type=float, default=None)
    args = ap.parse_args()
    out = {}
    if args.section in ("compile_vs_depth", "both"):
        out["compile_vs_depth"] = compile_vs_depth(
            budget_s=args.budget_s
        )
    if args.section in ("remat_memory", "both"):
        out["remat_memory"] = remat_memory(budget_s=args.budget_s)
    print(json.dumps(out if args.section == "both"
                     else out[args.section]))


if __name__ == "__main__":
    main()
