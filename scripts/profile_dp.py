"""Attribute the dp_scaling overhead (BENCH_r03: weak 0.771) on the
8-device virtual CPU mesh.

Decomposition measured here (all shard_map, batch_stats="local"
semantics, the step DistributedTrainer picks for the dp bench):

  A. full train step          n=1 b/8   and   n=8 b
  B. collectives alone: jitted pmean over the grads+state-shaped tree
  C. updater alone: replicated update on n=1 vs n=8 (the serialized
     host pays the 8x duplication that real chips run in parallel)

Residual = A(8) - 8*A(1,b/8)/8 ... i.e. whatever partitioning adds
beyond B and C's duplication. Prints one JSON line.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, time
import numpy as np
from __graft_entry__ import _ensure_devices
_ensure_devices(8)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.compat import shard_map_compat
shard_map = shard_map_compat()
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import build_mesh
from deeplearning4j_tpu.zoo import resnet50

n = int(os.environ["DP_DEVICES"])
b = int(os.environ["DP_BATCH"])
steps = int(os.environ.get("DP_STEPS", "3"))
what = os.environ["DP_WHAT"]  # step | pmean | update

conf = resnet50(height=32, width=32, channels=3, n_classes=10,
                cifar_stem=True, learning_rate=0.01)
net = ComputationGraph(conf).init()
mesh = build_mesh(data=n, model=1, devices=jax.devices()[:n])
updater = net.updater_def
rep_sh = NamedSharding(mesh, P())
dp_sh = NamedSharding(mesh, P("data"))

params = jax.device_put(net.params, rep_sh)
upd = jax.tree_util.tree_map(lambda a: jax.device_put(a, rep_sh),
                             net.updater_state)
state = jax.tree_util.tree_map(lambda a: jax.device_put(a, rep_sh),
                               net.state)
rng = jax.random.PRNGKey(0)
lrs = {k: jnp.asarray(v, jnp.float32)
       for k, v in updater.scheduled_lrs(0).items()}
t = jnp.asarray(1.0, jnp.float32)
rs = np.random.RandomState(0)
x = jax.device_put(rs.rand(b, 3, 32, 32).astype(np.float32), dp_sh)
y = jax.device_put(
    np.eye(10, dtype=np.float32)[rs.randint(0, 10, b)], dp_sh)

rep = P(); dp = P("data")

def time_fn(fn, args, donate=None):
    out = fn(*args)          # compile + 1 run
    jax.block_until_ready(out)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times)

if what == "step":
    def step(params, upd, state, x, y, lrs, t, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = jax.lax.pmean(grads, "data")
        score = jax.lax.pmean(score, "data")
        new_params, new_upd = updater.update(grads, upd, params, lrs, t)
        new_state = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), new_state)
        return new_params, new_upd, new_state, score
    f = jax.jit(shard_map(step, mesh=mesh,
                          in_specs=(rep, rep, rep, dp, dp, rep, rep, rep),
                          out_specs=(rep, rep, rep, rep),
                          check_rep=False))
    sec = time_fn(f, (params, upd, state, x, y, lrs, t, rng))
elif what == "fwdbwd":
    def step(params, state, x, y, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        def loss_fn(p):
            s, ns = net._score_pure(p, state, [x], [y], None, rng,
                                    train=True, fmasks=None)
            return s, ns
        (score, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return grads, new_state, score
    f = jax.jit(shard_map(step, mesh=mesh,
                          in_specs=(rep, rep, dp, dp, rep),
                          out_specs=(rep, rep, rep),
                          check_rep=False))
    sec = time_fn(f, (params, state, x, y, rng))
elif what == "pmean":
    def red(g, s):
        g = jax.lax.pmean(g, "data")
        s = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), s)
        return g, s
    f = jax.jit(shard_map(red, mesh=mesh, in_specs=(rep, rep),
                          out_specs=(rep, rep), check_rep=False))
    sec = time_fn(f, (params, state))
elif what == "update":
    def up(g, upd, params, lrs, t):
        return updater.update(g, upd, params, lrs, t)
    f = jax.jit(shard_map(up, mesh=mesh,
                          in_specs=(rep, rep, rep, rep, rep),
                          out_specs=(rep, rep), check_rep=False))
    sec = time_fn(f, (params, upd, params, lrs, t))
print(json.dumps({"what": what, "devices": n, "batch": b,
                  "sec": sec}))
"""


def run(what, n, b, steps=3):
    env = dict(os.environ)
    env.update({
        "JAX_COMPILATION_CACHE_DIR": "/tmp/deeplearning4j_tpu_jax_cache",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"
                      ).strip(),
        "DP_DEVICES": str(n), "DP_BATCH": str(b),
        "DP_STEPS": str(steps), "DP_WHAT": what,
        "PYTHONPATH": REPO,
    })
    t0 = time.time()
    out = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=3600)
    wall = time.time() - t0
    if out.returncode != 0:
        return {"what": what, "devices": n, "batch": b,
                "error": out.stderr[-1500:], "wall": round(wall, 1)}
    r = json.loads(out.stdout.strip().splitlines()[-1])
    r["wall"] = round(wall, 1)
    return r


def main():
    results = []
    for what, n, b in [
        ("step", 1, 8), ("step", 8, 64),
        ("fwdbwd", 1, 8), ("fwdbwd", 8, 64),
        ("pmean", 8, 64),
        ("update", 1, 8), ("update", 8, 64),
    ]:
        r = run(what, n, b)
        results.append(r)
        print(json.dumps(r), flush=True)
    print(json.dumps({"all": results}))


if __name__ == "__main__":
    main()
