#!/usr/bin/env python
"""Training input-pipeline smoke benchmark (CPU, seeded, seconds).

A/Bs the synchronous fit loop against the pipelined one
(``PrefetchIterator`` + ``AsyncDispatchWindow``) on the SAME seeded
data through ``DistributedTrainer``, with an iterator whose
``next()`` carries nontrivial host-side batch cost (a simulated
shard-fetch I/O wait plus optional numpy decode work — what a real
input pipeline pays per batch). Prints ONE JSON line::

    {"steps_per_window": ..., "host_cost_ms_per_batch": ...,
     "sync":      {"steps_per_s": ..., "p50_gap_ms": ...,
                   "p99_gap_ms": ..., "input_stall_fraction": ...},
     "pipelined": {"steps_per_s": ..., "p50_gap_ms": ...,
                   "p99_gap_ms": ..., "input_stall_fraction": ...},
     "speedup": ..., "trajectory_match": true}

The acceptance gates this makes falsifiable on CPU:

- ``speedup`` > 1: prefetching the materialize+cast+device_put off
  the critical path beats paying it inline when the input has real
  host cost (on this suite's 1-core CI box only the I/O half of the
  input cost can physically overlap the CPU backend's compute, so
  the speedup there is bounded by the I/O share; a real TPU host
  overlaps the CPU decode work too);
- ``trajectory_match``: params + updater state after N steps are
  BITWISE identical between the two modes (the pipeline must never
  change what is trained, only when the host waits);
- ``input_stall_fraction`` is the device-idle-on-input proxy (the
  fraction of wall time the consumer spent waiting for a batch — on
  the CPU backend host and device share the clock): sync mode pays
  the full input cost on the critical path; pipelined mode's
  residual wait says whether the run is host-bound (high: the
  source can't keep up even prefetched) or device-bound (near 0).

Optional A/B riders on the same seeded batches: ``--remat`` (policy
off vs on), ``--zero`` (replicated vs ZeRO-sharded optimizer state —
steps/sec, per-device updater bytes, bitwise trajectory),
``--grad-accum K`` (accum=1 vs K in-jit microbatches — steps/sec +
trajectory vs the single-big-batch run), ``--megastep K`` (per-step
fit vs K steps fused into ONE dispatch behind the chunk-mode
double-buffered prefetch — steps/sec, flight-recorder
dispatches/step <= 1.5/K, residual input-stall < 5 %, bitwise
trajectory), and ``--defense`` (data-
plane defense off vs fully on — clean-path overhead gated <= 5 %,
zero quarantines on a clean stream, and the no-trip bitwise
contracts; a gate failure exits nonzero).

Windows are interleaved best-of-N like ``scripts/bench_serving.py``
(host noise only ever slows a run). Runnable standalone
(``python scripts/bench_training.py``) or from ``bench.py``'s
``input_pipeline`` / ``zero_sharding`` sections under
``BENCH_BUDGET_S``.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _make_net(seed=0, n_in=64, hidden=256, n_out=8, updater=None):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    b = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.1)
    )
    if updater:
        b = b.updater(updater)
    b = (
        b.list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh"))
        .layer(OutputLayer(n_out=n_out))
    )
    return MultiLayerNetwork(b.build()).init()


class CostlyIterator:
    """Seeded batches behind a deterministic host-side per-batch cost:
    an I/O wait (``io_ms`` sleep — the stand-in for a shard fetch /
    disk read, the cost ``CloudDataSetIterator`` pays per batch) plus
    optional CPU work (``cost_loops`` matmul+tanh passes — decode/
    augment). The I/O half is what a prefetch thread can always
    overlap, even on a 1-core host where CPU-bound producer work and
    the CPU backend's "device" compute necessarily serialize. Tracks
    time spent inside ``next()`` so the synchronous mode's input
    stall is measurable."""

    def __init__(self, batches, io_ms: float = 4.0,
                 cost_loops: int = 0):
        self._batches = batches
        self.io_ms = io_ms
        self.cost_loops = cost_loops
        self._scratch = np.random.RandomState(99).rand(
            192, 192
        ).astype(np.float32)
        self._pos = 0
        self.input_seconds = 0.0

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if self._pos >= len(self._batches):
            raise StopIteration
        t0 = time.perf_counter()
        if self.io_ms > 0:
            time.sleep(self.io_ms / 1000.0)
        a = self._scratch
        for _ in range(self.cost_loops):
            a = np.tanh(a @ self._scratch)
        ds = self._batches[self._pos]
        self._pos += 1
        self.input_seconds += time.perf_counter() - t0
        return ds

    next = __next__

    def has_next(self):
        return self._pos < len(self._batches)

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batches[0].num_examples()

    def total_examples(self):
        return sum(b.num_examples() for b in self._batches)


class _GapListener:
    """Per-step dispatch timestamps -> step-gap percentiles."""

    supports_batched_iterations = False

    def __init__(self):
        self.stamps = []

    def iteration_done(self, model, iteration):
        self.stamps.append(time.perf_counter())

    def gaps_ms(self):
        return [
            (b - a) * 1000.0
            for a, b in zip(self.stamps, self.stamps[1:])
        ]


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def _params_flat(net):
    return np.concatenate([
        np.asarray(a).ravel()
        for ln in sorted(net.params)
        for _, a in sorted(net.params[ln].items())
    ])


def _remat_ab(batches, policy, windows, seed) -> dict:
    """Activation-remat A/B on the same seeded batches: steps/sec and
    bitwise trajectory with the policy off vs on (the remat transform
    may only trade compute for memory, never change what is
    trained)."""
    import jax

    def fit_all(net):
        for ds in batches:
            net.fit_minibatch(ds)
        jax.block_until_ready(net.params)

    nets = {}
    for key, pol in (("off", "none"), ("on", policy)):
        nets[key] = _make_net(seed=seed).set_transforms(remat=pol)
        nets[key].fit_minibatch(batches[0])  # compile outside windows
    out = {"policy": policy}
    best = {"off": float("inf"), "on": float("inf")}
    for _ in range(windows):
        for key in ("off", "on"):
            t0 = time.perf_counter()
            fit_all(nets[key])
            best[key] = min(best[key], time.perf_counter() - t0)
    out["steps_per_s_off"] = round(len(batches) / best["off"], 2)
    out["steps_per_s_on"] = round(len(batches) / best["on"], 2)
    fresh = {
        key: _make_net(seed=seed).set_transforms(remat=pol)
        for key, pol in (("off", "none"), ("on", policy))
    }
    for net in fresh.values():
        fit_all(net)
    out["trajectory_match"] = bool(np.array_equal(
        _params_flat(fresh["off"]), _params_flat(fresh["on"])
    ))
    return out


def _upd_bytes_per_device(model):
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(model.updater_state):
        if hasattr(leaf, "addressable_shards"):
            total += leaf.addressable_shards[0].data.nbytes
        else:
            total += np.asarray(leaf).nbytes
    return total


def _zero_ab(batches, windows, seed) -> dict:
    """ZeRO optimizer-state sharding A/B on the same seeded batches
    through ``DistributedTrainer``: steps/sec replicated vs sharded,
    per-device updater bytes for both (the ~1/N claim), and the
    bitwise trajectory check (sharding may only move bytes, never
    change what is trained)."""
    import jax

    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, build_mesh,
    )

    def mk(zero):
        net = _make_net(seed=seed, updater="ADAM")
        return DistributedTrainer(net, mesh=build_mesh(), zero=zero)

    def fit_all(tr):
        for ds in batches:
            tr.fit_minibatch(ds)
        jax.block_until_ready(tr.model.params)

    trainers = {"replicated": mk(False), "zero": mk(True)}
    for tr in trainers.values():
        tr.fit_minibatch(batches[0])  # compile outside windows
        jax.block_until_ready(tr.model.params)
    out = {"data_shards": int(trainers["zero"].mesh.shape["data"])}
    best = {k: float("inf") for k in trainers}
    for _ in range(windows):
        for key, tr in trainers.items():
            t0 = time.perf_counter()
            fit_all(tr)
            best[key] = min(best[key], time.perf_counter() - t0)
    for key, tr in trainers.items():
        out[f"steps_per_s_{key}"] = round(len(batches) / best[key], 2)
        out[f"updater_bytes_per_device_{key}"] = (
            _upd_bytes_per_device(tr.model)
        )
    out["updater_bytes_ratio"] = round(
        out["updater_bytes_per_device_zero"]
        / max(out["updater_bytes_per_device_replicated"], 1), 4,
    )
    fresh = {key: mk(key == "zero") for key in trainers}
    for tr in fresh.values():
        fit_all(tr)
    out["trajectory_match"] = bool(np.array_equal(
        _params_flat(fresh["replicated"].model),
        _params_flat(fresh["zero"].model),
    ))
    return out


def _grad_accum_ab(batches, k, windows, seed) -> dict:
    """In-jit gradient-accumulation A/B through the GSPMD trainer
    step: steps/sec with accum=1 vs accum=k on the same batches, and
    the trajectory check vs the single-big-batch run (tight
    tolerance — the batch-dim matmul regroups its reduction under the
    microbatch scan; the BITWISE contract is vs the unfused
    per-microbatch reference, pinned in tests/test_zero.py)."""
    import jax

    from deeplearning4j_tpu.nn import core
    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, build_mesh,
    )

    def mk(kk):
        net = _make_net(seed=seed, updater="ADAM")
        tr = DistributedTrainer(net, mesh=build_mesh())
        if kk > 1:
            core.set_grad_accum(net, kk)
        return tr

    def fit_all(tr):
        for ds in batches:
            tr.fit_minibatch(ds)
        jax.block_until_ready(tr.model.params)

    trainers = {"accum1": mk(1), f"accum{k}": mk(k)}
    for tr in trainers.values():
        tr.fit_minibatch(batches[0])
        jax.block_until_ready(tr.model.params)
    out = {"microbatches": k}
    best = {key: float("inf") for key in trainers}
    for _ in range(windows):
        for key, tr in trainers.items():
            t0 = time.perf_counter()
            fit_all(tr)
            best[key] = min(best[key], time.perf_counter() - t0)
    for key in trainers:
        out[f"steps_per_s_{key}"] = round(len(batches) / best[key], 2)
    fresh = {key: mk(kk) for key, kk in (("accum1", 1),
                                         (f"accum{k}", k))}
    for tr in fresh.values():
        fit_all(tr)
    a = _params_flat(fresh["accum1"].model)
    b = _params_flat(fresh[f"accum{k}"].model)
    # float-ulp regrouping noise compounds through ADAM's moment
    # normalization over the window, so the gate is loose; the raw
    # max divergence is reported for trend tracking
    out["trajectory_close"] = bool(np.allclose(a, b, rtol=5e-3,
                                               atol=1e-5))
    out["trajectory_max_abs_diff"] = float(np.max(np.abs(a - b)))
    return out


def _upd_flat(net):
    import jax

    leaves = [
        np.asarray(leaf).ravel()
        for leaf in jax.tree_util.tree_leaves(net.updater_state)
    ]
    return np.concatenate(leaves) if leaves else np.zeros(0)


def _megastep_ab(batches, k, windows, seed, io_ms,
                 queue_depth) -> dict:
    """Megastep A/B through the GSPMD trainer on the SAME seeded
    batches behind an I/O-bound iterator: the per-step fit (plain
    prefetch) vs ``megastep=K`` (chunk-mode prefetch: the worker
    stacks + places the NEXT K-batch block while the device runs the
    current fused dispatch). Reports steps/sec, flight-recorder
    dispatches/step (records over optimizer steps — ~1 per step vs
    ~1/K under megastep, gated at <= 1.5/K), the STEADY-STATE
    input-stall fraction of the double-buffered feed (per-take waits
    excluding the first take — the one-time pipeline fill, reported
    separately as ``pipeline_fill_ms``, amortizes over a real epoch
    but dominates a seconds-long bench window; gated < 5 %), and the
    BITWISE trajectory (params + updater state) vs the per-step
    reference."""
    import jax

    from deeplearning4j_tpu.datasets.api import ListDataSetIterator
    from deeplearning4j_tpu.datasets.prefetch import PrefetchIterator
    from deeplearning4j_tpu.nn import core
    from deeplearning4j_tpu.observability import profiler as prof_mod
    from deeplearning4j_tpu.observability.flightrec import (
        FlightRecorder,
    )
    from deeplearning4j_tpu.observability.metrics import MetricsRegistry
    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, build_mesh,
    )

    steps = len(batches)

    def mk(kk):
        net = _make_net(seed=seed)
        tr = DistributedTrainer(net, mesh=build_mesh())
        if kk > 1:
            core.set_transforms(net, megastep=kk)
        return tr

    trainers = {"per_step": mk(1), "megastep": mk(k)}
    # compile both programs outside the windows (per-step for the
    # tail fallback too). TWO chunks, not one: the first chunk runs
    # with a host-placed it0 scalar, steady-state chunks reuse the
    # committed device counter from note_it0 — two jit
    # specializations, both warmed here
    for tr in trainers.values():
        tr.fit_minibatch(batches[0])
    trainers["megastep"].fit(ListDataSetIterator(batches[:2 * k]),
                             epochs=1)
    for tr in trainers.values():
        jax.block_until_ready(tr.model.params)

    def window(key):
        tr = trainers[key]
        rec = FlightRecorder(capacity=8192)
        prof = prof_mod.StepProfiler(
            registry=MetricsRegistry(enabled=False), recorder=rec,
        )
        it = CostlyIterator(batches, io_ms, 0)
        reg = MetricsRegistry(enabled=False)
        if key == "megastep":
            pf = PrefetchIterator(
                it, queue_depth=queue_depth, registry=reg,
                megastep=k, chunk_placement=tr.place_chunk,
            )
        else:
            pf = PrefetchIterator(
                it, queue_depth=queue_depth, registry=reg,
                placement=tr.place_minibatch,
            )
        # per-take consumer waits: waits[0] is the pipeline fill (the
        # first take always waits for the whole first item to
        # assemble), the rest are the steady-state stall
        waits = []
        orig_advance = pf._advance

        def timed_advance():
            t = time.perf_counter()
            orig_advance()
            waits.append(time.perf_counter() - t)

        pf._advance = timed_advance
        prev = prof_mod.set_active_profiler(prof)
        t0 = time.perf_counter()
        try:
            tr.fit(pf, epochs=1)
            jax.block_until_ready(tr.model.params)
        finally:
            prof_mod.set_active_profiler(prev)
            pf.shutdown()
        wall = time.perf_counter() - t0
        n_rec = sum(1 for r in rec.tail() if r.get("type") == "step")
        fill = waits[0] if waits else 0.0
        steady = sum(waits[1:])
        return wall, n_rec, steady / wall, fill

    # element-wise best across interleaved windows: scheduler noise
    # (the worker losing the core to the consumer on a small CI box)
    # only ever INFLATES wall and stall, so the minimum of each is
    # the honest capability number — same principle as the best-of-N
    # wall windows elsewhere in this file
    best = {key: None for key in trainers}
    for _ in range(windows):
        for key in trainers:
            res = window(key)
            prev = best[key]
            best[key] = res if prev is None else tuple(
                min(a, b) for a, b in zip(prev, res)
            )

    out = {"k": k, "io_ms": io_ms}
    for key in trainers:
        wall, n_rec, stall, fill = best[key]
        out[f"steps_per_s_{key}"] = round(steps / wall, 2)
        out[f"dispatches_per_step_{key}"] = round(n_rec / steps, 4)
        out[f"input_stall_fraction_{key}"] = round(stall, 4)
        out[f"pipeline_fill_ms_{key}"] = round(fill * 1000.0, 3)
    out["speedup"] = round(
        out["steps_per_s_megastep"] / out["steps_per_s_per_step"], 3,
    )
    out["dispatch_ratio_ok"] = bool(
        out["dispatches_per_step_megastep"] <= 1.5 / k
    )
    out["input_stall_ok"] = bool(
        out["input_stall_fraction_megastep"] < 0.05
    )

    # -- bitwise trajectory (fresh models, outside the windows) ---------
    fresh = {"per_step": mk(1), "megastep": mk(k)}
    for ds in batches:
        fresh["per_step"].fit_minibatch(ds)
    jax.block_until_ready(fresh["per_step"].model.params)
    fresh["megastep"].fit(ListDataSetIterator(batches), epochs=1)
    jax.block_until_ready(fresh["megastep"].model.params)
    out["trajectory_match"] = bool(
        np.array_equal(_params_flat(fresh["per_step"].model),
                       _params_flat(fresh["megastep"].model))
        and np.array_equal(_upd_flat(fresh["per_step"].model),
                           _upd_flat(fresh["megastep"].model))
    )
    out["megastep_ok"] = bool(
        out["dispatch_ratio_ok"] and out["input_stall_ok"]
        and out["trajectory_match"]
    )
    return out


def _defense_ab(windows, seed) -> dict:
    """Data-plane defense A/B on seeded CLEAN batches: steps/sec with
    the defense off vs fully on (``BatchValidator`` screening every
    batch + the statistical anomaly guard's in-jit EWMA), gating the
    clean-path overhead at <= 5 % and the no-trip exactness contracts.

    The A/B runs its own workload (64 -> 1024 -> 8 at batch 1024, not
    the harness's toy step): the defense cost is a fixed per-step host
    charge (the guard's ok-flag consult + the validator's numpy pass,
    ~0.3 ms total), so the gate is only meaningful against a step big
    enough to represent real training — against a ~1.7 ms toy step the
    same fixed charge reads as 15 %+.

    Exactness contracts:

    - ``quarantined_on_clean`` must be 0 (the validator never
      rejects a clean batch);
    - ``validator_bitwise``: validator on vs off is BITWISE identical
      (host-side filtering, same compiled step);
    - ``statguard_bitwise``: stats armed vs the plain NaN guard is
      BITWISE identical (the EWMA fold rides alongside the update
      math without perturbing it). Together these bound the full
      off-vs-on delta to XLA program identity — any guard changes the
      compiled program, a pre-existing last-ulp boundary pinned by
      the PR-11 guard tests.
    """
    import tempfile

    import jax

    from deeplearning4j_tpu.datasets.api import (
        DataSet, ListDataSetIterator,
    )
    from deeplearning4j_tpu.datasets.validate import (
        BatchSchema, BatchValidator, QuarantineStore,
        ValidatingIterator,
    )
    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, build_mesh,
    )
    from deeplearning4j_tpu.resilience import (
        DivergenceGuard, StatGuardConfig,
    )

    rng = np.random.RandomState(seed)
    batches = [
        DataSet(
            features=rng.randn(1024, 64).astype(np.float32),
            labels=np.eye(8, dtype=np.float32)[
                rng.randint(0, 8, 1024)
            ],
        )
        for _ in range(12)
    ]
    schema = BatchSchema(feature_dim=64, label_dim=8,
                         label_range=(0.0, 1.0), max_abs=1e6)

    def mk(guard):
        net = _make_net(seed=seed, hidden=1024, updater="ADAM")
        return DistributedTrainer(net, mesh=build_mesh(),
                                  divergence_guard=guard)

    def fit_all(tr, validator=None, store=None):
        tr.fit(ListDataSetIterator(batches), epochs=1,
               validator=validator, quarantine=store)
        jax.block_until_ready(tr.model.params)

    qdir = tempfile.mkdtemp(prefix="bench-defense-q-")
    arms = {
        "off": (mk(None), None, None),
        "on": (mk(DivergenceGuard(stats=StatGuardConfig())),
               BatchValidator(schema), QuarantineStore(qdir)),
    }
    for tr, _, _ in arms.values():  # compile + settle
        tr.fit_minibatch(batches[0])
        jax.block_until_ready(tr.model.params)
    best = {key: float("inf") for key in arms}
    for _ in range(windows):
        for key, (tr, v, s) in arms.items():
            t0 = time.perf_counter()
            fit_all(tr, v, s)
            best[key] = min(best[key], time.perf_counter() - t0)
    out = {
        "steps_per_s_off": round(len(batches) / best["off"], 2),
        "steps_per_s_on": round(len(batches) / best["on"], 2),
    }
    out["overhead_fraction"] = round(
        max(0.0, best["on"] / best["off"] - 1.0), 4
    )
    out["overhead_ok"] = out["overhead_fraction"] <= 0.05

    # -- exactness lemmas (fresh models, outside the timed windows) -----
    vit = ValidatingIterator(ListDataSetIterator(batches),
                             BatchValidator(schema))
    plain, defended = mk(None), mk(None)
    fit_all(plain)
    defended.fit(vit, epochs=1, validator=vit.validator)
    jax.block_until_ready(defended.model.params)
    out["quarantined_on_clean"] = len(vit.skipped_offsets)
    out["validator_bitwise"] = bool(np.array_equal(
        _params_flat(plain.model), _params_flat(defended.model)
    ))
    nan_guard = mk(DivergenceGuard())
    stat_guard = mk(DivergenceGuard(stats=StatGuardConfig()))
    fit_all(nan_guard)
    fit_all(stat_guard)
    out["statguard_bitwise"] = bool(np.array_equal(
        _params_flat(nan_guard.model), _params_flat(stat_guard.model)
    ))
    out["defense_ok"] = bool(
        out["overhead_ok"]
        and out["quarantined_on_clean"] == 0
        and out["validator_bitwise"]
        and out["statguard_bitwise"]
    )
    return out


def run(steps=40, batch=256, io_ms=4.0, cost_loops=0,
        queue_depth=3, max_in_flight=3, windows=3,
        seed=0, remat="none", zero=False, grad_accum=0,
        defense=False, megastep=0, megastep_io_ms=0.5) -> dict:
    import jax

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.prefetch import PrefetchIterator
    from deeplearning4j_tpu.observability.metrics import MetricsRegistry
    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, build_mesh,
    )

    rng = np.random.RandomState(seed)
    batches = [
        DataSet(
            features=rng.randn(batch, 64).astype(np.float32),
            labels=np.eye(8, dtype=np.float32)[
                rng.randint(0, 8, batch)
            ],
        )
        for _ in range(steps)
    ]

    def make_trainer(mif):
        net = _make_net(seed=seed)
        return net, DistributedTrainer(
            net, mesh=build_mesh(), max_in_flight=mif,
        )

    # per-batch host cost, measured once (untimed elsewhere)
    probe = CostlyIterator(batches[:4], io_ms, cost_loops)
    list(probe)
    host_cost_ms = probe.input_seconds / 4 * 1000.0

    # -- trajectory equivalence (outside the timed windows) -------------
    net_a, tr_a = make_trainer(1)
    for ds in batches:
        tr_a.fit_minibatch(ds)
    net_b, tr_b = make_trainer(max_in_flight)
    tr_b.fit(
        PrefetchIterator(
            CostlyIterator(batches, 0.0, 0), queue_depth=queue_depth,
            placement=tr_b.place_minibatch,
            registry=MetricsRegistry(enabled=False),
        ),
        epochs=1,
    )
    trajectory_match = bool(np.array_equal(
        _params_flat(net_a), _params_flat(net_b)
    ))

    # -- timed A/B -------------------------------------------------------
    net_s, tr_sync = make_trainer(1)
    net_p, tr_pipe = make_trainer(max_in_flight)
    # compile + settle both before any window
    for tr in (tr_sync, tr_pipe):
        tr.fit_minibatch(batches[0])
        jax.block_until_ready(tr.model.params)

    def sync_window():
        it = CostlyIterator(batches, io_ms, cost_loops)
        gaps = _GapListener()
        tr_sync.model.listeners.append(gaps)
        t0 = time.perf_counter()
        for ds in it:  # the pre-pipeline loop: input cost inline
            tr_sync.fit_minibatch(ds)
        jax.block_until_ready(tr_sync.model.params)
        wall = time.perf_counter() - t0
        tr_sync.model.listeners.remove(gaps)
        return wall, gaps.gaps_ms(), it.input_seconds / wall

    def pipe_window():
        reg = MetricsRegistry()
        it = CostlyIterator(batches, io_ms, cost_loops)
        pf = PrefetchIterator(
            it, queue_depth=queue_depth,
            placement=tr_pipe.place_minibatch, registry=reg,
        )
        gaps = _GapListener()
        tr_pipe.model.listeners.append(gaps)
        t0 = time.perf_counter()
        try:
            tr_pipe.fit(pf, epochs=1)
        finally:
            pf.shutdown()
            tr_pipe.model.listeners.remove(gaps)
        wall = time.perf_counter() - t0
        # residual consumer stall on the critical path: total
        # prefetch wait (ms) over the wall window
        wait_ms = reg.get("training_prefetch_wait_ms")._default().total
        return wall, gaps.gaps_ms(), (wait_ms / 1000.0) / wall

    best = {"sync": None, "pipelined": None}
    for _ in range(windows):
        for name, fn in (("sync", sync_window),
                         ("pipelined", pipe_window)):
            wall, gaps, stall = fn()
            if best[name] is None or wall < best[name][0]:
                best[name] = (wall, gaps, stall)

    out = {
        "steps_per_window": steps,
        "batch": batch,
        "windows": windows,
        "queue_depth": queue_depth,
        "max_in_flight": max_in_flight,
        "host_cost_ms_per_batch": round(host_cost_ms, 3),
        "trajectory_match": trajectory_match,
    }
    for name in ("sync", "pipelined"):
        wall, gaps, stall = best[name]
        g = sorted(gaps)
        out[name] = {
            "steps_per_s": round(steps / wall, 2),
            "p50_gap_ms": round(_pct(g, 0.50) or 0.0, 3),
            "p99_gap_ms": round(_pct(g, 0.99) or 0.0, 3),
            "input_stall_fraction": round(stall, 4),
        }
    out["speedup"] = round(
        out["pipelined"]["steps_per_s"] / out["sync"]["steps_per_s"], 3
    )
    if remat and remat != "none":
        out["remat"] = _remat_ab(batches, remat, windows, seed)
    if zero:
        out["zero_sharding"] = _zero_ab(batches, windows, seed)
    if grad_accum and grad_accum > 1:
        out["grad_accum"] = _grad_accum_ab(
            batches, grad_accum, windows, seed
        )
    if defense:
        out["defense"] = _defense_ab(windows, seed)
    if megastep and megastep > 1:
        out["megastep"] = _megastep_ab(
            batches, megastep, windows, seed, megastep_io_ms,
            queue_depth,
        )
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=40,
                    help="minibatches per measured window")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--io-ms", type=float, default=4.0,
                    help="simulated I/O wait per batch (shard fetch)")
    ap.add_argument("--cost-loops", type=int, default=0,
                    help="host CPU-work passes per batch (decode)")
    ap.add_argument("--queue-depth", type=int, default=3)
    ap.add_argument("--max-in-flight", type=int, default=3)
    ap.add_argument("--windows", type=int, default=3,
                    help="same-length windows per mode (best wins)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--remat", default="none",
                    choices=("none", "dots_saveable", "full"),
                    help="also A/B activation remat off vs this "
                         "policy (steps/sec + bitwise trajectory)")
    ap.add_argument("--zero", action="store_true",
                    help="also A/B ZeRO optimizer-state sharding "
                         "(steps/sec + per-device updater bytes + "
                         "bitwise trajectory)")
    ap.add_argument("--grad-accum", type=int, default=0,
                    metavar="K",
                    help="also A/B in-jit gradient accumulation "
                         "accum=1 vs accum=K (steps/sec + trajectory "
                         "vs the single-big-batch run)")
    ap.add_argument("--megastep", type=int, default=0,
                    metavar="K",
                    help="also A/B megastep epochs: per-step fit vs "
                         "K steps fused into one dispatch behind a "
                         "chunk-mode prefetch (steps/sec, recorder "
                         "dispatches/step <= 1.5/K, input-stall "
                         "< 5%%, bitwise trajectory) — exits nonzero "
                         "on a gate failure")
    ap.add_argument("--megastep-io-ms", type=float, default=0.5,
                    help="simulated I/O wait per batch for the "
                         "megastep A/B's I/O-bound iterator")
    ap.add_argument("--defense", action="store_true",
                    help="also A/B the data-plane defense off vs on "
                         "(validator + statistical guard): gates "
                         "clean-path overhead <= 5%% and the no-trip "
                         "bitwise contracts — exits nonzero on a "
                         "gate failure")
    args = ap.parse_args()
    doc = run(
        steps=args.steps, batch=args.batch, io_ms=args.io_ms,
        cost_loops=args.cost_loops, queue_depth=args.queue_depth,
        max_in_flight=args.max_in_flight, windows=args.windows,
        seed=args.seed, remat=args.remat, zero=args.zero,
        grad_accum=args.grad_accum, defense=args.defense,
        megastep=args.megastep, megastep_io_ms=args.megastep_io_ms,
    )
    print(json.dumps(doc))
    if args.defense and not doc["defense"]["defense_ok"]:
        sys.exit(1)
    if args.megastep and not doc["megastep"]["megastep_ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
