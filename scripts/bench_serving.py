#!/usr/bin/env python
"""Serving micro-batch smoke benchmark (CPU, seeded, few seconds).

Drives ``ModelServer.submit()`` directly — the serving hot path
(admission -> queue -> drain -> predict -> response) minus socket
I/O, so the number isolates what micro-batching changes rather than
stdlib HTTP overhead — with a seeded synthetic closed-loop load at
fixed concurrency, once in solo mode (``micro_batch=False``, the
PR-2 one-predict-per-request loop) and once micro-batched. Prints
ONE JSON line::

    {"concurrency": 32,
     "solo":    {"req_per_s": ..., "p50_ms": ..., "p99_ms": ...,
                 "p50_ms_c1": ...},
     "batched": {"req_per_s": ..., "p50_ms": ..., "p99_ms": ...,
                 "p50_ms_c1": ..., "mean_batch_rows": ...,
                 "batches_total": ..., "xla_compiles_total": ...,
                 "post_warmup_compiles_total": ...},
     "speedup": ...}

The acceptance gates this makes falsifiable on CPU:

- ``speedup`` >= 4: one wide XLA dispatch per coalesced batch beats
  per-request dispatch at concurrency 32;
- ``post_warmup_compiles_total`` == 0: steady bucketed load compiles
  nothing after the eager warmup;
- ``p50_ms_c1`` (batched) is no worse than solo at concurrency 1:
  the adaptive batcher dispatches immediately when nothing else is
  in flight.

Runnable standalone (``python scripts/bench_serving.py``) or
imported by ``bench.py``'s serving section.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _make_net(seed=0, n_in=64, hidden=1024, n_out=8):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.1)
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh"))
        .layer(DenseLayer(n_in=hidden, n_out=hidden,
                          activation="tanh"))
        .layer(OutputLayer(n_out=n_out))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _drive(server, feats_pool, concurrency, per_thread):
    """Closed-loop load: each of ``concurrency`` threads submits
    ``per_thread`` requests back to back. Returns (req/s, p50 ms,
    p99 ms) over the whole run."""
    lat_per_thread = [[] for _ in range(concurrency)]
    errors = []

    def worker(tid):
        lats = lat_per_thread[tid]
        n = len(feats_pool)
        for i in range(per_thread):
            f = feats_pool[(tid * per_thread + i) % n]
            t0 = time.perf_counter()
            code, _, _ = server.submit(f)
            lats.append(time.perf_counter() - t0)
            if code != 200:
                errors.append(code)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(
            f"{len(errors)} non-200 responses (first: {errors[0]})"
        )
    lats = sorted(v for lst in lat_per_thread for v in lst)
    total = concurrency * per_thread

    def pct(q):
        return lats[min(len(lats) - 1, int(q * len(lats)))] * 1000.0

    return total / wall, pct(0.50), pct(0.99)


def run(concurrency=32, per_thread=40, seed=0,
        max_batch_size=64, batch_timeout_ms=8.0, windows=3) -> dict:
    from deeplearning4j_tpu.serving import ModelServer

    net = _make_net(seed=seed)
    rng = np.random.RandomState(seed)
    feats_pool = [rng.rand(1, 64).astype(np.float32)
                  for _ in range(256)]
    out = {"concurrency": concurrency,
           "requests_per_window": concurrency * per_thread,
           "windows": windows}

    kw = dict(workers=4, queue_depth=max(concurrency * 2, 64))
    solo = ModelServer(net, micro_batch=False, **kw).start()
    batched = ModelServer(
        net, max_batch_size=max_batch_size,
        batch_timeout_ms=batch_timeout_ms, **kw,
    ).start()
    best = {solo: None, batched: None}
    try:
        for s in (solo, batched):
            _drive(s, feats_pool, concurrency, 5)  # warm the loop
        # INTERLEAVED same-length windows, best per mode: host noise
        # (scheduler, frequency) drifts over seconds and only ever
        # SLOWS a run (the bench.py estimator), so alternating the
        # modes samples the same conditions for both and the max of
        # N honest end-to-end windows estimates each mode's
        # unimpeded rate
        for _ in range(windows):
            for s in (solo, batched):
                r, p50, p99 = _drive(s, feats_pool, concurrency,
                                     per_thread)
                if best[s] is None or r > best[s][0]:
                    best[s] = (r, p50, p99)
        # concurrency-1 latency: the adaptive batcher must not tax
        # the unloaded path
        c1 = {s: _drive(s, feats_pool, 1, 100)[1]
              for s in (solo, batched)}
        snap = batched.metrics_snapshot()
    finally:
        solo.stop(drain_timeout=2)
        batched.stop(drain_timeout=2)
    for name, s in (("solo", solo), ("batched", batched)):
        r, p50, p99 = best[s]
        out[name] = {"req_per_s": round(r, 1),
                     "p50_ms": round(p50, 3),
                     "p99_ms": round(p99, 3),
                     "p50_ms_c1": round(c1[s], 3)}
    occ = snap.get("batch_occupancy_rows") or {}
    out["batched"].update({
        "batches_total": snap["batches_total"],
        "mean_batch_rows": round(occ.get("mean") or 0.0, 2),
        "xla_compiles_total": snap["xla_compiles_total"],
        "post_warmup_compiles_total":
            snap["post_warmup_compiles_total"],
    })
    out["speedup"] = round(
        out["batched"]["req_per_s"] / out["solo"]["req_per_s"], 2
    )
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--per-thread", type=int, default=40,
                    help="requests per thread per measured window")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch-size", type=int, default=64)
    ap.add_argument("--batch-timeout-ms", type=float, default=8.0)
    ap.add_argument("--windows", type=int, default=3,
                    help="same-length windows per mode (max wins)")
    args = ap.parse_args()
    print(json.dumps(run(
        concurrency=args.concurrency, per_thread=args.per_thread,
        seed=args.seed, max_batch_size=args.max_batch_size,
        batch_timeout_ms=args.batch_timeout_ms,
        windows=args.windows,
    )))


if __name__ == "__main__":
    main()
