#!/usr/bin/env python
"""Serving micro-batch smoke benchmark (CPU, seeded, few seconds).

Drives ``ModelServer.submit()`` directly — the serving hot path
(admission -> queue -> drain -> predict -> response) minus socket
I/O, so the number isolates what micro-batching changes rather than
stdlib HTTP overhead — with a seeded synthetic closed-loop load at
fixed concurrency, once in solo mode (``micro_batch=False``, the
PR-2 one-predict-per-request loop) and once micro-batched. Prints
ONE JSON line::

    {"concurrency": 32,
     "solo":    {"req_per_s": ..., "p50_ms": ..., "p99_ms": ...,
                 "p50_ms_c1": ...},
     "batched": {"req_per_s": ..., "p50_ms": ..., "p99_ms": ...,
                 "p50_ms_c1": ..., "mean_batch_rows": ...,
                 "batches_total": ..., "xla_compiles_total": ...,
                 "post_warmup_compiles_total": ...},
     "speedup": ...}

The acceptance gates this makes falsifiable on CPU:

- ``speedup`` >= 4: one wide XLA dispatch per coalesced batch beats
  per-request dispatch at concurrency 32;
- ``post_warmup_compiles_total`` == 0: steady bucketed load compiles
  nothing after the eager warmup;
- ``p50_ms_c1`` (batched) is no worse than solo at concurrency 1:
  the adaptive batcher dispatches immediately when nothing else is
  in flight.

Runnable standalone (``python scripts/bench_serving.py``) or
imported by ``bench.py``'s serving section.

Fleet mode (``--fleet N``) measures the multi-tenant serving fleet:
N backend server processes (each serving ``--tenants`` named models
with quotas and a paging budget) behind one ``ServingRouter``,
driven closed-loop over real HTTP at fixed TOTAL concurrency, then
the same load against a single backend through the same router path
(so the comparison isolates process-level parallelism, not router
overhead). Prints ONE JSON line::

    {"fleet": {"processes": N, "req_per_s": ..., "per_tenant":
               {"m0": {"p50_ms": ..., "p99_ms": ...}, ...},
               "paging": {...}, "xla_compiles_total": ...},
     "single": {"req_per_s": ...},
     "scaling": fleet_req_per_s / single_req_per_s,
     "cpu_count": ...}

``scaling`` approaches the process count only when the host has the
cores to back it — on a 1-core CI box the processes time-share and
the honest number is ~1; ``cpu_count`` rides along so the reader
can tell the difference.
"""

import argparse
import http.client
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _make_net(seed=0, n_in=64, hidden=1024, n_out=8):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.1)
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh"))
        .layer(DenseLayer(n_in=hidden, n_out=hidden,
                          activation="tanh"))
        .layer(OutputLayer(n_out=n_out))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _drive(server, feats_pool, concurrency, per_thread):
    """Closed-loop load: each of ``concurrency`` threads submits
    ``per_thread`` requests back to back. Returns (req/s, p50 ms,
    p99 ms) over the whole run."""
    lat_per_thread = [[] for _ in range(concurrency)]
    errors = []

    def worker(tid):
        lats = lat_per_thread[tid]
        n = len(feats_pool)
        for i in range(per_thread):
            f = feats_pool[(tid * per_thread + i) % n]
            t0 = time.perf_counter()
            code, _, _ = server.submit(f)
            lats.append(time.perf_counter() - t0)
            if code != 200:
                errors.append(code)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(
            f"{len(errors)} non-200 responses (first: {errors[0]})"
        )
    lats = sorted(v for lst in lat_per_thread for v in lst)
    total = concurrency * per_thread

    def pct(q):
        return lats[min(len(lats) - 1, int(q * len(lats)))] * 1000.0

    return total / wall, pct(0.50), pct(0.99)


def run(concurrency=32, per_thread=40, seed=0,
        max_batch_size=64, batch_timeout_ms=8.0, windows=3) -> dict:
    from deeplearning4j_tpu.serving import ModelServer

    net = _make_net(seed=seed)
    rng = np.random.RandomState(seed)
    feats_pool = [rng.rand(1, 64).astype(np.float32)
                  for _ in range(256)]
    out = {"concurrency": concurrency,
           "requests_per_window": concurrency * per_thread,
           "windows": windows}

    kw = dict(workers=4, queue_depth=max(concurrency * 2, 64))
    solo = ModelServer(net, micro_batch=False, **kw).start()
    batched = ModelServer(
        net, max_batch_size=max_batch_size,
        batch_timeout_ms=batch_timeout_ms, **kw,
    ).start()
    best = {solo: None, batched: None}
    try:
        for s in (solo, batched):
            _drive(s, feats_pool, concurrency, 5)  # warm the loop
        # INTERLEAVED same-length windows, best per mode: host noise
        # (scheduler, frequency) drifts over seconds and only ever
        # SLOWS a run (the bench.py estimator), so alternating the
        # modes samples the same conditions for both and the max of
        # N honest end-to-end windows estimates each mode's
        # unimpeded rate
        for _ in range(windows):
            for s in (solo, batched):
                r, p50, p99 = _drive(s, feats_pool, concurrency,
                                     per_thread)
                if best[s] is None or r > best[s][0]:
                    best[s] = (r, p50, p99)
        # concurrency-1 latency: the adaptive batcher must not tax
        # the unloaded path
        c1 = {s: _drive(s, feats_pool, 1, 100)[1]
              for s in (solo, batched)}
        snap = batched.metrics_snapshot()
    finally:
        solo.stop(drain_timeout=2)
        batched.stop(drain_timeout=2)
    for name, s in (("solo", solo), ("batched", batched)):
        r, p50, p99 = best[s]
        out[name] = {"req_per_s": round(r, 1),
                     "p50_ms": round(p50, 3),
                     "p99_ms": round(p99, 3),
                     "p50_ms_c1": round(c1[s], 3)}
    occ = snap.get("batch_occupancy_rows") or {}
    out["batched"].update({
        "batches_total": snap["batches_total"],
        "mean_batch_rows": round(occ.get("mean") or 0.0, 2),
        "xla_compiles_total": snap["xla_compiles_total"],
        "post_warmup_compiles_total":
            snap["post_warmup_compiles_total"],
    })
    out["speedup"] = round(
        out["batched"]["req_per_s"] / out["solo"]["req_per_s"], 2
    )
    return out


# -- fleet mode ---------------------------------------------------------


N_IN_FLEET = 32  # smaller tenant nets: N processes boot in seconds


def _make_tenant_net(idx, seed=0):
    return _make_net(seed=seed + idx, n_in=N_IN_FLEET, hidden=128,
                     n_out=4)


def serve_backend(tenants=4, seed=0, workers=4, queue_depth=128,
                  quota=None, max_device_models=None,
                  preemption_drain=False):
    """``--serve``: one fleet backend process. Serves ``tenants``
    named models (``m0..``) from one ``ModelServer``, prints its port
    as one JSON line, then blocks until stdin closes (the parent's
    handle on our lifetime) — SIGKILL-ing us mid-load is the chaos
    scenario the router must absorb.

    ``--preemption-drain`` installs the resilience tier's
    ``PreemptionHandler`` and translates SIGTERM/SIGINT into the
    graceful drain (in-flight requests finish, new work sheds with
    503), then exits 0 — the preemption-notice chaos storm for the
    serving tier."""
    from deeplearning4j_tpu.serving import ModelServer

    models = {
        f"m{i}": {"model": _make_tenant_net(i, seed), "quota": quota}
        for i in range(tenants)
    }
    server = ModelServer(
        models=models, workers=workers, queue_depth=queue_depth,
        max_batch_size=32,
        max_device_models=max_device_models or None,
    ).start()
    drained = threading.Event()
    if preemption_drain:
        from deeplearning4j_tpu.resilience.preemption import (
            PreemptionHandler,
        )

        handler = PreemptionHandler().install()
        server.install_preemption_drain(handler, drain_timeout=10.0)
        handler.on_preemption(lambda reason: drained.set())
    print(json.dumps({"port": server.port, "pid": os.getpid()}),
          flush=True)
    if preemption_drain:
        # stdin EOF (parent died) on a side thread; the main thread
        # waits for the drain so the process exit code means
        # "drained cleanly", not "killed mid-request"
        eof = threading.Thread(target=sys.stdin.read, daemon=True)
        eof.start()
        while not drained.is_set() and eof.is_alive():
            drained.wait(0.05)
        if not drained.is_set():
            server.stop(drain_timeout=2)
        return
    try:
        sys.stdin.read()  # parent closed our stdin: time to go
    except KeyboardInterrupt:
        pass
    server.stop(drain_timeout=2)


def _spawn_backends(n, tenants, seed, timeout=120.0,
                    max_device_models=0):
    """Start n ``--serve`` children; returns (procs, ports)."""
    script = os.path.abspath(__file__)
    env = dict(os.environ)
    # one shared persistent compile cache: sibling backends load the
    # executables the first one compiled instead of recompiling the
    # same HLO n times (tenant nets differ only in weights)
    env.setdefault("DL4J_TPU_COMPILE_CACHE_DIR", os.path.join(
        tempfile.gettempdir(), "dl4j-fleet-compile-cache",
    ))
    procs, ports = [], []
    for i in range(n):
        procs.append(subprocess.Popen(
            [sys.executable, script, "--serve",
             "--tenants", str(tenants), "--seed", str(seed),
             "--max-device-models", str(max_device_models)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=env,
        ))
    try:
        for p in procs:
            deadline = time.monotonic() + timeout
            line = ""
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if line.strip():
                    break
            ports.append(int(json.loads(line)["port"]))
    except Exception:
        for p in procs:
            p.kill()
        raise
    return procs, ports


def _http_drive(router_port, tenants, concurrency, per_thread,
                seed=0):
    """Closed-loop HTTP load through the router: ``concurrency``
    threads, each pinned to one tenant (round-robin), ``per_thread``
    requests back to back on a keep-alive connection. Returns
    (req/s, {tenant: sorted latency list}, error list)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    feats = [rng.rand(1, N_IN_FLEET).astype(np.float32).tolist()
             for _ in range(64)]
    lat = {f"m{i}": [] for i in range(tenants)}
    lat_lock = threading.Lock()
    errors = []

    def worker(tid):
        tenant = f"m{tid % tenants}"
        mine = []
        conn = http.client.HTTPConnection("127.0.0.1", router_port,
                                          timeout=60)
        try:
            for i in range(per_thread):
                body = json.dumps({
                    "model": tenant,
                    "features": feats[(tid + i) % len(feats)],
                }).encode()
                t0 = time.perf_counter()
                try:
                    conn.request("POST", "/predict", body=body)
                    resp = conn.getresponse()
                    resp.read()
                    code = resp.status
                except OSError:
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", router_port, timeout=60,
                    )
                    code = -1
                mine.append(time.perf_counter() - t0)
                if code != 200:
                    errors.append(code)
        finally:
            conn.close()
        with lat_lock:
            lat[tenant].extend(mine)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return concurrency * per_thread / wall, lat, errors


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i] * 1000.0


def _scrape(port, path="/metrics"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def run_fleet(processes=4, tenants=4, concurrency=16, per_thread=30,
              seed=0, windows=2) -> dict:
    """Fleet A/B: N processes behind the router vs ONE process behind
    the same router, same total concurrency. Backends run with a
    device budget of tenants-1 models, so the paging stats in the
    JSON are MEASURED under load (one tenant per backend is always
    cold and faults in), not a dormant code path."""
    from deeplearning4j_tpu.serving import ServingRouter

    out = {"cpu_count": os.cpu_count(),
           "tenants": tenants, "concurrency": concurrency,
           "requests_per_window": concurrency * per_thread}
    budget = max(tenants - 1, 1)

    class _Topology:
        def __init__(self, n_backends):
            self.procs, self.ports = _spawn_backends(
                n_backends, tenants, seed, max_device_models=budget,
            )
            self.router = ServingRouter(
                [f"127.0.0.1:{p}" for p in self.ports]
            ).start()
            self.best_rate = None
            self.best_lat = None

        def drive(self, n):
            rate, lat, errors = _http_drive(
                self.router.port, tenants, concurrency, n, seed,
            )
            if errors:
                raise RuntimeError(
                    f"{len(errors)} non-200 through the router "
                    f"(first: {errors[0]})"
                )
            if self.best_rate is None or rate > self.best_rate:
                self.best_rate, self.best_lat = rate, lat

        def teardown(self):
            self.router.stop()
            for p in self.procs:
                p.stdin.close()  # graceful: backend drains, exits
            for p in self.procs:
                if p.wait() is None:
                    p.kill()

    # both topologies live at once, windows INTERLEAVED: host noise
    # on a shared box only ever slows a run, so alternating samples
    # the same conditions for both and the max of N honest windows
    # estimates each topology's unimpeded rate (same design as the
    # solo-vs-batched A/B above)
    fleet = _Topology(processes)
    single = _Topology(1)
    try:
        for t in (fleet, single):
            _http_drive(t.router.port, tenants, concurrency, 5,
                        seed)  # warm the whole path
        for _ in range(windows):
            for t in (fleet, single):
                t.drive(per_thread)
        snaps = [_scrape(p) for p in fleet.ports]
        rsnap = fleet.router.metrics_snapshot()
    finally:
        fleet.teardown()
        single.teardown()
    rate, lat = fleet.best_rate, fleet.best_lat
    srate = single.best_rate
    out["fleet"] = {
        "processes": processes,
        "req_per_s": round(rate, 1),
        "per_tenant": {
            t: {"p50_ms": round(_pct(sorted(v), 0.50), 3),
                "p99_ms": round(_pct(sorted(v), 0.99), 3),
                "requests": len(v)}
            for t, v in lat.items() if v
        },
        "paging": {
            **{k: sum(s["paging"][k] or 0 for s in snaps)
               for k in ("device_resident_models",
                         "device_resident_bytes",
                         "weight_pagein_total",
                         "weight_evict_total")},
            "pagein_p50_ms": round(max(
                ((s["paging"]["weight_pagein_ms"] or {}).get("p50")
                 or 0.0)
                for s in snaps
            ), 3),
        },
        "xla_compiles_total": sum(
            s["xla_compiles_total"] for s in snaps
        ),
        "post_warmup_compiles_total": sum(
            s["post_warmup_compiles_total"] for s in snaps
        ),
        "router": rsnap,
    }
    out["single"] = {"req_per_s": round(srate, 1)}
    out["scaling"] = round(rate / srate, 2)
    if (os.cpu_count() or 1) < processes:
        out["note"] = (
            f"host has {os.cpu_count()} core(s) for {processes} "
            "backend processes: they time-share, so scaling cannot "
            "approach the process count here — rerun on a host with "
            f">= {processes} cores for the parallel number"
        )
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--per-thread", type=int, default=40,
                    help="requests per thread per measured window")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch-size", type=int, default=64)
    ap.add_argument("--batch-timeout-ms", type=float, default=8.0)
    ap.add_argument("--windows", type=int, default=3,
                    help="same-length windows per mode (max wins)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: N backend processes behind a "
                         "router vs 1, same total concurrency")
    ap.add_argument("--tenants", type=int, default=4,
                    help="named models per backend (fleet/serve)")
    ap.add_argument("--serve", action="store_true",
                    help="internal: run one fleet backend process")
    ap.add_argument("--max-device-models", type=int, default=0,
                    help="backend weight-paging budget (0 = no "
                         "paging)")
    ap.add_argument("--preemption-drain", action="store_true",
                    help="with --serve: translate SIGTERM/SIGINT "
                         "into a graceful drain and exit 0")
    args = ap.parse_args()
    if args.serve:
        serve_backend(tenants=args.tenants, seed=args.seed,
                      max_device_models=args.max_device_models,
                      preemption_drain=args.preemption_drain)
        return
    if args.fleet:
        print(json.dumps(run_fleet(
            processes=args.fleet, tenants=args.tenants,
            concurrency=min(args.concurrency, 16),
            per_thread=min(args.per_thread, 30), seed=args.seed,
            windows=min(args.windows, 2),
        )))
        return
    print(json.dumps(run(
        concurrency=args.concurrency, per_thread=args.per_thread,
        seed=args.seed, max_batch_size=args.max_batch_size,
        batch_timeout_ms=args.batch_timeout_ms,
        windows=args.windows,
    )))


if __name__ == "__main__":
    main()
