#!/usr/bin/env python
"""Fused-kernel A/B: the Pallas conv/matmul epilogue kernels vs the
plain XLA path (ROADMAP: the kernel half of the MFU campaign).

Per config — a conv stack, a ResNet-50 bottleneck block, and an MLP —
this measures three variants of the same math:

``kernel``     ``ops.conv_block``/``ops.matmul_block`` (ONE Pallas
               kernel per stage: MXU contraction + in-register
               bias/BN-affine/activation epilogue, single HBM
               writeback).
``fused``      the XLA reference path: one jitted expression per
               chain; XLA fuses the epilogue into the conv/matmul
               consumer, so it is the fair same-compiler baseline.
``unfused``    the historical op-at-a-time decomposition: conv,
               +bias, BN affine, and activation each compiled as a
               SEPARATE executable. Every executable boundary is a
               real HBM materialization — these are exactly the
               round-trips the fused epilogue deletes. (An in-jit
               "unfused" build is not honest evidence: XLA elides
               optimization barriers on some backends and re-fuses.)

Round-trip evidence is compiled-op/executable counts, not wall clock:
the unfused pipeline must carry more executables and more total
entry-computation instructions than the fused build, and the bytes of
its intermediate buffers (``epilogue_roundtrip_bytes``) quantify the
HBM traffic the epilogue fusion eliminates per step.

Gates:
  * forward parity: max |kernel - fused| <= 1e-5 (f32; interpret mode
    on CPU exercises the identical kernel code path).
  * epilogue fusion: fused executables (1 per chain) < unfused stage
    executables, fused entry ops < unfused total, round-trip bytes
    positive — for every config.

Timing (interleaved A/B windows, step time + achieved FLOP/s + MFU
delta) runs only on a real TPU: in interpreter mode the kernel
executes as a correctness shim, so CPU runs report correctness-only
and set ``timing_skipped``. Prints ONE JSON line; runnable standalone
or from ``bench.py``'s ``fused_kernels`` section (PR-5 SIGALRM budget
box + PR-6 compile-stats sidecar ride along in the bench harness).

``--tuned`` runs the autotuner A/B instead (``bench.py``'s
``kernel_autotune`` section): a cold ``DL4J_TPU_TUNE=on`` pass
searches conv/matmul tilings into a fresh cache (the tuner's own
interleaved best-of-N measures heuristic + top-K candidates), then a
``cached``-mode pass re-resolves from the persisted entries with the
searches/measure counters asserted at ZERO — the warm-cache
zero-measurement contract. Per kernel it reports the winner config,
the measured heuristic-vs-winner times from the persisted entry, and
``tuned_delta`` (fractional improvement, non-negative by construction
since the heuristic is always in the measured set and the winner is
the argmin).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PARITY_TOL = 1e-5  # f32 forward gate vs the XLA reference


def _entry_op_count(fn, *args) -> int:
    """Instructions in the compiled module's ENTRY computation — the
    backend-honest surviving-op count (post-fusion, post-DCE)."""
    import jax

    txt = jax.jit(fn).lower(*args).compile().as_text()
    count, in_entry = 0, False
    for line in txt.splitlines():
        s = line.strip()
        if s.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if s.startswith("}"):
                break
            if "=" in s:
                count += 1
    return count


def _interleaved_times(fn_a, fn_b, args_a, args_b, inner=8, rounds=3):
    """Alternating timed windows (A, B, A, B, ...) so drift hits both
    sides equally; returns (best_a_seconds, best_b_seconds) per call."""
    import jax

    jax.block_until_ready(fn_a(*args_a))
    jax.block_until_ready(fn_b(*args_b))
    best_a = best_b = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(inner):
            r = fn_a(*args_a)
        jax.block_until_ready(r)
        best_a = min(best_a, (time.perf_counter() - t0) / inner)
        t0 = time.perf_counter()
        for _ in range(inner):
            r = fn_b(*args_b)
        jax.block_until_ready(r)
        best_b = min(best_b, (time.perf_counter() - t0) / inner)
    return best_a, best_b


def _conv_config(name, x_shape, stage_specs, strides, pads, dtype):
    """One conv-chain config. Every stage is conv + bias + BN affine +
    relu; ``unfused`` dispatches the four sub-ops as separate
    executables per stage (the op-at-a-time decomposition)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops import conv_block, conv_block_reference

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(*x_shape), dtype)
    params = []
    for (o, c, kh, kw) in stage_specs:
        params.append((
            jnp.asarray(rng.randn(o, c, kh, kw) * 0.1, dtype),
            jnp.asarray(rng.randn(o) * 0.1, jnp.float32),
            jnp.asarray(rng.rand(o) + 0.5, jnp.float32),
            jnp.asarray(rng.randn(o) * 0.1, jnp.float32),
        ))

    def run_kernel(x, params):
        for (w, b, a, bb), s, p in zip(params, strides, pads):
            x = conv_block(x, w, b, a, bb, stride=s, padding=p,
                           activation="relu")
        return x

    def run_fused(x, params):
        for (w, b, a, bb), s, p in zip(params, strides, pads):
            x = conv_block_reference(x, w, b, a, bb, stride=s,
                                     padding=p, activation="relu")
        return x

    def _conv_only(x, w, s, p):
        y = jax.lax.conv_general_dilated(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=s,
            padding=((p[0], p[0]), (p[1], p[1])),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )
        return jnp.transpose(y, (0, 3, 1, 2))

    # one executable per sub-op, chained at the Python level: each
    # boundary materializes its output — the round-trips under test
    stages = []
    for (w, b, a, bb), s, p in zip(params, strides, pads):
        stages.append(jax.jit(
            lambda x, w=w, s=s, p=p: _conv_only(x, w, s, p)))
        stages.append(jax.jit(
            lambda y, b=b: y + b.reshape(1, -1, 1, 1)))
        stages.append(jax.jit(
            lambda y, a=a, bb=bb: y * a.reshape(1, -1, 1, 1)
            + bb.reshape(1, -1, 1, 1)))
        stages.append(jax.jit(
            lambda y: jnp.maximum(y, 0.0).astype(dtype)))

    def run_unfused(x, params):
        del params  # stages close over their own
        for f in stages:
            x = f(x)
        return x

    # analytic MXU work: 2 * N * OH * OW * KH * KW * C * O per stage
    flops = 0
    h, w_ = x_shape[2], x_shape[3]
    for (o, c, kh, kw), s, p in zip(stage_specs, strides, pads):
        oh = (h + 2 * p[0] - kh) // s[0] + 1
        ow = (w_ + 2 * p[1] - kw) // s[1] + 1
        flops += 2 * x_shape[0] * oh * ow * kh * kw * c * o
        h, w_ = oh, ow

    return _measure(name, run_kernel, run_fused, run_unfused, stages,
                    (x, params), flops)


def _mlp_config(name, m, dims, dtype):
    """Dense-chain config (activation(x @ w + b) per stage)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops import matmul_block, matmul_block_reference

    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(m, dims[0]), dtype)
    params = [
        (jnp.asarray(rng.randn(k, n) * 0.05, dtype),
         jnp.asarray(rng.randn(n) * 0.1, jnp.float32))
        for k, n in zip(dims[:-1], dims[1:])
    ]

    def run_kernel(x, params):
        for w, b in params:
            x = matmul_block(x, w, b, activation="relu")
        return x

    def run_fused(x, params):
        for w, b in params:
            x = matmul_block_reference(x, w, b, activation="relu")
        return x

    stages = []
    for w, b in params:
        stages.append(jax.jit(
            lambda x, w=w: jnp.dot(
                x, w, preferred_element_type=jnp.float32)))
        stages.append(jax.jit(lambda y, b=b: y + b))
        stages.append(jax.jit(
            lambda y: jnp.maximum(y, 0.0).astype(dtype)))

    def run_unfused(x, params):
        del params
        for f in stages:
            x = f(x)
        return x

    flops = sum(2 * m * k * n for k, n in zip(dims[:-1], dims[1:]))
    return _measure(name, run_kernel, run_fused, run_unfused, stages,
                    (x, params), flops)


def _measure(name, run_kernel, run_fused, run_unfused, stages, args,
             flops):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.observability import profiler
    from deeplearning4j_tpu.ops.dispatch import pallas_interpret

    jk = jax.jit(run_kernel)
    jf = jax.jit(run_fused)

    yk = jax.block_until_ready(jk(*args))
    yf = jax.block_until_ready(jf(*args))
    err = float(jnp.max(jnp.abs(
        yk.astype(jnp.float32) - yf.astype(jnp.float32)
    )))

    # round-trip accounting: replay the stage chain, lowering each
    # executable with its real input and summing the intermediate
    # buffers it materializes (everything but the final output)
    ops_unfused = 0
    roundtrip_bytes = 0
    y = args[0]
    for i, f in enumerate(stages):
        ops_unfused += _entry_op_count(f, y)
        y = f(y)
        if i + 1 < len(stages):
            roundtrip_bytes += int(y.size * y.dtype.itemsize)
    ops_fused = _entry_op_count(run_fused, *args)

    # MFU accounting goes through the profiler's CostModel (XLA's own
    # scheduled cost for the fused reference executable), not the
    # analytic count — which stays as a sanity cross-check only
    cm = profiler.CostModel.from_jitted(jf, *args, key=name)
    peak, peak_src = profiler.peak_flops()
    peak_bw, _ = profiler.peak_bytes_per_sec()

    out = {
        "mode": "interpret" if pallas_interpret() else "pallas",
        "parity_max_err": err,
        "parity_ok": bool(err <= PARITY_TOL),
        "cost_model": {
            "flops": cm.flops,
            "bytes_accessed": cm.bytes_accessed,
            "arithmetic_intensity": round(cm.arithmetic_intensity, 3),
            "roofline_class": cm.roofline_class(peak, peak_bw),
        },
        "flops_per_step_analytic": flops,
        "executables_fused": 1,
        "executables_unfused": len(stages),
        "entry_ops_fused": ops_fused,
        "entry_ops_unfused": ops_unfused,
        "epilogue_roundtrip_bytes": roundtrip_bytes,
        # the evidence: op-at-a-time needs more executables AND more
        # surviving instructions; the byte count is the HBM traffic
        # the in-register epilogue deletes
        "epilogue_fusion_verified": bool(
            len(stages) > 1
            and ops_fused < ops_unfused
            and roundtrip_bytes > 0
        ),
    }
    if pallas_interpret():
        # interpreter mode is a correctness shim, not a kernel — wall
        # clock would compare the interpreter loop to native XLA
        out["timing_skipped"] = True
        return name, out
    ju = jax.jit(run_unfused)
    t_kernel, t_fused = _interleaved_times(jk, jf, args, args)
    _, t_unfused = _interleaved_times(jk, ju, args, args)
    # achieved rates + MFU from the CostModel (both variants compute
    # the same math, so the fused executable's cost is the work done)
    ach_k = cm.achieved(t_kernel, peak)
    ach_f = cm.achieved(t_fused, peak)
    out.update({
        "timing_skipped": False,
        "step_ms_kernel": round(t_kernel * 1e3, 4),
        "step_ms_xla_fused": round(t_fused * 1e3, 4),
        "step_ms_xla_unfused": round(t_unfused * 1e3, 4),
        "flops_per_sec_kernel": ach_k["flops_per_sec"],
        "flops_per_sec_xla": ach_f["flops_per_sec"],
        "bytes_per_sec_kernel": ach_k["bytes_per_sec"],
        "speedup_vs_fused": round(t_fused / t_kernel, 3),
        "speedup_vs_unfused": round(t_unfused / t_kernel, 3),
    })
    if ach_k["mfu"] is not None:
        out.update({
            "mfu_kernel": round(ach_k["mfu"], 4),
            "mfu_xla": round(ach_f["mfu"], 4),
            "mfu_delta": round(ach_k["mfu"] - ach_f["mfu"], 4),
            "peak_flops_source": peak_src,
        })
    return name, out


def _counter_total(name):
    """Summed value of every child of a counter family (0 when the
    family has not been created yet)."""
    from deeplearning4j_tpu.observability.metrics import default_registry

    fam = default_registry().get(name)
    if fam is None:
        return 0.0
    return float(sum(c.value for c in fam.children()))


def _hist_count(name):
    from deeplearning4j_tpu.observability.metrics import default_registry

    fam = default_registry().get(name)
    if fam is None:
        return 0
    return int(sum(c.count for c in fam.children()))


def _autotune_ab(budget_s=None, cache_dir=None):
    """Tuned-vs-heuristic A/B through the real autotuner: cold
    ``on``-mode search into a fresh cache, then a warm ``cached``-mode
    resolve asserted to perform zero searches and zero measurements.
    Timings come from the persisted entries (the tuner's own
    interleaved best-of-N), so the delta is exactly what dispatch will
    see."""
    import importlib
    import tempfile

    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import dispatch

    autotune = importlib.import_module("deeplearning4j_tpu.ops.autotune")
    tiling = importlib.import_module("deeplearning4j_tpu.ops.tiling")
    cbm = importlib.import_module("deeplearning4j_tpu.ops.conv_block")
    mmm = importlib.import_module("deeplearning4j_tpu.ops.matmul_block")

    if cache_dir is None:
        cache_dir = tempfile.mkdtemp(prefix="dl4j_tune_bench_")
    saved = {k: os.environ.get(k)
             for k in ("DL4J_TPU_TUNE", "DL4J_TPU_TUNE_CACHE_DIR",
                       "DL4J_TPU_TUNE_BUDGET_MS")}
    # split the soft budget across the searches; the tuner's heuristic
    # measurement is budget-exempt so even a tiny box yields a delta
    per_search_ms = 1500.0
    if budget_s:
        per_search_ms = max(250.0, float(budget_s) * 1e3 / 4)
    out = {"cache_dir": cache_dir, "kernels": {}}
    try:
        os.environ["DL4J_TPU_TUNE"] = "on"
        os.environ["DL4J_TPU_TUNE_CACHE_DIR"] = cache_dir
        os.environ["DL4J_TPU_TUNE_BUDGET_MS"] = str(per_search_ms)
        dispatch.reset_for_tests()
        interp = dispatch.pallas_interpret()
        out["mode"] = "interpret" if interp else "pallas"

        conv_args = ((4, 8, 16, 16), (16, 8, 3, 3), (1, 1), (1, 1))
        subjects = {
            "conv_block": {
                "resolve": lambda: cbm._resolve_fwd_blocks(
                    *conv_args, jnp.float32, interp),
                "identity": cbm._identity(*conv_args, jnp.float32),
                "heuristic": tiling.pick_conv_blocks(*conv_args, 4),
            },
            "matmul_block": {
                "resolve": lambda: mmm._resolve_blocks(
                    128, 256, 256, jnp.float32, False, interp),
                "identity": {"m": 128, "k": 256, "n": 256,
                             "dtype": "float32", "residual": False},
                "heuristic": tiling.pick_matmul_blocks(128, 256, 256,
                                                       4),
            },
        }

        s0 = _counter_total("tuner_searches_total")
        cold = {k: sub["resolve"]() for k, sub in subjects.items()}
        out["cold_searches"] = _counter_total(
            "tuner_searches_total") - s0

        # warm pass: cached mode must resolve every entry from disk
        # with ZERO searches and ZERO measurement rounds
        os.environ["DL4J_TPU_TUNE"] = "cached"
        dispatch.reset_for_tests()
        s1 = _counter_total("tuner_searches_total")
        m1 = _hist_count("tuner_measure_ms")
        warm = {k: sub["resolve"]() for k, sub in subjects.items()}
        out["warm_searches"] = _counter_total(
            "tuner_searches_total") - s1
        out["warm_measurements"] = _hist_count("tuner_measure_ms") - m1
        out["warm_cache_hits"] = _counter_total(
            "tuner_cache_hits_total")

        deltas_ok = True
        for name, sub in subjects.items():
            doc = autotune.read_entry(name, sub["identity"]) or {}
            timings = doc.get("timings_ms") or {}
            heur_tag = autotune._cfg_tag(sub["heuristic"])
            heur_ms = timings.get(heur_tag)
            best_ms = doc.get("best_ms")
            delta = None
            if heur_ms and best_ms is not None:
                delta = (heur_ms - best_ms) / heur_ms
                deltas_ok = deltas_ok and delta >= -1e-9
            else:
                deltas_ok = False
            out["kernels"][name] = {
                "heuristic": heur_tag,
                "config": ("x".join(str(v) for v in cold[name])
                           if cold[name] else None),
                "warm_config": ("x".join(str(v) for v in warm[name])
                                if warm[name] else None),
                "heuristic_ms": heur_ms,
                "best_ms": best_ms,
                "tuned_delta": delta,
                "measured": doc.get("measured"),
            }
        out["tuned_nonneg_ok"] = deltas_ok
        out["warm_zero_measure_ok"] = bool(
            out["warm_searches"] == 0 and out["warm_measurements"] == 0
        )
        out["warm_configs_match"] = all(
            cold[k] == warm[k] for k in subjects
        )
        out["autotune_ok"] = bool(
            out["tuned_nonneg_ok"] and out["warm_zero_measure_ok"]
            and out["warm_configs_match"]
        )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        dispatch.reset_for_tests()
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=float, default=None,
                    help="soft budget hint (sizes are fixed; the "
                         "bench harness owns the hard SIGALRM box)")
    ap.add_argument("--config", choices=["conv_stack", "resnet50_block",
                                         "mlp"], default=None)
    ap.add_argument("--tuned", action="store_true",
                    help="run the autotuner A/B (cold search + warm "
                         "zero-measurement resolve) instead of the "
                         "fused-kernel configs")
    ap.add_argument("--tune-cache-dir", default=None,
                    help="tuning cache dir for --tuned (default: a "
                         "fresh temp dir, so the cold pass really "
                         "searches)")
    args = ap.parse_args()

    if args.tuned:
        auto = _autotune_ab(args.budget_s, args.tune_cache_dir)
        doc = {"autotune": auto, "autotune_ok": auto["autotune_ok"]}
        print(json.dumps(doc))
        return 0 if doc["autotune_ok"] else 1

    configs = {}

    def want(key):
        return args.config is None or args.config == key

    if want("conv_stack"):
        k, v = _conv_config(
            "conv_stack",
            x_shape=(8, 8, 16, 16),
            stage_specs=[(16, 8, 3, 3), (16, 16, 3, 3), (32, 16, 3, 3)],
            strides=[(1, 1), (1, 1), (2, 2)],
            pads=[(1, 1), (1, 1), (0, 0)],
            dtype="float32",
        )
        configs[k] = v
    if want("resnet50_block"):
        # the conv14 bottleneck: 1x1 reduce, 3x3, 1x1 expand
        k, v = _conv_config(
            "resnet50_block",
            x_shape=(4, 64, 14, 14),
            stage_specs=[(16, 64, 1, 1), (16, 16, 3, 3),
                         (64, 16, 1, 1)],
            strides=[(1, 1), (1, 1), (1, 1)],
            pads=[(0, 0), (1, 1), (0, 0)],
            dtype="float32",
        )
        configs[k] = v
    if want("mlp"):
        k, v = _mlp_config("mlp", m=64, dims=(128, 256, 256, 128),
                           dtype="float32")
        configs[k] = v

    doc = {
        "configs": configs,
        "kernel_parity_ok": all(c["parity_ok"]
                                for c in configs.values()),
        "epilogue_fusion_verified": all(
            c["epilogue_fusion_verified"] for c in configs.values()
        ),
        "parity_tol": PARITY_TOL,
    }
    print(json.dumps(doc))
    return 0 if doc["kernel_parity_ok"] and \
        doc["epilogue_fusion_verified"] else 1


if __name__ == "__main__":
    sys.exit(main())
