#!/usr/bin/env python
"""Static check: both engine wrappers delegate their hot paths to the
unified functional core (``deeplearning4j_tpu/nn/core.py``).

History: ``MultiLayerNetwork`` and ``ComputationGraph`` each carried a
private copy of the train-step builder, the scan-fused multi-step,
the pretrain step, and the fit drivers — every perf PR paid its tax
twice, and the copies drifted. The core refactor collapsed them; this
lint keeps them collapsed:

1. Both engine modules must import ``deeplearning4j_tpu.nn.core``.
2. Neither engine module may call the primitives that define a hot
   path of its own: ``value_and_grad`` / ``grad`` (a private backward
   pass), ``lax.scan`` / ``checkpoint`` / ``remat`` (a private
   whole-net transform), ``updater.update`` outside the core, or any
   cross-device collective (``psum`` / ``all_gather`` /
   ``psum_scatter`` — collectives live only in ``parallel/`` and
   ``nn/core.py``; an engine that grows one has re-inlined a
   distribution concern, e.g. the ZeRO all-gather).
3. The core must actually define the shared machinery the engines
   claim to delegate to (``build_step``, ``build_multi_step``,
   ``build_pretrain_step``, ``apply_layer_run``, ``fit_batches``).
4. Both engine classes must still expose the delegating methods the
   rest of the stack calls (``_build_step``, ``_build_multi_step``,
   ``fit_minibatch``, ``output``).

Pure AST scan — nothing is imported, so this runs in milliseconds in
any environment (part of the ``scripts/run_chaos.sh`` preamble next
to ``lint_metrics.py``).

Exit 0 when the split holds; exit 1 with the exact violations.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
NN = REPO / "deeplearning4j_tpu" / "nn"
ENGINES = {
    "MultiLayerNetwork": NN / "multilayer.py",
    "ComputationGraph": NN / "graph.py",
}
CORE = NN / "core.py"

# calling any of these inside an engine module means a duplicate hot
# path grew back (the backward pass, a scan fusion, or a remat wrap
# that belongs in the core)
FORBIDDEN_CALLS = {"value_and_grad", "scan", "checkpoint", "remat"}
# cross-device collectives: distribution (grad psum, the ZeRO state
# all-gather, reduce-scatter variants) lives in parallel/ + nn/core.py
# only — an engine file growing one of these has re-inlined it
FORBIDDEN_COLLECTIVES = {
    "psum", "all_gather", "all_gather_invariant", "psum_scatter",
}
# plus updater.update(...) — the optimizer application site
FORBIDDEN_METHOD_ON = {"update": {"updater", "upd_def", "updater_def"}}

CORE_REQUIRED = {
    "build_step", "build_multi_step", "build_pretrain_step",
    "apply_layer_run", "maybe_remat", "fit_batches", "run_scan_chunk",
    "apply_step_out",
}
ENGINE_REQUIRED_METHODS = {
    "_build_step", "_build_multi_step", "fit_minibatch", "output",
}


def call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def call_base(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return ""


def check_engine(name: str, path: Path, errors: list) -> None:
    tree = ast.parse(path.read_text(), filename=str(path))
    imports_core = any(
        (isinstance(n, ast.ImportFrom)
         and n.module == "deeplearning4j_tpu.nn"
         and any(a.name == "core" for a in n.names))
        or (isinstance(n, ast.ImportFrom)
            and n.module == "deeplearning4j_tpu.nn.core")
        or (isinstance(n, ast.Import)
            and any(a.name == "deeplearning4j_tpu.nn.core"
                    for a in n.names))
        for n in ast.walk(tree)
    )
    if not imports_core:
        errors.append(
            f"{path.name}: does not import deeplearning4j_tpu.nn.core"
        )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        base = call_base(node)
        if base == "core":
            continue  # delegation to the core is the point
        if cn in FORBIDDEN_CALLS:
            errors.append(
                f"{path.name}:{node.lineno}: calls {cn}() — the "
                "backward pass / scan fusion / remat belongs in "
                "nn/core.py"
            )
        if cn in FORBIDDEN_COLLECTIVES:
            errors.append(
                f"{path.name}:{node.lineno}: calls {cn}() — "
                "collectives live only in parallel/ + nn/core.py"
            )
        bases = FORBIDDEN_METHOD_ON.get(cn)
        if bases and base in bases:
            errors.append(
                f"{path.name}:{node.lineno}: calls {base}.{cn}() — "
                "optimizer application belongs in nn/core.py"
            )
    # the engine class must still expose the delegating surface
    cls = next(
        (n for n in tree.body
         if isinstance(n, ast.ClassDef) and n.name == name), None,
    )
    if cls is None:
        errors.append(f"{path.name}: class {name} not found")
        return
    methods = {
        n.name for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for m in sorted(ENGINE_REQUIRED_METHODS - methods):
        errors.append(
            f"{path.name}: {name} lost its delegating method {m}()"
        )


def check_pallas_locality(errors: list) -> None:
    """All Pallas entry points live in ``deeplearning4j_tpu/ops/`` and
    go through the dispatch gate. A layer (or any other) module calling
    ``pl.pallas_call`` directly has grown a private kernel outside the
    library: it bypasses ``dispatch.use_pallas()``/``pallas_interpret``
    (the off-TPU interpreter arming), the dispatch metrics, and the
    interleaved A/B in ``scripts/bench_kernels.py``."""
    pkg = REPO / "deeplearning4j_tpu"
    ops_dir = pkg / "ops"
    for path in sorted(pkg.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        calls_pallas = [
            node.lineno for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and call_name(node) == "pallas_call"
        ]
        if not calls_pallas:
            continue
        if ops_dir not in path.parents:
            errors.append(
                f"{path.relative_to(REPO)}:{calls_pallas[0]}: calls "
                "pallas_call() outside deeplearning4j_tpu/ops/ — "
                "Pallas kernels live in the ops/ library behind "
                "dispatch.use_pallas()"
            )
            continue
        # an ops kernel module must reference the dispatch gate (its
        # public wrappers resolve interpret/use_pallas before the call)
        names = {
            n.attr if isinstance(n, ast.Attribute) else
            getattr(n, "id", "")
            for n in ast.walk(tree)
            if isinstance(n, (ast.Attribute, ast.Name))
        }
        if not names & {"use_pallas", "pallas_interpret"}:
            errors.append(
                f"{path.relative_to(REPO)}: calls pallas_call() but "
                "never consults dispatch.use_pallas()/"
                "pallas_interpret() — forced-on CPU runs would crash "
                "in Mosaic lowering instead of interpreting"
            )


def check_core(errors: list) -> None:
    tree = ast.parse(CORE.read_text(), filename=str(CORE))
    defined = {
        n.name for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for fn in sorted(CORE_REQUIRED - defined):
        errors.append(
            f"core.py: missing shared implementation {fn}() — the "
            "engines have nothing to delegate to"
        )


def main() -> int:
    errors: list = []
    check_core(errors)
    for name, path in ENGINES.items():
        check_engine(name, path, errors)
    check_pallas_locality(errors)
    if errors:
        print("engine/core parity violations:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print(
        "lint_parity: both engines delegate step/apply/fit hot paths "
        "to nn/core.py; Pallas kernels stay in ops/ behind dispatch"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
