#!/usr/bin/env python
"""Static check: both engine wrappers delegate their hot paths to the
unified functional core (``deeplearning4j_tpu/nn/core.py``).

History: ``MultiLayerNetwork`` and ``ComputationGraph`` each carried a
private copy of the train-step builder, the scan-fused multi-step,
the pretrain step, and the fit drivers — every perf PR paid its tax
twice, and the copies drifted. The core refactor collapsed them; this
lint keeps them collapsed:

1. Both engine modules must import ``deeplearning4j_tpu.nn.core``.
2. Neither engine module may call the primitives that define a hot
   path of its own: ``value_and_grad`` / ``grad`` (a private backward
   pass), ``lax.scan`` / ``checkpoint`` / ``remat`` (a private
   whole-net transform), ``updater.update`` outside the core, or any
   cross-device collective (``psum`` / ``all_gather`` /
   ``psum_scatter`` — collectives live only in ``parallel/`` and
   ``nn/core.py``; an engine that grows one has re-inlined a
   distribution concern, e.g. the ZeRO all-gather).
3. The core must actually define the shared machinery the engines
   claim to delegate to (``build_step``, ``build_multi_step``,
   ``build_pretrain_step``, ``apply_layer_run``, ``fit_batches``).
4. Both engine classes must still expose the delegating methods the
   rest of the stack calls (``_build_step``, ``_build_multi_step``,
   ``fit_minibatch``, ``output``).

Pure AST scan — nothing is imported, so this runs in milliseconds in
any environment (part of the ``scripts/run_chaos.sh`` preamble next
to ``lint_metrics.py``).

Exit 0 when the split holds; exit 1 with the exact violations.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
NN = REPO / "deeplearning4j_tpu" / "nn"
ENGINES = {
    "MultiLayerNetwork": NN / "multilayer.py",
    "ComputationGraph": NN / "graph.py",
}
CORE = NN / "core.py"

# calling any of these inside an engine module means a duplicate hot
# path grew back (the backward pass, a scan fusion, or a remat wrap
# that belongs in the core)
FORBIDDEN_CALLS = {"value_and_grad", "scan", "checkpoint", "remat"}
# cross-device collectives: distribution (grad psum, the ZeRO state
# all-gather, reduce-scatter variants) lives in parallel/ + nn/core.py
# only — an engine file growing one of these has re-inlined it
FORBIDDEN_COLLECTIVES = {
    "psum", "all_gather", "all_gather_invariant", "psum_scatter",
}
# plus updater.update(...) — the optimizer application site
FORBIDDEN_METHOD_ON = {"update": {"updater", "upd_def", "updater_def"}}

CORE_REQUIRED = {
    "build_step", "build_multi_step", "build_pretrain_step",
    "apply_layer_run", "maybe_remat", "fit_batches", "run_scan_chunk",
    "apply_step_out", "build_megastep", "run_megastep_chunk",
    "megastep_readback", "fit_epoch_megastep",
}

# megastep contract: the per-chunk driver loop in nn/core.py owns ONE
# designated host-readback site (megastep_readback). Any other host
# sync inside the drivers silently turns the fused K-step dispatch
# back into K round trips — the exact regression the megastep exists
# to kill, and invisible to correctness tests (trajectory unchanged,
# only dispatches/step bloats).
MEGASTEP_DRIVERS = {
    "run_megastep_chunk", "fit_epoch_megastep", "flush_megastep",
}
MEGASTEP_FORBIDDEN = {
    "block_until_ready", "device_get", "item", "tolist", "asarray",
    "copy_to_host_async",
}
ENGINE_REQUIRED_METHODS = {
    "_build_step", "_build_multi_step", "fit_minibatch", "output",
}


def call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def call_base(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return ""


def check_engine(name: str, path: Path, errors: list) -> None:
    tree = ast.parse(path.read_text(), filename=str(path))
    imports_core = any(
        (isinstance(n, ast.ImportFrom)
         and n.module == "deeplearning4j_tpu.nn"
         and any(a.name == "core" for a in n.names))
        or (isinstance(n, ast.ImportFrom)
            and n.module == "deeplearning4j_tpu.nn.core")
        or (isinstance(n, ast.Import)
            and any(a.name == "deeplearning4j_tpu.nn.core"
                    for a in n.names))
        for n in ast.walk(tree)
    )
    if not imports_core:
        errors.append(
            f"{path.name}: does not import deeplearning4j_tpu.nn.core"
        )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        base = call_base(node)
        if base == "core":
            continue  # delegation to the core is the point
        if cn in FORBIDDEN_CALLS:
            errors.append(
                f"{path.name}:{node.lineno}: calls {cn}() — the "
                "backward pass / scan fusion / remat belongs in "
                "nn/core.py"
            )
        if cn in FORBIDDEN_COLLECTIVES:
            errors.append(
                f"{path.name}:{node.lineno}: calls {cn}() — "
                "collectives live only in parallel/ + nn/core.py"
            )
        bases = FORBIDDEN_METHOD_ON.get(cn)
        if bases and base in bases:
            errors.append(
                f"{path.name}:{node.lineno}: calls {base}.{cn}() — "
                "optimizer application belongs in nn/core.py"
            )
    # the engine class must still expose the delegating surface
    cls = next(
        (n for n in tree.body
         if isinstance(n, ast.ClassDef) and n.name == name), None,
    )
    if cls is None:
        errors.append(f"{path.name}: class {name} not found")
        return
    methods = {
        n.name for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for m in sorted(ENGINE_REQUIRED_METHODS - methods):
        errors.append(
            f"{path.name}: {name} lost its delegating method {m}()"
        )


def check_pallas_locality(errors: list) -> None:
    """All Pallas entry points live in ``deeplearning4j_tpu/ops/`` and
    go through the dispatch gate. A layer (or any other) module calling
    ``pl.pallas_call`` directly has grown a private kernel outside the
    library: it bypasses ``dispatch.use_pallas()``/``pallas_interpret``
    (the off-TPU interpreter arming), the dispatch metrics, and the
    interleaved A/B in ``scripts/bench_kernels.py``."""
    pkg = REPO / "deeplearning4j_tpu"
    ops_dir = pkg / "ops"
    for path in sorted(pkg.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        calls_pallas = [
            node.lineno for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and call_name(node) == "pallas_call"
        ]
        if not calls_pallas:
            continue
        if ops_dir not in path.parents:
            errors.append(
                f"{path.relative_to(REPO)}:{calls_pallas[0]}: calls "
                "pallas_call() outside deeplearning4j_tpu/ops/ — "
                "Pallas kernels live in the ops/ library behind "
                "dispatch.use_pallas()"
            )
            continue
        # an ops kernel module must reference the dispatch gate (its
        # public wrappers resolve interpret/use_pallas before the call)
        names = {
            n.attr if isinstance(n, ast.Attribute) else
            getattr(n, "id", "")
            for n in ast.walk(tree)
            if isinstance(n, (ast.Attribute, ast.Name))
        }
        if not names & {"use_pallas", "pallas_interpret"}:
            errors.append(
                f"{path.relative_to(REPO)}: calls pallas_call() but "
                "never consults dispatch.use_pallas()/"
                "pallas_interpret() — forced-on CPU runs would crash "
                "in Mosaic lowering instead of interpreting"
            )


TILING_OWNERS = {"tiling.py", "autotune.py"}
# legacy per-module block pickers the tiling refactor deleted; one of
# these reappearing means a kernel grew a private divisor heuristic
# the autotuner can't see (its candidate space and the dispatch
# heuristic would disagree about feasibility)
LEGACY_PICKERS = {
    "_pick_blocks", "_seq_batch_block", "_divisors_desc",
    "_largest_divisor_leq",
}


def check_tiling_locality(errors: list) -> None:
    """Block-size selection for the Pallas kernels lives ONLY in
    ``ops/tiling.py`` (VMEM budget, divisor heuristics, candidate
    enumeration) and ``ops/autotune.py`` (measured winners over that
    same candidate space). A kernel module doing its own inline
    divisor math (the ``%`` operator) or re-growing a private picker
    forks the feasibility rules: the heuristic, the tuner's candidate
    space, and the ``*_ok`` routing gates drift apart, and a persisted
    tuning entry can validate against one rule set and dispatch under
    another. (String ``%``-formatting is exempt; blocked-grid
    ``//`` arithmetic is fine — only divisibility/remainder tests are
    selection logic.)"""
    ops_dir = REPO / "deeplearning4j_tpu" / "ops"
    for path in sorted(ops_dir.glob("*.py")):
        if path.name in TILING_OWNERS:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Mod)
                    and not (isinstance(node.left, ast.Constant)
                             and isinstance(node.left.value, str))):
                errors.append(
                    f"ops/{path.name}:{node.lineno}: inline '%' "
                    "remainder math — block feasibility/selection "
                    "lives in ops/tiling.py (+ measured winners in "
                    "ops/autotune.py)"
                )
            if (isinstance(node,
                           (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in LEGACY_PICKERS):
                errors.append(
                    f"ops/{path.name}:{node.lineno}: defines "
                    f"{node.name}() — a private block picker grew "
                    "back; extend ops/tiling.py instead"
                )
        calls_pallas = any(
            isinstance(n, ast.Call) and call_name(n) == "pallas_call"
            for n in ast.walk(tree)
        )
        if not calls_pallas:
            continue
        names = {
            n.attr if isinstance(n, ast.Attribute) else
            getattr(n, "id", "")
            for n in ast.walk(tree)
            if isinstance(n, (ast.Attribute, ast.Name))
        }
        if not names & {"tiling", "autotune"}:
            errors.append(
                f"ops/{path.name}: calls pallas_call() but never "
                "consults ops.tiling/ops.autotune — its block "
                "configs come from somewhere private"
            )


def check_megastep_readback(errors: list) -> None:
    """The megastep driver functions may not read device values
    except through the single ``megastep_readback()`` call — one
    blocking host sync per K-step chunk, at the designated site.
    (``float()``/``bool()`` on the ALREADY-read-back host dict are
    fine and not flagged; ``device_get``/``block_until_ready``/
    ``.item()``/``.tolist()``/``asarray`` inside a driver are not.)"""
    tree = ast.parse(CORE.read_text(), filename=str(CORE))
    drivers = {
        n.name: n for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in MEGASTEP_DRIVERS
    }
    for fn in sorted(MEGASTEP_DRIVERS - set(drivers)):
        errors.append(
            f"core.py: megastep driver {fn}() not found — the "
            "readback-site lint has nothing to protect"
        )
    readback_calls = []
    for fn_name, fn in drivers.items():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn == "megastep_readback":
                readback_calls.append((fn_name, node.lineno))
            elif cn in MEGASTEP_FORBIDDEN:
                errors.append(
                    f"core.py:{node.lineno}: {fn_name}() calls "
                    f"{cn}() — megastep drivers must not touch the "
                    "device outside the single megastep_readback() "
                    "site (one host sync per chunk)"
                )
    if drivers and len(readback_calls) != 1:
        sites = ", ".join(
            f"{f}:{ln}" for f, ln in readback_calls) or "none"
        errors.append(
            "core.py: expected exactly ONE megastep_readback() call "
            f"across the megastep drivers, found {len(readback_calls)}"
            f" ({sites}) — the per-chunk readback has one designated "
            "site in run_megastep_chunk()"
        )
    elif readback_calls and readback_calls[0][0] != "run_megastep_chunk":
        errors.append(
            f"core.py:{readback_calls[0][1]}: the megastep_readback() "
            "site moved out of run_megastep_chunk() — keep the "
            "designated readback in the chunk driver"
        )


def check_embedding_locality(errors: list) -> None:
    """Collective-locality rules for the sharded embeddings subsystem.

    1. Raw cross-device collectives (``FORBIDDEN_COLLECTIVES``) may be
       CALLED only under ``parallel/``, in ``nn/core.py``, or in
       ``embeddings/table.py`` — the subsystem's one designated
       collective site. A workload module (``embeddings/word2vec.py``,
       ``embeddings/deepwalk.py``) growing its own psum has re-inlined
       the exchange the table owns; anywhere else it has re-inlined a
       distribution concern (same rationale as the engine rule above).
    2. ``segment_sum`` — the sparse scatter-add primitive — may be
       called only in ``embeddings/`` + ``nn/core.py``: a layer or
       workload summing duplicate-id gradients itself bypasses the
       dedup contract (PAD_ID padding, sorted-order determinism) the
       cross-mesh bitwise tests pin down in ``embeddings/sparse.py``.
    3. ``embeddings/table.py`` must consult the shard_map machinery it
       claims to ride (``shard_map_compat`` from ``parallel/compat``)
       — a raw ``jax.shard_map``/``Mesh`` context grown there would
       bypass the version-compat shim every other mesh program uses.
    """
    pkg = REPO / "deeplearning4j_tpu"
    emb_dir = pkg / "embeddings"
    table_py = emb_dir / "table.py"
    collective_ok = lambda p: (  # noqa: E731
        (pkg / "parallel") in p.parents
        or p == CORE
        or p == table_py
    )
    for path in sorted(pkg.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn in FORBIDDEN_COLLECTIVES and not collective_ok(path):
                errors.append(
                    f"{path.relative_to(REPO)}:{node.lineno}: calls "
                    f"{cn}() — raw collectives live only in parallel/, "
                    "nn/core.py, and embeddings/table.py (the "
                    "subsystem's designated collective site)"
                )
            if cn == "segment_sum" and not (
                emb_dir in path.parents or path == CORE
            ):
                errors.append(
                    f"{path.relative_to(REPO)}:{node.lineno}: calls "
                    "segment_sum() — sparse scatter-add dedup lives in "
                    "embeddings/ (+ nn/core.py); use "
                    "embeddings.sparse.dedup_segment_sum"
                )
    if table_py.exists():
        tree = ast.parse(table_py.read_text(), filename=str(table_py))
        names = {
            n.attr if isinstance(n, ast.Attribute) else
            getattr(n, "id", "")
            for n in ast.walk(tree)
            if isinstance(n, (ast.Attribute, ast.Name))
        }
        if "shard_map_compat" not in names:
            errors.append(
                "embeddings/table.py: never consults "
                "shard_map_compat() — mesh programs ride the "
                "parallel/compat shim, not a raw shard_map"
            )
    else:
        errors.append(
            "embeddings/table.py: missing — the collective-locality "
            "rule has nothing to protect"
        )


def check_core(errors: list) -> None:
    tree = ast.parse(CORE.read_text(), filename=str(CORE))
    defined = {
        n.name for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for fn in sorted(CORE_REQUIRED - defined):
        errors.append(
            f"core.py: missing shared implementation {fn}() — the "
            "engines have nothing to delegate to"
        )


def main() -> int:
    errors: list = []
    check_core(errors)
    check_megastep_readback(errors)
    for name, path in ENGINES.items():
        check_engine(name, path, errors)
    check_pallas_locality(errors)
    check_tiling_locality(errors)
    check_embedding_locality(errors)
    if errors:
        print("engine/core parity violations:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print(
        "lint_parity: both engines delegate step/apply/fit hot paths "
        "to nn/core.py; Pallas kernels stay in ops/ behind dispatch; "
        "block selection stays in ops/tiling.py + ops/autotune.py; "
        "megastep drivers keep one readback site; embedding "
        "collectives stay in embeddings/table.py"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
