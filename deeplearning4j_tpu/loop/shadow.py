"""Shadow scoring: mirror sampled live traffic to a candidate model.

The serving tier calls ``observe(features, live_output, live_ms)``
after every successful live forward (``ModelServer.set_shadow``). The
scorer — on a seeded Bernoulli sample of those calls — runs the SAME
rows through the candidate model over the SAME padded bucketed path
the live model used, and accumulates:

- **agreement**: fraction of rows whose candidate output matches the
  live output (argmax for 2-d classification outputs, allclose
  otherwise) — the primary promotion gate;
- **latency**: candidate forward ms vs the live forward ms it
  shadowed, so a candidate that is quality-equal but 3x slower is
  gated on the p99 delta;
- **health**: candidate exceptions and non-finite candidate outputs
  (either fails a zero-tolerance gate), plus non-finite LIVE outputs
  (the probation-mode regression signal, see below);
- **samples**: a bounded ring of recently shadowed feature rows — the
  promoter's probation probes replay these against a suspect version.

Candidate outputs are never returned to clients: ``observe`` runs
*after* the live responses complete, never raises, and a candidate
fault only increments ``shadow_error_total``.

The same class runs promotion **probation** in reverse: after a swap,
the *previous* version becomes the shadow of the new live traffic —
continued agreement and finite live outputs are the evidence the
promotion holds; their collapse (e.g. a distribution shift the
candidate cannot handle) triggers auto-rollback.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


def _finite(a: np.ndarray) -> bool:
    return bool(np.all(np.isfinite(a)))


def agreement_rows(live: np.ndarray, cand: np.ndarray,
                   tol: float = 1e-4) -> "tuple[int, int]":
    """(agreeing rows, total rows) between two output arrays of equal
    leading dimension: argmax equality for 2-d outputs with >1
    column (classification), elementwise closeness otherwise."""
    live = np.asarray(live)
    cand = np.asarray(cand)
    if live.ndim == 1:
        live = live[None, :]
    if cand.ndim == 1:
        cand = cand[None, :]
    rows = int(min(live.shape[0], cand.shape[0]))
    if rows == 0:
        return 0, 0
    live, cand = live[:rows], cand[:rows]
    if live.ndim == 2 and live.shape[1] > 1:
        agree = int(np.sum(
            np.argmax(live, axis=1) == np.argmax(cand, axis=1)
        ))
    else:
        flat_axis = tuple(range(1, live.ndim))
        agree = int(np.sum(np.all(
            np.isclose(live, cand, rtol=tol, atol=tol), axis=flat_axis,
        )))
    return agree, rows


class ShadowScorer:
    """Mirror a sampled fraction of live traffic to ``candidate``.

    ``fraction`` is the Bernoulli mirror probability from a private
    ``random.Random(seed)`` — the same seed mirrors the same requests,
    so chaos runs replay bit-for-bit. ``ladder`` (a serving
    ``BucketLadder``) routes candidate forwards through the same
    padded buckets live traffic uses; without one the candidate runs
    the raw shape.
    """

    def __init__(self, candidate, *, fraction: float = 1.0,
                 seed: int = 0, ladder=None, registry=None,
                 sample_ring: int = 64, tol: float = 1e-4,
                 name: str = "candidate"):
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        self.candidate = candidate
        self.fraction = fraction
        self.ladder = ladder
        self.tol = tol
        self.name = name
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # counters kept as plain ints under the lock (exact even with
        # a disabled registry — gates read these, not the exporter)
        self.requests = 0        # observe() calls offered
        self.shadowed = 0        # mirrored to the candidate
        self.rows = 0
        self.agree_rows = 0
        self.errors = 0          # candidate raised or went non-finite
        self.live_nonfinite = 0  # LIVE output non-finite (probation)
        self._cand_ms: list = []
        self._live_ms: list = []
        self._samples: list = []
        self._sample_ring = max(int(sample_ring), 1)
        reg = registry
        if reg is not None:
            self._m_predicts = reg.counter(
                "shadow_predicts_total",
                help="loop: live requests mirrored to the shadow model",
            )._default()
            self._m_errors = reg.counter(
                "shadow_error_total",
                help="loop: shadow forwards that raised or produced "
                     "non-finite output",
            )._default()
            self._m_live_nonfinite = reg.counter(
                "shadow_live_nonfinite_total",
                help="loop: LIVE outputs observed non-finite while "
                     "shadowing (probation regression signal)",
            )._default()
            self._m_agreement = reg.gauge(
                "shadow_agreement",
                help="loop: row agreement between live and shadow "
                     "outputs (argmax / allclose), running fraction",
            )._default()
            self._m_latency = reg.summary(
                "shadow_latency_ms",
                help="loop: shadow-model forward latency",
            )._default()
        else:
            self._m_predicts = self._m_errors = None
            self._m_live_nonfinite = self._m_agreement = None
            self._m_latency = None

    # -- the mirror (called from serving worker threads) ----------------

    def observe(self, features, live_output, live_ms:
                Optional[float] = None) -> None:
        """One successful live forward: maybe mirror it. NEVER raises
        and never touches the live response — a shadow fault is a
        counter, not an error."""
        try:
            self._observe(features, live_output, live_ms)
        except Exception:  # belt and braces: the live path is sacred
            logger.exception("shadow observe failed (ignored)")
            with self._lock:
                self.errors += 1
            if self._m_errors is not None:
                self._m_errors.inc()

    def _observe(self, features, live_output, live_ms) -> None:
        with self._lock:
            self.requests += 1
            mirror = self._rng.random() < self.fraction
        live_out = np.asarray(live_output)
        if not _finite(live_out):
            with self._lock:
                self.live_nonfinite += 1
            if self._m_live_nonfinite is not None:
                self._m_live_nonfinite.inc()
        if not mirror:
            return
        feats = np.asarray(features, np.float32)
        if feats.ndim == 1:
            feats = feats[None, :]
        t0 = time.perf_counter()
        try:
            out = self._forward(feats)
        except Exception:
            logger.warning("shadow model %r raised on mirrored "
                           "traffic", self.name, exc_info=True)
            with self._lock:
                self.shadowed += 1
                self.errors += 1
            if self._m_predicts is not None:
                self._m_predicts.inc()
                self._m_errors.inc()
            return
        ms = (time.perf_counter() - t0) * 1000.0
        bad = not _finite(out)
        agree, rows = (0, int(feats.shape[0])) if bad else \
            agreement_rows(live_out, out, self.tol)
        with self._lock:
            self.shadowed += 1
            self.rows += rows
            self.agree_rows += agree
            if bad:
                self.errors += 1
            self._cand_ms.append(ms)
            if live_ms is not None:
                self._live_ms.append(float(live_ms))
            if len(self._cand_ms) > 4096:
                del self._cand_ms[:2048]
                del self._live_ms[:2048]
            for row in feats[:4]:  # bounded ring of live samples
                self._samples.append(np.array(row, np.float32))
            if len(self._samples) > self._sample_ring:
                del self._samples[:len(self._samples)
                                  - self._sample_ring]
            agreement = (self.agree_rows / self.rows
                         if self.rows else None)
        if self._m_predicts is not None:
            self._m_predicts.inc()
            if bad:
                self._m_errors.inc()
            self._m_latency.observe(ms)
            if agreement is not None:
                self._m_agreement.set(agreement)

    def _forward(self, feats: np.ndarray) -> np.ndarray:
        """Candidate forward over the same padded bucketed path live
        traffic uses (``output_padded`` on the bucket that fits), raw
        shape otherwise."""
        from deeplearning4j_tpu.serving.batcher import pad_rows

        model = self.candidate
        rows = int(feats.shape[0])
        fn = getattr(model, "output_padded", None)
        bucket = self.ladder.bucket_for(rows) if self.ladder else None
        if fn is not None and bucket is not None:
            out = fn(pad_rows(feats, bucket), n_valid=rows)
        elif fn is not None:
            out = fn(feats, n_valid=rows)
        else:
            out = model.output(feats)
        out = out[0] if isinstance(out, (list, tuple)) else out
        return np.asarray(out)[:rows]

    def warmup(self, features) -> bool:
        """Compile the candidate's bucket for ``features`` OFF the
        serving worker threads (the promoter calls this at shadow
        install). Returns False when the forward fails — the caller
        treats that like a failed canary."""
        try:
            feats = np.asarray(features, np.float32)
            if feats.ndim == 1:
                feats = feats[None, :]
            out = self._forward(feats)
            return _finite(out)
        except Exception:
            logger.warning("shadow warmup failed for %r", self.name,
                           exc_info=True)
            return False

    # -- gate inputs ----------------------------------------------------

    @staticmethod
    def _p99(values: list) -> Optional[float]:
        if not values:
            return None
        s = sorted(values)
        return float(s[min(len(s) - 1, int(0.99 * len(s)))])

    def snapshot(self) -> dict:
        """The gate-evaluation view: counts, agreement, p99s."""
        with self._lock:
            cand_p99 = self._p99(self._cand_ms)
            live_p99 = self._p99(self._live_ms)
            return {
                "name": self.name,
                "requests": self.requests,
                "shadowed": self.shadowed,
                "rows": self.rows,
                "agree_rows": self.agree_rows,
                "agreement": (self.agree_rows / self.rows
                              if self.rows else None),
                "errors": self.errors,
                "live_nonfinite": self.live_nonfinite,
                "candidate_p99_ms": cand_p99,
                "live_p99_ms": live_p99,
                "p99_delta_ms": (
                    cand_p99 - live_p99
                    if cand_p99 is not None and live_p99 is not None
                    else None
                ),
            }

    def samples(self) -> np.ndarray:
        """Recently shadowed live feature rows (the probation probe
        replay set); empty array when nothing was mirrored yet."""
        with self._lock:
            if not self._samples:
                return np.zeros((0, 0), np.float32)
            return np.stack(self._samples)
