"""The continuous-learning loop: train → checkpoint → shadow →
canary → promote, with crash-safe promotion and auto-rollback.

Every piece existed — ``CheckpointManager`` (atomic versioned
checkpoints + AOT bundles), the serving tier's canary-validated hot
reload and immutable version snapshots, the breaker, the metrics
registry, the prefetching trainer — but nothing closed the loop, and
nothing could *undo* a bad model once it took traffic. This package
closes it:

- ``trainer.py`` — ``ContinualTrainer``: fit a streaming iterator
  incrementally, publish a versioned checkpoint (AOT bundle
  attached) every N steps, resume bitwise from a mid-epoch kill;
- ``shadow.py`` — ``ShadowScorer``: mirror a seeded fraction of live
  traffic to a candidate over the same padded bucketed path, results
  never returned to clients; accumulate agreement / latency-delta /
  health evidence;
- ``promoter.py`` — ``Promoter`` + ``PromotionGates``: the
  candidate → shadowing → canarying → promoted | rolled_back state
  machine, every transition journaled before its side effects;
  rollback re-installs the previous version's retained snapshot with
  zero XLA compiles and zero dropped in-flight requests;
- ``journal.py`` — ``PromotionJournal``: one atomic on-disk JSON
  document; a SIGKILLed promoter resumes exactly where it died, and
  a half-applied promotion is always rolled forward or back — never
  left split. The journal's referenced steps are retention-protected
  in the checkpoint store.

End-to-end demo: ``scripts/run_loop.py`` (train → publish → shadow →
promote → inject regression → auto-rollback, JSON verdict). Chaos
storms: ``tests/test_loop.py`` via ``scripts/run_chaos.sh``.
"""

from deeplearning4j_tpu.loop.journal import (  # noqa: F401
    CANARYING,
    IDLE,
    PROMOTED,
    PromotionJournal,
    QUARANTINED,
    ROLLED_BACK,
    SHADOWING,
    SimulatedKill,
    STATE_CODES,
)
from deeplearning4j_tpu.loop.promoter import (  # noqa: F401
    Promoter,
    PromotionGates,
)
from deeplearning4j_tpu.loop.shadow import (  # noqa: F401
    ShadowScorer,
    agreement_rows,
)
from deeplearning4j_tpu.loop.trainer import (  # noqa: F401
    ContinualTrainer,
)
