"""The promoter: candidate → shadowing → canarying → promoted |
rolled_back, crash-safe at every arrow.

The promoter polls the checkpoint store for versions newer than what
is serving, and walks each candidate through an explicit state
machine journaled to disk (``journal.py``) *before* every side
effect:

1. **candidate**: a new step appeared. Its checkpoint is CRC-verified
   first — a corrupt candidate is *quarantined* (journaled, skipped
   forever, counted) while the live version keeps serving.
2. **shadowing**: the candidate model is restored (its AOT bundle
   installed, so shadow forwards deserialize rather than compile) and
   installed as the server's shadow: a seeded fraction of live
   traffic mirrors through the same padded bucketed path, results
   never returned to clients (``shadow.py``).
3. gates (``PromotionGates``): once ``min_shadow_requests`` have
   mirrored, the candidate must clear row agreement, shadow error
   count, the p99 latency delta, and the divergence-guard trip budget.
   Failure journals ``rolled_back`` (reason recorded), counts
   ``loop_rejected_total``, and the live version never changed.
4. **canarying**: gates passed; the journal records the intent, THEN
   the existing canary-validated hot reload swaps the candidate in
   (``reload({"step": N})`` — idempotent, so a crash between journal
   and swap rolls *forward* on recovery by just re-issuing it).
5. **promoted (probation)**: the previous ``ModelVersion`` snapshot is
   retained and becomes the shadow of the new live traffic (the same
   scorer, reversed). For ``probation_requests`` observations the new
   version must keep agreeing with its predecessor and producing
   finite outputs, and the server's error rate must stay under the
   gate — a regression triggers **rollback**: the previous snapshot
   (model object, warmed shapes record, AOT-installed executables and
   all) is swapped back atomically. Zero XLA compiles, zero dropped
   in-flight requests (in-flight work finishes on the version it
   started with — the same invariant hot reload always had).
6. **promoted (final)**: probation passed; the previous snapshot is
   released and the journal seals the promotion.

``recover()`` makes the machine SIGKILL-proof: whatever state the
journal shows, recovery either rolls the half-applied transition
forward (gates had passed → finish the swap) or back (re-enter
shadowing / restore the promoted version), and re-establishes the
serving invariant "the server serves the journal's promoted step (or
newer under evaluation)". ``fail_after_journal`` is the chaos hook:
set it to a state name and the promoter raises ``SimulatedKill``
right after that journal write — the worst instant — which the chaos
storms use to prove convergence.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.loop.journal import (
    CANARYING,
    CANDIDATE,
    IDLE,
    PROMOTED,
    PromotionJournal,
    QUARANTINED,
    ROLLED_BACK,
    SHADOWING,
    SimulatedKill,
    state_code,
)
from deeplearning4j_tpu.loop.shadow import ShadowScorer, agreement_rows

logger = logging.getLogger(__name__)


@dataclass
class PromotionGates:
    """The configurable promotion/rollback thresholds.

    - ``min_shadow_requests``: mirrored forwards required before the
      shadow gates are judged (and before probation is judged).
    - ``min_agreement``: required live/shadow row agreement.
    - ``max_shadow_errors``: shadow forwards that raised or went
      non-finite; the default 0 means one bad forward kills the
      candidate.
    - ``max_p99_delta_ms``: candidate forward p99 minus live forward
      p99 (None disables the latency gate).
    - ``max_divergence_trips``: divergence-guard trips (skips +
      rollbacks) the training run may have accumulated for the
      candidate to stay eligible (None disables; needs a
      ``trip_source``).
    - ``max_error_rate``: server 5xx per prediction during probation;
      above it the promotion rolls back.
    - ``probation_requests``: shadowed observations the new version
      must survive before the promotion seals.
    - ``probation_min_agreement``: required agreement between the new
      live version and its predecessor during probation (None = same
      as ``min_agreement``). A candidate legitimately *improves* on
      its predecessor, so this is usually looser than the shadow
      gate; its collapse — e.g. under a traffic shift the candidate
      cannot handle — is the regression signal.
    """

    min_shadow_requests: int = 8
    min_agreement: float = 0.98
    max_shadow_errors: int = 0
    max_p99_delta_ms: Optional[float] = None
    max_divergence_trips: Optional[int] = None
    max_error_rate: float = 0.0
    probation_requests: int = 8
    probation_min_agreement: Optional[float] = None
    # a promotion may not seal before BOTH the observation count and
    # this dwell have elapsed — regressions (traffic shifts, slow
    # poisoning) take wall-clock time to manifest, and a fast traffic
    # burst must not close the watch window in milliseconds
    probation_min_seconds: float = 0.0


class Promoter:
    """Drive the promotion state machine for one ``ModelServer``
    (default tenant) against one ``CheckpointManager``.

    ``trip_source`` is an optional callable returning the training
    side's cumulative divergence-guard trip count (gates on the
    delta since the last candidate). ``poll()`` advances the machine
    one turn and returns the journal state; ``run(interval)`` polls
    on a daemon thread with ``stop()`` to cancel.
    """

    def __init__(self, server, manager, journal: PromotionJournal, *,
                 gates: Optional[PromotionGates] = None,
                 shadow_fraction: float = 1.0, seed: int = 0,
                 trip_source: Optional[Callable[[], int]] = None,
                 registry=None):
        self.server = server
        self.manager = manager
        self.journal = journal
        self.gates = gates or PromotionGates()
        self.shadow_fraction = shadow_fraction
        self.seed = seed
        self.trip_source = trip_source
        # retention contract on the promoter's manager instance too
        # (the trainer process guards its own via ContinualTrainer)
        manager.protect = journal.referenced_steps
        self._scorer: Optional[ShadowScorer] = None
        self._prev_snapshot = None     # ModelVersion before the swap
        self._trips_at_candidate = 0
        self._errors_at_promote = 0
        self._predictions_at_promote = 0
        self._promoted_at = 0.0
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # chaos hook: journal state name -> raise SimulatedKill right
        # after that journal write lands on disk
        self.fail_after_journal: Optional[str] = None

        reg = registry if registry is not None \
            else server.metrics.registry
        self.registry = reg
        self._m_promotions = reg.counter(
            "loop_promotions_total",
            help="loop: candidates promoted to serving",
        )._default()
        self._m_rollbacks = reg.counter(
            "loop_rollbacks_total",
            help="loop: promotions rolled back to the previous "
                 "version's snapshot",
        )._default()
        self._m_rejected = reg.counter(
            "loop_rejected_total",
            help="loop: candidates rejected before taking traffic "
                 "(shadow gates or canary)",
        )._default()
        self._m_quarantined = reg.counter(
            "loop_quarantined_total",
            help="loop: candidate checkpoints quarantined (failed "
                 "CRC/zip verification)",
        )._default()
        self._m_recoveries = reg.counter(
            "loop_journal_recoveries_total",
            help="loop: promoter restarts that resumed from the "
                 "journal",
        )._default()
        self._m_state = reg.gauge(
            "loop_state",
            help="loop: promoter state (0 idle, 1 candidate, "
                 "2 shadowing, 3 canarying, 4 promoted, "
                 "5 rolled_back, 6 quarantined)",
        )._default()
        self._m_candidate_step = reg.gauge(
            "loop_candidate_step",
            help="loop: checkpoint step under evaluation",
        )._default()
        self._m_promoted_step = reg.gauge(
            "loop_promoted_step",
            help="loop: last fully promoted checkpoint step",
        )._default()
        self._publish_state(self.journal.read())

    # -- journal plumbing -----------------------------------------------

    def _write(self, state: str, **fields) -> dict:
        doc = self.journal.write(state, **fields)
        self._publish_state(doc)
        if self.fail_after_journal == state:
            raise SimulatedKill(
                f"chaos: killed right after journaling {state!r}"
            )
        return doc

    def _publish_state(self, doc: dict) -> None:
        self._m_state.set(state_code(doc.get("state")))
        if doc.get("candidate_step") is not None:
            self._m_candidate_step.set(doc["candidate_step"])
        if doc.get("promoted_step") is not None:
            self._m_promoted_step.set(doc["promoted_step"])

    @property
    def state(self) -> str:
        return self.journal.state

    # -- one machine turn -----------------------------------------------

    def poll(self) -> str:
        """Advance the state machine one turn. Safe to call from a
        timer thread; every turn is idempotent w.r.t. the journal."""
        with self._lock:
            doc = self.journal.read()
            st = doc.get("state", IDLE)
            if st == SHADOWING:
                self._evaluate_shadow(doc)
            elif st == CANARYING:
                self._do_promote(doc)
            elif st == PROMOTED and doc.get("probation"):
                self._evaluate_probation(doc)
            else:
                self._check_for_candidate(doc)
            return self.journal.state

    # -- candidate discovery --------------------------------------------

    def _live_step(self) -> Optional[int]:
        return self.server._watched_step

    def _check_for_candidate(self, doc: dict) -> None:
        latest = self.manager.latest_step()
        if latest is None:
            return
        live = self._live_step()
        skip = set(self.journal.skip_steps())
        if doc.get("promoted_step") is not None:
            skip.add(doc["promoted_step"])
        if live is not None:
            skip.add(live)
        candidates = [s for s in self.manager.list_steps()
                      if s not in skip
                      and (live is None or s > live)]
        if not candidates:
            return
        step = candidates[-1]  # newest eligible version
        info = next((i for i in self.manager.available()
                     if i.step == step), None)
        if info is None:
            return
        if not self.manager.verify(info):
            # corrupt candidate: quarantine it, keep serving
            logger.warning(
                "candidate step %d failed verification; quarantined "
                "(live version keeps serving)", step,
            )
            self._m_quarantined.inc()
            self._write(QUARANTINED, quarantined_steps=[step],
                        reason=f"step {step} failed CRC/zip "
                               "verification")
            return
        if self.trip_source is not None:
            self._trips_at_candidate = int(self.trip_source())
        try:
            candidate = self.manager.restore(info, load_updater=False)
        except Exception:
            logger.warning("candidate step %d failed to restore; "
                           "quarantined", step, exc_info=True)
            self._m_quarantined.inc()
            self._write(QUARANTINED, quarantined_steps=[step],
                        reason=f"step {step} failed to restore")
            return
        self._install_candidate_aot(candidate, info)
        scorer = ShadowScorer(
            candidate, fraction=self.shadow_fraction,
            seed=self.seed ^ step, ladder=self._ladder(),
            registry=self.registry, name=f"candidate-{step}",
        )
        # compile the canary bucket off the worker threads; a
        # candidate that cannot even forward is rejected here
        feats = self.server._canary_features(candidate)
        if feats is not None and not scorer.warmup(feats):
            self._m_rejected.inc()
            self._write(ROLLED_BACK, candidate_step=step,
                        rejected_steps=[step],
                        reason="candidate failed shadow warmup")
            return
        self._scorer = scorer
        self.server.set_shadow(scorer)
        self._write(SHADOWING, candidate_step=step,
                    previous_step=live, gates_passed=False,
                    probation=False, reason=None)
        logger.info("shadowing candidate step %d against live step "
                    "%s", step, live)

    def _install_candidate_aot(self, candidate, info) -> None:
        """Best-effort: install the candidate's bundled executables so
        shadow forwards (and the later canary/warmup) deserialize
        instead of compiling."""
        if (not getattr(self.server, "aot", False)
                or getattr(candidate, "aot_install_output", None)
                is None):
            return
        try:
            blobs = self.manager.load_artifacts(info)
            if blobs:
                from deeplearning4j_tpu.compile.aot import (
                    install_serving_bundle,
                )

                install_serving_bundle(candidate, blobs,
                                       registry=self.registry)
        except Exception:
            logger.warning("candidate AOT install failed; shadow "
                           "will JIT", exc_info=True)

    def _ladder(self):
        batcher = getattr(self.server, "batcher", None)
        return batcher.ladder if batcher is not None else None

    # -- shadow gates ---------------------------------------------------

    def _gate_failures(self, snap: dict) -> "list[str]":
        g = self.gates
        fails = []
        agreement = snap.get("agreement")
        if agreement is None or agreement < g.min_agreement:
            fails.append(
                f"agreement {agreement if agreement is None else round(agreement, 4)}"
                f" < {g.min_agreement}"
            )
        if snap.get("errors", 0) > g.max_shadow_errors:
            fails.append(f"shadow errors {snap['errors']} > "
                         f"{g.max_shadow_errors}")
        if g.max_p99_delta_ms is not None:
            delta = snap.get("p99_delta_ms")
            if delta is not None and delta > g.max_p99_delta_ms:
                fails.append(f"p99 delta {delta:.2f}ms > "
                             f"{g.max_p99_delta_ms}ms")
        if (g.max_divergence_trips is not None
                and self.trip_source is not None):
            trips = int(self.trip_source()) - self._trips_at_candidate
            if trips > g.max_divergence_trips:
                fails.append(f"divergence trips {trips} > "
                             f"{g.max_divergence_trips}")
        return fails

    def _evaluate_shadow(self, doc: dict) -> None:
        scorer = self._scorer
        if scorer is None:
            # promoter restarted mid-shadow (recover() re-enters);
            # defensive: restart the candidate flow
            self._write(IDLE, reason="shadow lost; re-entering")
            return
        snap = scorer.snapshot()
        step = doc.get("candidate_step")
        if snap["shadowed"] < self.gates.min_shadow_requests:
            return  # keep mirroring
        fails = self._gate_failures(snap)
        if fails:
            self.server.set_shadow(None)
            self._scorer = None
            self._m_rejected.inc()
            logger.info("candidate step %s rejected: %s", step,
                        "; ".join(fails))
            self._write(ROLLED_BACK, rejected_steps=[step],
                        gates_passed=False,
                        reason="; ".join(fails))
            return
        logger.info("candidate step %s cleared shadow gates "
                    "(agreement %.4f over %d rows)", step,
                    snap["agreement"], snap["rows"])
        self._write(CANARYING, gates_passed=True)
        self._do_promote(self.journal.read())

    # -- the swap -------------------------------------------------------

    def _do_promote(self, doc: dict) -> None:
        step = doc.get("candidate_step")
        if step is None:
            self._write(IDLE, reason="canarying without a candidate")
            return
        entry = self.server.model_registry.entry()
        prev = entry.current
        code, body = self.server.reload({"step": step})
        self.server.set_shadow(None)
        if code != 200:
            # canary (or restore) failed: live version untouched
            self._scorer = None
            self._m_rejected.inc()
            logger.warning("candidate step %d failed promotion "
                           "reload (%s); live version keeps serving",
                           step, body.get("error", {}).get("status"))
            self._write(ROLLED_BACK, rejected_steps=[step],
                        reason=f"canary/reload failed "
                               f"({body.get('error', {}).get('status')})")
            return
        # keep the PREVIOUS snapshot (model object, warmed shapes,
        # installed executables) — rollback re-installs it with zero
        # compiles and zero dropped requests
        self._prev_snapshot = prev
        self._errors_at_promote = self.server.metrics.get(
            "server_error_total")
        self._predictions_at_promote = self.server.metrics.get(
            "predictions_total")
        self._promoted_at = time.monotonic()
        self._m_promotions.inc()
        probation = self.gates.probation_requests > 0
        if probation:
            prev_model = prev.model
            scorer = ShadowScorer(
                prev_model, fraction=self.shadow_fraction,
                seed=self.seed ^ step ^ 0xA5A5,
                ladder=self._ladder(), registry=self.registry,
                name=f"probation-prev-{doc.get('previous_step')}",
            )
            self._scorer = scorer
            self.server.set_shadow(scorer)
        else:
            self._scorer = None
        self._write(PROMOTED, promoted_step=step,
                    probation=probation, reason=None)
        logger.info("promoted candidate step %d (%s)", step,
                    "probation" if probation else "final")
        if not probation:
            self._prev_snapshot = None

    # -- probation ------------------------------------------------------

    def _error_rate_since_promote(self) -> float:
        errs = self.server.metrics.get("server_error_total") \
            - self._errors_at_promote
        preds = self.server.metrics.get("predictions_total") \
            - self._predictions_at_promote
        return errs / max(preds + errs, 1)

    def _probation_failures(self, snap: dict) -> "list[str]":
        g = self.gates
        fails = []
        # the reversed shadow: the previous version scores the NEW
        # live outputs — collapse in agreement or finiteness is the
        # regression signal
        floor = (g.probation_min_agreement
                 if g.probation_min_agreement is not None
                 else g.min_agreement)
        agreement = snap.get("agreement")
        if agreement is not None and agreement < floor:
            fails.append(f"probation agreement {agreement:.4f} < "
                         f"{floor}")
        if snap.get("live_nonfinite", 0) > 0:
            fails.append(f"live outputs non-finite x"
                         f"{snap['live_nonfinite']}")
        rate = self._error_rate_since_promote()
        if rate > g.max_error_rate:
            fails.append(f"error rate {rate:.4f} > {g.max_error_rate}")
        from deeplearning4j_tpu.resilience.breaker import OPEN

        if self.server.breaker.state == OPEN:
            fails.append("predict breaker open")
        return fails

    def _evaluate_probation(self, doc: dict) -> None:
        scorer = self._scorer
        if scorer is None or self._prev_snapshot is None:
            # recovered process: recover() re-arms probation; if it
            # could not, seal the promotion (nothing to roll back TO)
            self._write(PROMOTED, probation=False,
                        reason="probation unarmed after recovery")
            return
        snap = scorer.snapshot()
        fails = self._probation_failures(snap)
        if fails:
            self._rollback(doc, "; ".join(fails))
            return
        if snap["shadowed"] < self.gates.probation_requests:
            return  # keep watching
        if (time.monotonic() - self._promoted_at
                < self.gates.probation_min_seconds):
            return  # count met, dwell not: keep watching
        self.server.set_shadow(None)
        self._scorer = None
        self._prev_snapshot = None
        self._write(PROMOTED, probation=False, reason=None)
        logger.info("promotion of step %s sealed (probation passed)",
                    doc.get("promoted_step"))

    def _rollback(self, doc: dict, reason: str) -> None:
        """Re-install the previous version's snapshot atomically: the
        retained ``ModelVersion`` still carries its jitted/AOT
        executables and warmed shape record, so the swap performs
        zero XLA compiles, and in-flight requests finish on the
        version they started with (workers snapshot the reference at
        predict start — the hot-reload invariant)."""
        step = doc.get("promoted_step")
        prev = self._prev_snapshot
        self.server.set_shadow(None)
        self._scorer = None
        with self.server._model_lock:
            entry = self.server.model_registry.entry()
            self.server.model_registry.swap(entry, prev)
        # the bad candidate's step stays "handled": the reload
        # idempotence skip and check_for_update must not re-promote it
        self.server._watched_step = doc.get("previous_step")
        self._prev_snapshot = None
        self._m_rollbacks.inc()
        logger.warning("rolled back promotion of step %s: %s", step,
                       reason)
        self._write(ROLLED_BACK, rejected_steps=[step],
                    promoted_step=doc.get("previous_step"),
                    probation=False, reason=reason)

    # -- crash recovery -------------------------------------------------

    def recover(self) -> str:
        """Resume from whatever the journal shows — called once when a
        promoter (re)starts. Every half-applied transition is rolled
        forward or back; on return the server serves a version
        consistent with the journal."""
        with self._lock:
            doc = self.journal.read()
            st = doc.get("state", IDLE)
            if st in (CANDIDATE, SHADOWING):
                # the in-memory shadow died with the process: re-enter
                # the candidate flow from scratch (same candidate will
                # be re-discovered and re-shadowed)
                self._m_recoveries.inc()
                self._write(IDLE,
                            reason="recovered mid-shadow; re-entering")
            elif st == CANARYING:
                # gates passed, swap may or may not have landed: roll
                # FORWARD — reload({"step": N}) is an idempotent no-op
                # when the swap already happened
                self._m_recoveries.inc()
                logger.info("recovering a promotion of step %s from "
                            "the journal", doc.get("candidate_step"))
                self._do_promote(doc)
            elif st == PROMOTED and doc.get("probation"):
                # probation was live: re-arm it with the previous
                # version restored from its (retention-protected)
                # checkpoint; when that is impossible, seal
                self._m_recoveries.inc()
                self._recover_probation(doc)
            else:
                self._ensure_serving_consistency(doc)
            return self.journal.state

    def _recover_probation(self, doc: dict) -> None:
        prev_step = doc.get("previous_step")
        info = next((i for i in self.manager.available()
                     if i.step == prev_step), None)
        if info is None or not self.manager.verify(info):
            logger.warning(
                "cannot re-arm probation: previous step %s not "
                "restorable; sealing the promotion", prev_step,
            )
            self._write(PROMOTED, probation=False,
                        reason="probation unarmed after recovery")
            return
        try:
            prev_model = self.manager.restore(info, load_updater=False)
        except Exception:
            self._write(PROMOTED, probation=False,
                        reason="probation unarmed after recovery")
            return
        self._install_candidate_aot(prev_model, info)
        from deeplearning4j_tpu.serving.registry import ModelVersion

        entry = self.server.model_registry.entry()
        self._prev_snapshot = ModelVersion(
            prev_model, entry.current.version,
            f"checkpoint-step-{prev_step}",
            self.server.compile_cache.register(),
        )
        scorer = ShadowScorer(
            prev_model, fraction=self.shadow_fraction,
            seed=self.seed ^ int(doc.get("promoted_step") or 0)
            ^ 0xA5A5,
            ladder=self._ladder(), registry=self.registry,
            name=f"probation-prev-{prev_step}",
        )
        feats = self.server._canary_features(prev_model)
        if feats is not None:
            scorer.warmup(feats)
        self._scorer = scorer
        self.server.set_shadow(scorer)
        self._errors_at_promote = self.server.metrics.get(
            "server_error_total")
        self._predictions_at_promote = self.server.metrics.get(
            "predictions_total")
        self._promoted_at = time.monotonic()  # dwell restarts
        logger.info("re-armed probation of step %s against restored "
                    "previous step %s", doc.get("promoted_step"),
                    prev_step)

    def _ensure_serving_consistency(self, doc: dict) -> None:
        """Steady states: the server must serve the journal's promoted
        step — a fresh boot restores the NEWEST checkpoint, which may
        be an unvetted candidate; demote it back to the promoted
        version so evaluation starts from a consistent base."""
        promoted = doc.get("promoted_step")
        if promoted is None or self._live_step() == promoted:
            return
        code, body = self.server.reload({"step": promoted})
        if code == 200:
            self._m_recoveries.inc()
            logger.info(
                "recovery demoted serving back to promoted step %d "
                "(was %s)", promoted, self._live_step(),
            )
        else:
            logger.warning(
                "recovery could not restore promoted step %s (%s); "
                "serving continues on step %s", promoted, body,
                self._live_step(),
            )

    # -- background polling ---------------------------------------------

    def run(self, interval: float = 0.25) -> "Promoter":
        """Poll on a daemon thread every ``interval`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval):
                try:
                    self.poll()
                except SimulatedKill:
                    raise  # chaos: let the thread die like the process
                except Exception:
                    logger.exception("promoter poll failed")

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="dl4j-loop-promoter",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- introspection --------------------------------------------------

    def snapshot(self) -> dict:
        doc = self.journal.read()
        out = {
            "state": doc.get("state"),
            "candidate_step": doc.get("candidate_step"),
            "previous_step": doc.get("previous_step"),
            "promoted_step": doc.get("promoted_step"),
            "probation": doc.get("probation"),
            "reason": doc.get("reason"),
            "promotions": self._m_promotions.value,
            "rollbacks": self._m_rollbacks.value,
            "rejected": self._m_rejected.value,
            "quarantined": self._m_quarantined.value,
            "journal_recoveries": self._m_recoveries.value,
        }
        if self._scorer is not None:
            out["shadow"] = self._scorer.snapshot()
        return out
