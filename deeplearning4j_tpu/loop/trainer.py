"""Continual training: stream in, versioned checkpoints out.

``ContinualTrainer`` closes the producer half of the continuous-
learning loop: it consumes a streaming ``DataSetIterator`` (or any
iterable of ``DataSet``s) through the existing fit machinery — either
an engine's ``fit_minibatch`` or a ``DistributedTrainer``'s (prefetch
and async dispatch compose exactly as in a batch fit) — and publishes
a versioned checkpoint through ``CheckpointManager`` every
``publish_every`` optimizer steps, with the serving AOT bundle
attached when ``aot_buckets`` is set (``compile.aot.
export_serving_bundle``), so a promotion never pays an XLA compile.

Crash-safety is inherited, not reinvented: checkpoints are atomic +
CRC-manifested, and ``resume()`` restores the newest restorable
version (params, updater state, step counter) so a trainer killed
mid-epoch — prefetch runahead and all — replays the *identical*
trajectory the uninterrupted run would have taken
(``tests/test_resilience.py`` asserts this bitwise, with prefetch and
artifacts attached).

The publish cadence is step-based, not time-based, on purpose: a
resumed trainer re-publishes the same step numbers it would have
published uninterrupted, so the promoter downstream sees one
consistent version line regardless of how many times the trainer
died.
"""

from __future__ import annotations

import logging
from typing import Callable, Iterable, Optional

logger = logging.getLogger(__name__)


class ContinualTrainer:
    """Incrementally fit a model from a stream and publish versioned
    checkpoints.

    ``model`` is a ``MultiLayerNetwork``/``ComputationGraph``;
    ``trainer`` (optional) a ``DistributedTrainer`` wrapping the same
    model — steps then run through its sharded fit path.
    ``artifact_fn`` overrides the AOT exporter (tests inject stub
    blobs; the default exports the serving bundle for
    ``aot_buckets``). ``journal`` (a ``PromotionJournal``) wires the
    retention contract: steps the journal references are never
    pruned.
    """

    def __init__(self, model, manager, *, publish_every: int = 8,
                 trainer=None, aot_buckets=None,
                 artifact_fn: Optional[Callable] = None,
                 feature_shape=None, journal=None, registry=None,
                 validator=None, quarantine=None):
        if publish_every < 1:
            raise ValueError("publish_every must be >= 1")
        self.model = model
        self.manager = manager
        self.trainer = trainer
        # data-plane defense: a datasets.BatchValidator screens every
        # stream batch before it reaches fit; offenders land in the
        # datasets.QuarantineStore and the quarantine ledger rides the
        # published manifests (bitwise kill/resume)
        self.validator = validator
        self.quarantine = quarantine
        self._resume_ledger = None
        self.publish_every = int(publish_every)
        self.aot_buckets = list(aot_buckets) if aot_buckets else None
        self.feature_shape = feature_shape
        self._artifact_fn = artifact_fn
        self.last_published = None  # CheckpointInfo of newest publish
        if journal is not None:
            # retention contract: pruning must never delete a step the
            # promotion journal still references (rollback target!)
            manager.protect = journal.referenced_steps
        if registry is None:
            from deeplearning4j_tpu.observability.metrics import (
                default_registry,
            )

            registry = default_registry()
        self._m_steps = registry.counter(
            "loop_train_steps_total",
            help="loop: optimizer steps consumed from the stream",
        )._default()
        self._m_published = registry.counter(
            "loop_published_total",
            help="loop: versioned checkpoints published",
        )._default()
        self._m_published_step = registry.gauge(
            "loop_published_step",
            help="loop: step of the newest published checkpoint",
        )._default()

    # -- resume ---------------------------------------------------------

    def resume(self, load_updater: bool = True) -> int:
        """Restore the newest restorable checkpoint into the model (or
        through the distributed trainer, which re-places params onto
        its mesh) and return the restored step; 0 when the store is
        empty (fresh start)."""
        if self.manager.latest_step() is None:
            return 0
        target = self.trainer if self.trainer is not None else self.model
        step = target.resume(self.manager,
                             load_updater=load_updater)
        self.last_published = next(
            (i for i in self.manager.available() if i.step == step),
            None,
        )
        # restore_into applied the manifest's guard doc; the data
        # ledger it left on the model tells the next run() how many
        # BASE batches (quarantined ones included) are already handled
        self._resume_ledger = getattr(self.model, "_data_ledger", None)
        logger.info("continual trainer resumed at step %d", step)
        return step

    # -- publish --------------------------------------------------------

    def _artifacts(self) -> Optional[dict]:
        if self._artifact_fn is not None:
            return self._artifact_fn(self.model)
        if not self.aot_buckets:
            return None
        from deeplearning4j_tpu.compile.aot import export_serving_bundle

        return export_serving_bundle(
            self.model, self.aot_buckets,
            feature_shape=self.feature_shape,
        )

    def publish(self, mode: Optional[str] = None) -> "CheckpointInfo":
        """Checkpoint the model at its current step, AOT bundle
        attached. Export failures degrade to a bundle-less publish
        (the consumer then JITs — a lost bundle costs a compile,
        never a version). ``mode`` rides through to
        ``CheckpointManager.save``: with an async manager (or
        ``mode="async"``) the publish is write-behind and this
        returns the :class:`AsyncSaveHandle` (its ``step`` is final;
        the manifest lands in the background)."""
        artifacts = None
        try:
            artifacts = self._artifacts()
        except Exception:
            logger.warning(
                "AOT export failed at step %d; publishing without a "
                "bundle", int(self.model.iteration_count),
                exc_info=True,
            )
        info = self.manager.save(self.model, artifacts=artifacts,
                                 mode=mode)
        self.last_published = info
        self._m_published.inc()
        self._m_published_step.set(info.step)
        logger.info("published checkpoint step %d (%d artifacts)",
                    info.step, len(getattr(info, "artifacts", None)
                                   or {}))
        return info

    def _publish_sync(self) -> "CheckpointInfo":
        """Emergency-path publish: always synchronous, so the
        preemption exit code never promises a checkpoint that a
        background writer has yet to finish."""
        return self.publish(mode="sync")

    # -- the stream loop ------------------------------------------------

    def run(self, stream: Iterable, max_steps: Optional[int] = None,
            publish_trailing: bool = True) -> int:
        """Consume ``stream`` (a ``DataSetIterator`` or any iterable
        of minibatches), fitting one optimizer step per batch and
        publishing every ``publish_every`` steps. Returns the number
        of steps consumed THIS call. ``max_steps`` bounds the call
        (tests and budget-boxed demos); ``publish_trailing`` also
        publishes a final partial window so a drained stream never
        strands unpublished progress."""
        from deeplearning4j_tpu.resilience import preemption

        fit = (self.trainer.fit_minibatch if self.trainer is not None
               else self.model.fit_minibatch)
        vit = None
        if self.validator is not None:
            from deeplearning4j_tpu.datasets.validate import (
                ValidatingIterator,
            )

            if isinstance(stream, ValidatingIterator):
                vit = stream
            else:
                vit = stream = ValidatingIterator(
                    stream, self.validator, quarantine=self.quarantine,
                )
            led = self._resume_ledger
            if led and vit.offset == 0:
                # bitwise resume: the manifest ledger says the first
                # `offset` base batches were already fit/quarantined —
                # re-consume them unvalidated and seed the ledger so
                # published counts keep accumulating, not restarting
                vit.fast_forward(int(led.get("offset", 0)))
                vit.skipped_offsets = [
                    int(i) for i in led.get("skipped", [])
                ]
                vit.reason_counts = {
                    str(k): int(v)
                    for k, v in (led.get("reasons") or {}).items()
                }
            self._resume_ledger = None
        consumed = 0
        from deeplearning4j_tpu.observability.trace import get_tracer

        run_span = get_tracer().start_span(
            "train.continual.run",
            attrs={"start_step": int(self.model.iteration_count),
                   "publish_every": int(self.publish_every)},
        )
        try:
            for ds in self._iter(stream):
                # preemption notice -> emergency publish through THIS
                # trainer's publish() (AOT artifacts attached, journal
                # retention honored), then PreemptedException
                preemption.check_fit(
                    self.model, checkpoint_fn=self._publish_sync,
                    prefetch=stream
                    if hasattr(stream, "shutdown") else None,
                )
                fit(ds)
                consumed += 1
                self._m_steps.inc()
                if vit is not None:
                    # snapshot AFTER the fit so a publish (scheduled
                    # or preemption-emergency) never claims a base
                    # batch the params don't yet reflect
                    self.model._data_ledger = vit.ledger()
                if self.model.iteration_count % self.publish_every == 0:
                    self.publish()
                if max_steps is not None and consumed >= max_steps:
                    break
            if publish_trailing and consumed and (
                self.last_published is None
                or self.last_published.step < self.model.iteration_count
            ):
                self.publish()
        except BaseException as e:
            run_span.set_attr("steps", consumed)
            run_span.end(status=type(e).__name__)
            raise
        run_span.set_attr("steps", consumed)
        run_span.end()
        return consumed

    @staticmethod
    def _iter(stream):
        if hasattr(stream, "has_next") and hasattr(stream, "next"):
            while stream.has_next():
                yield stream.next()
        else:
            yield from stream
