"""Crash-safe promotion journal.

The promoter's state machine (``promoter.py``) survives a SIGKILL at
any instant because every transition is journaled to ONE on-disk JSON
document *before* its side effects become visible, and the document is
replaced atomically (temp file + ``os.replace`` — the same primitive
the checkpoint manifests use). A recovering promoter reads the journal
and either rolls the half-applied transition forward or back; there is
no state the journal can describe that recovery cannot resolve.

Document (format 1)::

    {"format": 1,
     "state": "idle|candidate|shadowing|canarying|promoted|rolled_back"
              "|quarantined",
     "candidate_step": 12,       # the version under evaluation
     "previous_step": 8,         # what was serving when it appeared
     "promoted_step": 8,         # last FULLY promoted version
     "probation": false,         # promoted but still watched
     "gates_passed": true,       # shadow gates verdict (pre-canary)
     "reason": "...",            # why the last terminal state
     "rejected_steps": [...],    # candidates that failed gates/canary
     "quarantined_steps": [...], # candidates whose checkpoint was bad
     "history": [... last 32 transitions ...]}

``referenced_steps()`` is the retention contract: the checkpoint
manager must never prune the steps the journal still points at
(``CheckpointManager(protect=journal.referenced_steps)``), or a
recovery could find its rollback target deleted.

A journal that is missing reads as empty (fresh install). A journal
that is unreadable (torn by external tampering — atomic replace never
produces one) reads as empty too, with a warning: the promoter then
re-derives a consistent state from what the server actually serves,
which is always safe, merely forgetful.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.resilience.checkpoint import atomic_write_bytes

logger = logging.getLogger(__name__)

JOURNAL_FORMAT = 1
HISTORY_LIMIT = 32

# the promoter's state machine, as journaled
IDLE = "idle"
CANDIDATE = "candidate"
SHADOWING = "shadowing"
CANARYING = "canarying"
PROMOTED = "promoted"
ROLLED_BACK = "rolled_back"
QUARANTINED = "quarantined"

STATES = (IDLE, CANDIDATE, SHADOWING, CANARYING, PROMOTED,
          ROLLED_BACK, QUARANTINED)

# gauge encoding for ``loop_state`` (stable; dashboards key on it)
STATE_CODES = {s: i for i, s in enumerate(STATES)}


class PromotionJournal:
    """One atomic JSON document recording the promotion state machine.

    Reads are tolerant (missing/torn -> empty doc); writes are atomic
    and carry a bounded transition history for post-mortems.
    """

    def __init__(self, path):
        self.path = Path(path)

    # -- read -----------------------------------------------------------

    def read(self) -> dict:
        """The current document, or a fresh empty one when the file is
        missing or unreadable (never raises)."""
        try:
            doc = json.loads(self.path.read_text())
            if not isinstance(doc, dict):
                raise ValueError("journal root is not an object")
        except FileNotFoundError:
            return self._empty()
        except (ValueError, OSError) as e:
            logger.warning(
                "promotion journal %s is unreadable (%s); treating as "
                "empty — recovery re-derives state from the server",
                self.path, e,
            )
            return self._empty()
        base = self._empty()
        base.update(doc)
        return base

    @staticmethod
    def _empty() -> dict:
        return {
            "format": JOURNAL_FORMAT,
            "state": IDLE,
            "candidate_step": None,
            "previous_step": None,
            "promoted_step": None,
            "probation": False,
            "gates_passed": False,
            "reason": None,
            "rejected_steps": [],
            "quarantined_steps": [],
            "history": [],
        }

    @property
    def state(self) -> str:
        return self.read().get("state", IDLE)

    # -- write ----------------------------------------------------------

    def write(self, state: str, **fields) -> dict:
        """Record one transition: merge ``fields`` into the document,
        set ``state``, append to history, and replace the file
        atomically. Returns the new document."""
        if state not in STATES:
            raise ValueError(f"unknown journal state {state!r}")
        doc = self.read()
        doc["state"] = state
        for k, v in fields.items():
            if k in ("rejected_steps", "quarantined_steps"):
                # list fields merge (append-once), never overwrite
                merged = list(doc.get(k) or [])
                for step in (v if isinstance(v, (list, tuple)) else [v]):
                    if step is not None and step not in merged:
                        merged.append(step)
                doc[k] = merged
            else:
                doc[k] = v
        entry = {"state": state, "at": time.time()}
        for k in ("candidate_step", "previous_step", "promoted_step",
                  "probation", "reason"):
            if doc.get(k) is not None:
                entry[k] = doc[k]
        doc["history"] = (doc.get("history") or [])[-(HISTORY_LIMIT - 1):]
        doc["history"].append(entry)
        atomic_write_bytes(
            self.path, json.dumps(doc, indent=2).encode()
        )
        return doc

    # -- retention contract ---------------------------------------------

    def referenced_steps(self) -> List[int]:
        """Checkpoint steps the journal still points at — the steps
        retention pruning must never delete (candidate under
        evaluation, the serving previous version, the last promoted
        version). Wire as ``CheckpointManager(protect=
        journal.referenced_steps)``."""
        doc = self.read()
        out = []
        for k in ("candidate_step", "previous_step", "promoted_step"):
            v = doc.get(k)
            if isinstance(v, int) and v not in out:
                out.append(v)
        return out

    def skip_steps(self) -> List[int]:
        """Candidate steps already judged (rejected or quarantined) —
        the promoter must not re-shadow them on every poll."""
        doc = self.read()
        out = []
        for k in ("rejected_steps", "quarantined_steps"):
            for v in doc.get(k) or []:
                if isinstance(v, int) and v not in out:
                    out.append(v)
        return out


class SimulatedKill(RuntimeError):
    """Raised by the promoter's chaos hook (``fail_after_journal``) to
    model a SIGKILL landing right after a journal write — the worst
    instant, because the journal now leads the world. Tests and
    ``scripts/run_loop.py`` catch it and prove recovery converges."""


def state_code(state: Optional[str]) -> int:
    return STATE_CODES.get(state or IDLE, 0)
