"""Early stopping engine (reference: ``earlystopping/*`` — 20 files;
the trainer loop mirrors ``earlystopping/trainer/
BaseEarlyStoppingTrainer.java``: per-epoch fit, score on a holdout,
track best model, stop on epoch/iteration termination conditions,
persist via a model saver)."""

from __future__ import annotations

import numpy as np

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, List


# -- termination conditions (reference earlystopping/termination/*) -----


class EpochTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no improvement (reference class of the
    same name)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = math.inf
        self.since = 0

    def initialize(self) -> None:
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if score < self.best - self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        # Reference terminates once exactly `patience` evaluations pass
        # with no improvement (ScoreImprovementEpochTerminationCondition
        # .java:66: epochNum >= bestEpoch + maxEpochsWithNoImprovement)
        return self.since >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at/below a target."""

    def __init__(self, best_expected_score: float):
        self.target = best_expected_score

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.target


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self) -> None:
        self._start = time.time()

    def terminate(self, last_score: float) -> bool:
        return (time.time() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if the score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)


# -- score calculators (reference earlystopping/scorecalc) --------------


class DataSetLossCalculator:
    """Average loss over a DataSetIterator (reference
    ``DataSetLossCalculator``). Works for both model types."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet

        total, n = 0.0, 0
        for ds in self.iterator:
            if isinstance(ds, ChunkedDataSet):
                # score() consumes single minibatches; unstack
                batches = ds.to_datasets()
            else:
                batches = [ds]
            for b in batches:
                # weight each batch by its example count (reference
                # DataSetLossCalculator.java:36-41: lossSum += score*nEx)
                if hasattr(b, "num_examples"):
                    n_ex = b.num_examples()
                else:
                    n_ex = int(np.shape(b.features)[0])
                total += model.score(b) * n_ex
                n += n_ex
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        if n == 0:
            return float("nan")
        return total / n if self.average else total


# -- model savers (reference earlystopping/saver) -----------------------


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score: float) -> None:
        self._best = model.copy() if hasattr(model, "copy") else model

    def save_latest_model(self, model, score: float) -> None:
        self._latest = model.copy() if hasattr(model, "copy") else model

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Zip checkpoints in a directory (reference ``LocalFileModelSaver``
    writes bestModel.bin / latestModel.bin).

    Saves are atomic: ``write_model`` stages to a temp file in the
    same directory and ``os.replace``s it over bestModel.zip /
    latestModel.zip, so a crash mid-save never clobbers the last good
    checkpoint with a truncated zip."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    def save_best_model(self, model, score: float) -> None:
        from deeplearning4j_tpu.util import write_model

        write_model(model, self.best_path)

    def save_latest_model(self, model, score: float) -> None:
        from deeplearning4j_tpu.util import write_model

        write_model(model, self.latest_path)

    def get_best_model(self):
        from deeplearning4j_tpu.util import restore_model

        return restore_model(self.best_path)

    def get_latest_model(self):
        from deeplearning4j_tpu.util import restore_model

        return restore_model(self.latest_path)


# -- configuration + result (reference EarlyStoppingConfiguration) ------


@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any
    epoch_terminations: List[EpochTerminationCondition] = field(
        default_factory=list
    )
    iteration_terminations: List[IterationTerminationCondition] = field(
        default_factory=list
    )
    model_saver: Any = None
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False
    # resilience.CheckpointManager: when set, every trained epoch is
    # checkpointed (atomic + versioned + CRC-manifested), so an
    # early-stopping run survives preemption and resumes via
    # model.resume(manager) — best/latest saver semantics unchanged.
    checkpoint_manager: Any = None

    def __post_init__(self):
        if self.model_saver is None:
            self.model_saver = InMemoryModelSaver()


@dataclass
class EarlyStoppingResult:
    termination_reason: str  # EpochTerminationCondition name etc.
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any


# -- trainer (reference earlystopping/trainer) --------------------------


class EarlyStoppingTrainer:
    """Reference ``EarlyStoppingTrainer`` (MultiLayerNetwork flavor);
    ``EarlyStoppingGraphTrainer`` below for graphs — the loop is
    identical."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def _train_epoch(self):
        """One epoch; returns the tripped iteration-termination
        condition or None. Subclasses replace the training mechanics
        (parallel wrapper / cluster master) but share the loop."""
        from deeplearning4j_tpu.resilience import preemption

        cfg = self.config
        for ds in self.train_iterator:
            # preemption notice -> emergency checkpoint (the per-epoch
            # manager if configured, else the handler's) + raise
            preemption.check_fit(self.model,
                                 manager=cfg.checkpoint_manager)
            self.model.fit_minibatch(ds)
            for c in cfg.iteration_terminations:
                if c.terminate(self.model.score_value):
                    return c
        return None

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_terminations:
            c.initialize()
        for c in cfg.iteration_terminations:
            c.initialize()
        best_score = math.inf
        best_epoch = -1
        scores: dict = {}
        epoch = 0
        reason, details = "MaxEpochs", "exhausted"
        from deeplearning4j_tpu.resilience import preemption

        while True:
            # epoch boundary check covers subclasses whose
            # _train_epoch replaces the minibatch loop
            preemption.check_fit(self.model,
                                 manager=cfg.checkpoint_manager)
            stop_iter = self._train_epoch()
            if hasattr(self.train_iterator, "reset"):
                self.train_iterator.reset()
            if cfg.checkpoint_manager is not None:
                # per-epoch preemption point: versioned checkpoint of
                # the in-training model (distinct from best/latest,
                # which track the evaluation winner)
                cfg.checkpoint_manager.save(self.model)
            if stop_iter is not None:
                reason = "IterationTerminationCondition"
                details = type(stop_iter).__name__
                break
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
                scores[epoch] = score
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.model, score)
                stop_epoch = None
                for c in cfg.epoch_terminations:
                    if c.terminate(epoch, score):
                        stop_epoch = c
                        break
                if stop_epoch is not None:
                    reason = "EpochTerminationCondition"
                    details = type(stop_epoch).__name__
                    epoch += 1
                    break
            epoch += 1
        # best_epoch == -1 means no evaluation ever saved a best model
        # (e.g. NaN on the first minibatch) — don't ask the saver for a
        # file that was never written.
        best = (
            cfg.model_saver.get_best_model() if best_epoch >= 0 else None
        )
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            score_vs_epoch=scores,
            best_model=best if best is not None else self.model,
        )


class EarlyStoppingGraphTrainer(EarlyStoppingTrainer):
    """Reference ``EarlyStoppingGraphTrainer`` — same loop over a
    ComputationGraph."""


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over data-parallel replica training (reference
    ``parallelism/EarlyStoppingParallelTrainer.java`` — wraps
    ParallelWrapper instead of the single-model fit). Each epoch the
    wrapper deals the iterator's batches to replicas and averages;
    evaluation/termination runs on the synchronized model."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator, workers: int = 2,
                 averaging_frequency: int = 1):
        super().__init__(config, model, train_iterator)
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        self.wrapper = ParallelWrapper(
            model, workers=workers,
            averaging_frequency=averaging_frequency,
        )

    def _train_epoch(self):
        self.wrapper.fit(self.train_iterator)
        for c in self.config.iteration_terminations:
            if c.terminate(self.model.score_value):
                return c
        return None


class ClusterEarlyStoppingTrainer(EarlyStoppingTrainer):
    """Early stopping over cluster training (reference
    ``spark/earlystopping/SparkEarlyStoppingTrainer.java`` — each
    epoch runs through the TrainingMaster instead of per-batch
    fitting)."""

    def __init__(self, config: EarlyStoppingConfiguration, net,
                 training_master, train_data):
        super().__init__(config, net, train_data)
        self.training_master = training_master

    def _train_epoch(self):
        self.training_master.execute_training(
            self.model, self.train_iterator
        )
        for c in self.config.iteration_terminations:
            if c.terminate(self.model.score_value):
                return c
        return None
