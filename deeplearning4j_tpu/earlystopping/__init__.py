"""Early stopping (reference ``deeplearning4j-nn/.../earlystopping``:
``EarlyStoppingConfiguration`` + termination conditions + score
calculators + savers + ``EarlyStoppingTrainer``)."""

from deeplearning4j_tpu.earlystopping.core import (  # noqa: F401
    BestScoreEpochTerminationCondition,
    ClusterEarlyStoppingTrainer,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingGraphTrainer,
    EarlyStoppingParallelTrainer,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
