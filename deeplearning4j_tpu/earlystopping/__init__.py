"""Early stopping (reference ``deeplearning4j-nn/.../earlystopping``:
``EarlyStoppingConfiguration`` + termination conditions + score
calculators + savers + ``EarlyStoppingTrainer``)."""

from deeplearning4j_tpu.earlystopping.core import (  # noqa: F401
    BestScoreEpochTerminationCondition,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingGraphTrainer,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
