"""Model-serving route (reference
``routes/DL4jServeRouteBuilder.java:1`` — a Camel route that loads a
``ModelSerializer`` checkpoint, transforms the incoming record and
predicts; here a stdlib HTTP server with the same load->transform->
predict shape).

Endpoints:
- ``GET  /healthz``    -> {"status": "ok", "model": "<class>"}
- ``POST /predict``    -> body {"features": [[...]]}; returns
  {"output": [[...]]} (+ {"classes": [...]} argmaxes when
  ``output_classes``)
Binds loopback by default (same policy as the training UI server).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

MAX_BODY = 64 * 1024 * 1024


def _make_handler(server: "ModelServer"):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, obj, code: int = 200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._json({
                    "status": "ok",
                    "model": type(server.model).__name__,
                })
                return
            self._json({"error": "not found"}, 404)

        def do_POST(self):
            if self.path != "/predict":
                self._json({"error": "not found"}, 404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                self._json({"error": "bad Content-Length"}, 400)
                return
            if length < 0 or length > MAX_BODY:
                self._json({"error": "payload too large"}, 413)
                return
            try:
                payload = json.loads(self.rfile.read(length))
                feats = np.asarray(payload["features"], np.float32)
                if server.transform is not None:
                    feats = server.transform(feats)
                out = server.model.output(feats)
                out = np.asarray(
                    out[0] if isinstance(out, (list, tuple)) else out
                )
            except Exception as e:
                self._json({"error": f"bad request: {e}"}, 400)
                return
            resp = {"output": out.tolist()}
            if server.output_classes and out.ndim == 2:
                resp["classes"] = out.argmax(axis=1).tolist()
            self._json(resp)

    return Handler


class ModelServer:
    """Serve a saved model over HTTP (reference
    ``DL4jServeRouteBuilder`` — ``modelUri`` + ``transform`` +
    predict)."""

    def __init__(self, model_or_path, host: str = "127.0.0.1",
                 port: int = 0, transform=None,
                 output_classes: bool = False):
        if isinstance(model_or_path, str):
            from deeplearning4j_tpu.util.model_serializer import (
                restore_model,
            )

            self.model = restore_model(model_or_path)
        else:
            self.model = model_or_path
        self.transform = transform
        self.output_classes = output_classes
        self._httpd = ThreadingHTTPServer(
            (host, port), _make_handler(self)
        )
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ModelServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-serve",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
