"""Model-serving route (reference
``routes/DL4jServeRouteBuilder.java:1`` — a Camel route that loads a
``ModelSerializer`` checkpoint, transforms the incoming record and
predicts).

This module grew into the hardened serving tier in
``deeplearning4j_tpu/serving/`` — admission control, per-request
deadlines, circuit breaking, canary-validated hot reload, graceful
drain, ``/readyz`` vs ``/healthz``, ``/metrics`` — and now re-exports
it so existing ``streaming.ModelServer`` imports keep working with
the same constructor surface (model-or-path, host, port, transform,
output_classes) plus the new keyword-only hardening knobs. The old
toy handler's bugs are fixed in the shared implementation: bodies are
read to the full Content-Length (short reads are ``400``, missing
Content-Length is ``411``), malformed payloads are ``400``,
shape-invalid features are ``422`` with expected-vs-got, and
model/transform faults are ``500`` with an opaque error id — never a
masked ``400`` or a stack trace.
"""

from deeplearning4j_tpu.serving.server import (  # noqa: F401
    MAX_BODY,
    ModelServer,
)
