"""NDArray streaming over sockets (reference
``streaming/kafka/NDArrayKafkaClient.java`` + ``NDArrayPublisher`` /
``NDArrayConsumer`` — Kafka is the reference's transport; the honest
zero-dependency equivalent here is a length-prefixed TCP stream, with
the same publisher/consumer surface so a Kafka transport can slot in
behind it).

Wire format per message: 8-byte big-endian length + JSON header
{"dtype", "shape", "label_shape"?} + raw array bytes (+ label bytes).
Host-side only; the training loop consumes the resulting DataSets and
feeds the device as usual.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

_MAX_MESSAGE = 256 * 1024 * 1024


def encode_ndarray_message(features: np.ndarray,
                           labels: Optional[np.ndarray] = None) -> bytes:
    """Serialize one (features[, labels]) record (reference
    ``NDArrayPublisher.publish`` payload)."""
    features = np.ascontiguousarray(features, np.float32)
    header = {
        "dtype": "float32",
        "shape": list(features.shape),
    }
    parts = [features.tobytes()]
    if labels is not None:
        labels = np.ascontiguousarray(labels, np.float32)
        header["label_shape"] = list(labels.shape)
        parts.append(labels.tobytes())
    hb = json.dumps(header).encode()
    body = struct.pack(">I", len(hb)) + hb + b"".join(parts)
    return struct.pack(">Q", len(body)) + body


def decode_ndarray_message(body: bytes
                           ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    (hlen,) = struct.unpack(">I", body[:4])
    header = json.loads(body[4:4 + hlen].decode())
    off = 4 + hlen
    shape = tuple(header["shape"])
    n = int(np.prod(shape)) * 4
    feats = np.frombuffer(body[off:off + n], "<f4").reshape(shape).copy()
    off += n
    labels = None
    if "label_shape" in header:
        ls = tuple(header["label_shape"])
        m = int(np.prod(ls)) * 4
        labels = np.frombuffer(body[off:off + m], "<f4").reshape(ls).copy()
    return feats, labels


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("stream closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


class NDArrayPublisher:
    """Push arrays to a consumer (reference ``NDArrayPublisher``)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)

    def publish(self, features, labels=None) -> None:
        self._sock.sendall(encode_ndarray_message(
            np.asarray(features), None if labels is None
            else np.asarray(labels)
        ))

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        self._sock.close()


class NDArrayConsumer:
    """Listen for published arrays (reference ``NDArrayConsumer``).
    ``listen()`` starts a daemon acceptor; records land in a bounded
    queue consumed via ``get()`` / iteration."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_size: int = 256):
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def listen(self) -> "NDArrayConsumer":
        def run():
            while not self._closed.is_set():
                try:
                    conn, _ = self._server.accept()
                except OSError:
                    return
                try:
                    while True:
                        raw = _read_exact(conn, 8)
                        (size,) = struct.unpack(">Q", raw)
                        if size > _MAX_MESSAGE:
                            raise ValueError("message too large")
                        body = _read_exact(conn, size)
                        self._queue.put(decode_ndarray_message(body))
                except (ConnectionError, ValueError, OSError):
                    conn.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="ndarray-consumer")
        self._thread.start()
        return self

    def get(self, timeout: Optional[float] = None
            ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        return self._queue.get(timeout=timeout)

    def close(self) -> None:
        self._closed.set()
        self._server.close()


class StreamingDataSetIterator(DataSetIterator):
    """DataSetIterator over a live record stream (the
    Kafka->DataSet ingestion leg of the reference's streaming
    pipeline, ``SparkStreamingPipeline.java``): pulls records from an
    ``NDArrayConsumer`` (or any source with ``get(timeout)``),
    batches ``batch_size`` examples, stops after ``total_batches`` (or
    when ``None``, on source timeout)."""

    def __init__(self, source, batch_size: int,
                 total_batches: Optional[int] = None,
                 timeout: float = 10.0):
        self.source = source
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.timeout = timeout
        self._delivered = 0
        self._exhausted = False

    def has_next(self) -> bool:
        if self._exhausted:
            return False
        if self.total_batches is not None:
            return self._delivered < self.total_batches
        return True

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        feats, labels = [], []
        for _ in range(self.batch_size):
            try:
                f, l = self.source.get(timeout=self.timeout)
            except queue.Empty:
                self._exhausted = True
                break
            feats.append(f)
            labels.append(l)
        if not feats:
            raise StopIteration
        has_labels = [l is not None for l in labels]
        if any(has_labels) and not all(has_labels):
            raise ValueError(
                "stream mixes labeled and unlabeled records within one "
                "batch — labels would misalign with features"
            )
        self._delivered += 1
        return DataSet(
            features=np.stack(feats),
            labels=np.stack(labels) if all(has_labels) else None,
        )

    def reset(self) -> None:
        self._delivered = 0  # a live stream cannot rewind

    def batch(self) -> int:
        return self.batch_size
