"""Streaming ingestion + model serving (reference ``dl4j-streaming``:
``streaming/kafka/NDArrayKafkaClient.java:1`` / ``NDArrayPublisher`` /
``NDArrayConsumer`` and the Camel model-serving route
``routes/DL4jServeRouteBuilder.java:1``)."""

from deeplearning4j_tpu.streaming.ndarray_stream import (  # noqa: F401
    NDArrayConsumer,
    NDArrayPublisher,
    StreamingDataSetIterator,
    decode_ndarray_message,
    encode_ndarray_message,
)
from deeplearning4j_tpu.streaming.serve import ModelServer  # noqa: F401
