"""Cloud provisioning + object storage (reference:
``deeplearning4j-scaleout/deeplearning4j-aws`` — ``Ec2BoxCreator``,
``ClusterSetup``/``HostProvisioner``, ``S3Downloader``/``S3Uploader``,
``BaseS3DataSetIterator``), redesigned for TPU fleets: box creation
becomes TPU-pod provisioning plans, SSH fan-out becomes per-worker
command execution, and the S3 reader/uploader becomes an ObjectStore
SPI whose local-filesystem backend works in any environment (the
cloud-SDK backends are optional and gated on their clients)."""

from deeplearning4j_tpu.cloud.provision import (  # noqa: F401
    ClusterSetup,
    HostProvisioner,
    TpuPodProvisioner,
)
from deeplearning4j_tpu.cloud.storage import (  # noqa: F401
    GcsObjectStore,
    LocalObjectStore,
    ObjectStore,
    S3ObjectStore,
    StorageDownloader,
    StorageUploader,
    object_store_for,
)
from deeplearning4j_tpu.cloud.data import (  # noqa: F401
    CloudDataSetIterator,
    save_dataset_shards,
)
# retrying decorator lives in the resilience subsystem; re-exported
# here because it is storage-facing API (must import after .storage —
# it wraps the ObjectStore SPI)
from deeplearning4j_tpu.resilience.store import (  # noqa: F401
    RetryingObjectStore,
)
