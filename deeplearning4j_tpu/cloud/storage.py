"""Object storage SPI (reference: ``aws/s3/reader/S3Downloader.java:38``
— keysForBucket/iterateBucket/objectForKey/download/paginate;
``aws/s3/uploader/S3Uploader.java`` — upload/multi-part; both extend
``aws/s3/BaseS3.java`` credential plumbing).

Redesign: one ``ObjectStore`` interface with list/read/write/download
/upload, a ``LocalObjectStore`` filesystem backend that always works
(tests, on-host caches, NFS/FUSE-mounted GCS), and cloud backends
that are thin adapters gated on their SDKs (boto3 / google-cloud-
storage are NOT bundled; constructing them without the SDK raises
with the install hint). The reader/uploader split of the reference
collapses into the one interface; ``StorageDownloader`` /
``StorageUploader`` keep the reference's call-shape for migration."""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import IO, Iterator, List


class ObjectStore:
    """SPI: bucket-scoped object operations."""

    def keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def open(self, key: str) -> IO[bytes]:
        raise NotImplementedError

    def read(self, key: str) -> bytes:
        with self.open(key) as f:
            return f.read()

    def write(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def download(self, key: str, to_path) -> None:
        with self.open(key) as src, open(to_path, "wb") as dst:
            shutil.copyfileobj(src, dst)

    def upload(self, from_path, key: str) -> None:
        with open(from_path, "rb") as f:
            self.write(key, f.read())

    def iterate(self, prefix: str = "") -> Iterator[IO[bytes]]:
        """Stream every object under ``prefix`` (reference
        ``iterateBucket:84``)."""
        for key in self.keys(prefix):
            yield self.open(key)

    def paginate(self, listener, prefix: str = "") -> None:
        """Every key through ``listener(key)`` (reference
        ``paginate:118`` + BucketKeyListener — whose S3 pages are an
        API detail; the contract is per-key delivery in order)."""
        for key in self.keys(prefix):
            listener(key)


class LocalObjectStore(ObjectStore):
    """Filesystem-backed store: a 'bucket' is a directory, keys are
    relative paths. The backend every test and egress-less
    environment can run."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        p = (self.root / key).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"key {key!r} escapes the store root")
        return p

    def keys(self, prefix: str = "") -> List[str]:
        out = []
        for p in sorted(self.root.rglob("*")):
            if p.is_file():
                rel = p.relative_to(self.root).as_posix()
                if rel.startswith(prefix):
                    out.append(rel)
        return out

    def open(self, key: str) -> IO[bytes]:
        return open(self._path(key), "rb")

    def write(self, key: str, data: bytes) -> None:
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(data)


class S3ObjectStore(ObjectStore):
    """boto3-backed adapter (reference S3Downloader/S3Uploader).
    Gated: raises at construction when boto3 is absent."""

    def __init__(self, bucket: str, client=None):
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise ImportError(
                    "S3ObjectStore needs boto3 (pip install boto3) "
                    "or an injected client"
                ) from e
            client = boto3.client("s3")
        self.bucket = bucket
        self.client = client

    def keys(self, prefix: str = "") -> List[str]:
        out: List[str] = []
        token = None
        while True:
            kw = {"Bucket": self.bucket, "Prefix": prefix}
            if token:
                kw["ContinuationToken"] = token
            resp = self.client.list_objects_v2(**kw)
            out.extend(
                o["Key"] for o in resp.get("Contents", [])
            )
            if not resp.get("IsTruncated"):
                return out
            token = resp.get("NextContinuationToken")

    def open(self, key: str) -> IO[bytes]:
        return self.client.get_object(
            Bucket=self.bucket, Key=key
        )["Body"]

    def write(self, key: str, data: bytes) -> None:
        self.client.put_object(Bucket=self.bucket, Key=key, Body=data)


class GcsObjectStore(ObjectStore):
    """google-cloud-storage adapter (the TPU-side twin of the S3
    reader). Gated on the SDK like S3ObjectStore."""

    def __init__(self, bucket: str, client=None):
        if client is None:
            try:
                from google.cloud import storage
            except ImportError as e:
                raise ImportError(
                    "GcsObjectStore needs google-cloud-storage or an "
                    "injected client"
                ) from e
            client = storage.Client()
        self.bucket = client.bucket(bucket) if isinstance(
            bucket, str
        ) else bucket

    def keys(self, prefix: str = "") -> List[str]:
        return [b.name for b in self.bucket.list_blobs(prefix=prefix)]

    def open(self, key: str) -> IO[bytes]:
        import io

        return io.BytesIO(self.bucket.blob(key).download_as_bytes())

    def write(self, key: str, data: bytes) -> None:
        self.bucket.blob(key).upload_from_string(data)


def object_store_for(url: str, retry=None) -> ObjectStore:
    """URL-dispatching constructor: ``s3://bucket``, ``gs://bucket``,
    or a local path / ``file://`` directory. Bucket URLs must name
    ONLY the bucket — a key prefix would be silently ignored by the
    store, so it is rejected; pass prefixes to the key-taking APIs
    (``keys(prefix)``, ``CloudDataSetIterator(prefix=...)``).

    ``retry``: a ``resilience.RetryPolicy`` (or ``True`` for the
    defaults) wraps the store in a ``RetryingObjectStore`` so every
    read/write runs under bounded exponential backoff."""

    def _wrap(store: ObjectStore) -> ObjectStore:
        if retry is None:
            return store
        from deeplearning4j_tpu.resilience.retry import RetryPolicy
        from deeplearning4j_tpu.resilience.store import RetryingObjectStore

        policy = RetryPolicy() if retry is True else retry
        return RetryingObjectStore(store, policy)

    for scheme, cls in (("s3://", S3ObjectStore),
                        ("gs://", GcsObjectStore)):
        if url.startswith(scheme):
            rest = url[len(scheme):]
            bucket, _, suffix = rest.partition("/")
            if suffix:
                raise ValueError(
                    f"{url!r} names a key prefix; use "
                    f"{scheme}{bucket} and pass {suffix!r} as the "
                    "prefix argument"
                )
            return _wrap(cls(bucket))
    if url.startswith("file://"):
        url = url[7:]
    return _wrap(LocalObjectStore(url))


class StorageDownloader:
    """Reference-call-shape shim (``S3Downloader``): bucket-first
    methods over any ObjectStore backend."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def keys_for_bucket(self, prefix: str = "") -> List[str]:
        return self.store.keys(prefix)

    def object_for_key(self, key: str) -> IO[bytes]:
        return self.store.open(key)

    def download(self, key: str, to_path) -> None:
        self.store.download(key, to_path)

    def iterate_bucket(self, prefix: str = "") -> Iterator[IO[bytes]]:
        return self.store.iterate(prefix)

    def paginate(self, listener, prefix: str = "") -> None:
        self.store.paginate(listener, prefix)


class StorageUploader:
    """Reference-call-shape shim (``S3Uploader``)."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def upload(self, from_path, key: str = None) -> None:
        key = key or os.path.basename(str(from_path))
        self.store.upload(from_path, key)

    def upload_directory(self, directory, prefix: str = "") -> None:
        d = Path(directory)
        for p in sorted(d.rglob("*")):
            if p.is_file():
                rel = p.relative_to(d).as_posix()
                key = f"{prefix}{rel}" if prefix else rel
                self.store.upload(p, key)
