"""Object-store dataset iteration (reference:
``aws/s3/reader/BaseS3DataSetIterator.java`` — iterate serialized
DataSet objects straight out of a bucket — and the export-based
training path ``spark/data/BatchAndExportDataSetsFunction.java``,
which writes minibatch files a cluster later trains from).

Shards use THE shard codec — ``DataSet.save_npz``/``load_npz``
(``datasets/api.py``), shared with ``parallel.cluster``'s
export-based path — so shards written by either path read back
identically from the other. ``save_dataset_shards`` produces them,
``CloudDataSetIterator`` streams them back from any ``ObjectStore``
backend: export minibatches once, train many times from storage."""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.cloud.storage import ObjectStore
from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator


def save_dataset_shards(batches, store: ObjectStore,
                        prefix: str = "dataset/") -> List[str]:
    """Export minibatches as numbered npz shards (the
    BatchAndExportDataSetsFunction analog). Returns the keys."""
    keys = []
    for i, ds in enumerate(batches):
        key = f"{prefix}shard-{i:06d}.npz"
        store.write(key, ds.to_npz_bytes())
        keys.append(key)
    return keys


class CloudDataSetIterator(DataSetIterator):
    """Stream DataSet shards from an object store
    (``BaseS3DataSetIterator`` analog). Keys are listed once at
    construction; ``reset()`` restarts the stream. Feed it to any
    ``fit(iterator)`` — the engines' async prefetch wrapper
    (``datasets.iterators.AsyncDataSetIterator``) overlaps the
    store reads with device steps exactly as the reference wraps its
    S3 iterator."""

    def __init__(self, store: ObjectStore, prefix: str = "dataset/",
                 keys: Optional[List[str]] = None, retry=None):
        if retry is not None:
            # shard fetches run under bounded backoff (resilience
            # subsystem): a flaky read retries transparently instead of
            # killing the fit loop mid-epoch
            from deeplearning4j_tpu.resilience.retry import RetryPolicy
            from deeplearning4j_tpu.resilience.store import (
                RetryingObjectStore,
            )

            policy = RetryPolicy() if retry is True else retry
            if not isinstance(store, RetryingObjectStore):
                store = RetryingObjectStore(store, policy)
        self.store = store
        self._keys = list(keys) if keys is not None else store.keys(
            prefix
        )
        if not self._keys:
            raise ValueError(
                f"no dataset shards under prefix {prefix!r}"
            )
        self._pos = 0
        self._first: Optional[DataSet] = None

    def next(self) -> DataSet:
        ds = DataSet.from_npz_bytes(
            self.store.read(self._keys[self._pos])
        )
        self._pos += 1
        if self._first is None:
            self._first = ds
        return ds

    def has_next(self) -> bool:
        return self._pos < len(self._keys)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        if self._first is None:
            self._first = DataSet.from_npz_bytes(
                self.store.read(self._keys[0])
            )
        return self._first.num_examples()

    def total_examples(self) -> int:
        return -1  # unknown without reading every shard
