"""Fleet provisioning (reference:
``aws/ec2/Ec2BoxCreator.java:37`` — create N EC2 boxes from an AMI;
``aws/ec2/provision/ClusterSetup.java:38`` — provision master +
workers; ``aws/ec2/provision/HostProvisioner.java:1`` — SSH command
fan-out via JSch).

TPU-native redesign: the unit of provisioning is a TPU pod slice, not
a box. ``TpuPodProvisioner`` builds the full ``gcloud compute tpus``
command plan (create / describe / ssh / delete) plus the worker
environment (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``
consumed by ``parallel.mesh.init_distributed``); ``ClusterSetup``
composes plan + per-worker setup commands; ``HostProvisioner`` runs
commands either locally (dry-run/local worker) or through a
user-supplied runner (ssh binary, paramiko, CI executor). Everything
is side-effect-free until ``execute=True`` — this module must work in
an egress-less environment, and a provisioning plan you can read
beats one that half-ran."""

from __future__ import annotations

import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# accelerator-type -> (hosts, chips) for common v5e/v4 slices; used to
# derive NUM_PROCESSES for the jax.distributed bring-up
_SLICE_HOSTS = {
    "v5litepod-1": 1, "v5litepod-4": 1, "v5litepod-8": 1,
    "v5litepod-16": 4, "v5litepod-32": 8, "v5litepod-64": 16,
    "v5litepod-128": 32, "v5litepod-256": 64,
    "v4-8": 1, "v4-16": 2, "v4-32": 4, "v4-64": 8,
}


@dataclass
class TpuPodProvisioner:
    """Ec2BoxCreator analog: declares WHAT to create and emits the
    command plan that creates it (``Ec2BoxCreator.create()`` calls the
    EC2 API; here the plan is explicit and auditable)."""

    name: str
    accelerator_type: str = "v5litepod-8"
    zone: str = "us-central1-a"
    runtime_version: str = "v2-alpha-tpuv5-lite"
    project: Optional[str] = None
    preemptible: bool = False
    created: List[str] = field(default_factory=list)

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _scope(self) -> List[str]:
        out = ["--zone", self.zone]
        if self.project:
            out += ["--project", self.project]
        return out

    def create_plan(self) -> List[str]:
        cmd = self._base() + ["create", self.name] + self._scope() + [
            "--accelerator-type", self.accelerator_type,
            "--version", self.runtime_version,
        ]
        if self.preemptible:
            cmd.append("--preemptible")
        return cmd

    def delete_plan(self) -> List[str]:
        return self._base() + ["delete", self.name, "--quiet"] + \
            self._scope()

    def ssh_plan(self, command: str, worker: str = "all") -> List[str]:
        return self._base() + ["ssh", self.name] + self._scope() + [
            "--worker", worker, "--command", command,
        ]

    def num_hosts(self) -> int:
        n = _SLICE_HOSTS.get(self.accelerator_type)
        if n is None:
            raise ValueError(
                f"unknown accelerator type "
                f"{self.accelerator_type!r}; known: "
                f"{sorted(_SLICE_HOSTS)}"
            )
        return n

    def worker_env(self, coordinator_host: str,
                   port: int = 8476) -> List[Dict[str, str]]:
        """Per-worker env consumed by ``init_distributed`` (the
        reference wires master/worker addresses through ClusterSetup
        the same way)."""
        n = self.num_hosts()
        return [
            {
                "COORDINATOR_ADDRESS": f"{coordinator_host}:{port}",
                "NUM_PROCESSES": str(n),
                "PROCESS_ID": str(i),
            }
            for i in range(n)
        ]

    def create(self, runner: Optional[Callable] = None) -> List[str]:
        """Execute the create plan (reference ``create():90`` runs the
        EC2 request). ``runner`` defaults to subprocess; the plan is
        returned either way and ``created`` records the pod."""
        plan = self.create_plan()
        if runner is not None:
            runner(plan)
        else:
            subprocess.run(plan, check=True)
        self.created.append(self.name)
        return plan


class HostProvisioner:
    """Per-host command execution (reference
    ``HostProvisioner.java:1`` — JSch SSH: uploadAndRun, runRemote).
    ``runner(cmd_list)`` abstracts the transport: default dry-run
    records, ``local_runner`` executes on this machine, and an
    ssh/gcloud runner executes remotely."""

    def __init__(self, host: str, runner: Optional[Callable] = None):
        self.host = host
        self.commands_run: List[List[str]] = []
        self._runner = runner

    @staticmethod
    def local_runner(cmd: List[str]):
        return subprocess.run(
            cmd, check=True, capture_output=True, text=True
        )

    def run(self, command) -> Optional[object]:
        cmd = (
            shlex.split(command) if isinstance(command, str)
            else list(command)
        )
        self.commands_run.append(cmd)
        if self._runner is None:
            return None  # dry-run: plan recorded, nothing executed
        return self._runner(cmd)

    def run_all(self, commands) -> None:
        for c in commands:
            self.run(c)


class ClusterSetup:
    """ClusterSetup analog (reference ``ClusterSetup.java:38``:
    create boxes -> provision master -> provision workers in threads).
    Here: build the pod plan, then the per-worker setup command list
    (install, fetch code, export the jax.distributed env, launch)."""

    def __init__(self, provisioner: TpuPodProvisioner,
                 setup_commands: Optional[List[str]] = None,
                 train_command: str = "python -m your_training_entry"):
        self.provisioner = provisioner
        self.setup_commands = setup_commands or []
        self.train_command = train_command

    def worker_launch_commands(
        self, coordinator_host: str, port: int = 8476
    ) -> List[str]:
        envs = self.provisioner.worker_env(coordinator_host, port)
        out = []
        for env in envs:
            exports = " ".join(
                # deliberately NOT shlex.quote'd: values are either
                # host:port/ints (shell-safe by construction) or a
                # ${VAR} placeholder the user substitutes when running
                # the emitted plan — quoting would freeze the literal
                f"{k}={v}" for k, v in sorted(env.items())
            )
            out.append(f"{exports} {self.train_command}")
        return out

    def plan(self, coordinator_host: str = "${COORDINATOR_HOST}",
             port: int = 8476) -> List[str]:
        """The full provisioning plan as shell lines — the auditable
        equivalent of ``ClusterSetup.exec()``."""
        lines = [shlex.join(self.provisioner.create_plan())]
        for cmd in self.setup_commands:
            lines.append(
                shlex.join(self.provisioner.ssh_plan(cmd))
            )
        for i, launch in enumerate(
            self.worker_launch_commands(coordinator_host, port)
        ):
            lines.append(
                shlex.join(
                    self.provisioner.ssh_plan(launch, worker=str(i))
                )
            )
        return lines

    def exec(self, coordinator_host: str, port: int = 8476,
             runner: Optional[Callable] = None) -> List[str]:
        """Run the plan (reference ``exec():76``). Dry-run (collect
        only) when ``runner`` is None — provisioning real fleets is a
        deliberate, credentialed action."""
        lines = self.plan(coordinator_host, port)
        if runner is not None:
            for line in lines:
                runner(shlex.split(line))
        return lines
