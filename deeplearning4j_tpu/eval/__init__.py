"""Evaluation (reference ``deeplearning4j-nn/.../eval``)."""

from deeplearning4j_tpu.eval.evaluation import (  # noqa: F401
    ConfusionMatrix,
    Evaluation,
    Prediction,
    RegressionEvaluation,
)
