"""ROC / AUC (reference ``eval/ROC.java`` — thresholded ROC for binary
classifiers, with AUC by trapezoidal integration), plus the
multi-class one-vs-all variant."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC. ``threshold_steps`` mirrors the reference's
    constructor; probabilities are binned into thresholds rather than
    sorted exactly (same algorithm as ``ROC.java``)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = threshold_steps
        n = threshold_steps + 1
        self._tp = np.zeros(n, dtype=np.int64)
        self._fp = np.zeros(n, dtype=np.int64)
        self._fn = np.zeros(n, dtype=np.int64)
        self._tn = np.zeros(n, dtype=np.int64)
        self._count = 0

    def eval(self, labels, predictions,
             mask: Optional[np.ndarray] = None) -> None:
        """labels: [n] or [n, 2] one-hot (positive = column 1);
        predictions: matching probabilities."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            pos = labels[:, 1]
            prob = predictions[:, 1]
        else:
            pos = labels.reshape(-1)
            prob = predictions.reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            pos, prob = pos[keep], prob[keep]
        pos = pos > 0.5
        thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        for i, t in enumerate(thresholds):
            pred_pos = prob >= t
            self._tp[i] += int(np.sum(pred_pos & pos))
            self._fp[i] += int(np.sum(pred_pos & ~pos))
            self._fn[i] += int(np.sum(~pred_pos & pos))
            self._tn[i] += int(np.sum(~pred_pos & ~pos))
        self._count += pos.size

    def get_roc_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)] (reference ``getResults``)."""
        out = []
        thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        for i, t in enumerate(thresholds):
            p = self._tp[i] + self._fn[i]
            n = self._fp[i] + self._tn[i]
            tpr = self._tp[i] / p if p else 0.0
            fpr = self._fp[i] / n if n else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    def calculate_auc(self) -> float:
        """Trapezoidal AUC (reference ``calculateAUC``)."""
        pts = sorted((fpr, tpr) for _, fpr, tpr in self.get_roc_curve())
        pts = [(0.0, 0.0)] + pts + [(1.0, 1.0)]
        auc = 0.0
        for (x0, y0), (x1, y1) in zip(pts[:-1], pts[1:]):
            auc += (x1 - x0) * (y0 + y1) / 2.0
        return float(auc)


class ROCMultiClass:
    """One-vs-all ROC per class (reference ``eval/ROCMultiClass.java``)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = threshold_steps
        self._rocs: List[ROC] = []

    def eval(self, labels, predictions,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n_classes = labels.shape[1]
        if not self._rocs:
            self._rocs = [ROC(self.threshold_steps) for _ in range(n_classes)]
        for c in range(n_classes):
            self._rocs[c].eval(labels[:, c], predictions[:, c], mask)

    def calculate_auc(self, c: int) -> float:
        return self._rocs[c].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))
