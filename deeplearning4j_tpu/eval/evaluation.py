"""Classification evaluation (reference: ``eval/Evaluation.java:46``,
``eval/ConfusionMatrix.java``).

Host-side numpy: evaluation is bookkeeping over argmaxes, not a TPU
workload; the device does the batched ``output()`` forward pass.
Argmax-tie semantics follow numpy's first-max rule (the reference uses
nd4j argmax, also first-max).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    def __init__(self, n_classes: int):
        self.n = n_classes
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, c: int) -> int:
        return int(self.matrix[c, :].sum())

    def predicted_total(self, c: int) -> int:
        return int(self.matrix[:, c].sum())

    def total(self) -> int:
        return int(self.matrix.sum())


class Prediction:
    """One prediction with its source-record metadata (reference
    ``eval/meta/Prediction.java:1``)."""

    def __init__(self, actual_class: int, predicted_class: int,
                 record_meta_data=None):
        self.actual_class = actual_class
        self.predicted_class = predicted_class
        self.record_meta_data = record_meta_data

    def __repr__(self):
        return (
            f"Prediction(actualClass={self.actual_class},"
            f"predictedClass={self.predicted_class},"
            f"RecordMetaData={self.record_meta_data})"
        )

    def __eq__(self, other):
        return (
            isinstance(other, Prediction)
            and self.actual_class == other.actual_class
            and self.predicted_class == other.predicted_class
            and self.record_meta_data == other.record_meta_data
        )


class Evaluation:
    """Accuracy/precision/recall/F1 + confusion matrix; with record
    metadata, per-prediction attribution (reference ``eval():202`` +
    ``getPredictionErrors``/``getPredictionsByActualClass``)."""

    def __init__(self, n_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None):
        self.labels = labels
        self.n_classes = n_classes or (len(labels) if labels else None)
        self.confusion: Optional[ConfusionMatrix] = None
        # (actual, predicted) -> [Prediction]; populated only when
        # record metadata is supplied (reference addToMetaConfusionMatrix)
        self._meta: Dict[tuple, List[Prediction]] = defaultdict(list)

    def _ensure(self, n: int) -> None:
        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None,
             record_meta_data: Optional[List] = None) -> None:
        """labels/predictions: one-hot or probability arrays,
        ``[batch, nClasses]`` or RNN ``[batch, nClasses, time]`` with
        optional ``[batch, time]`` mask (reference ``eval():190`` and
        ``evalTimeSeries``). ``record_meta_data`` (reference ``:202``):
        one metadata object per example; predictions become queryable
        via ``get_prediction_errors`` etc."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            # -> rows per (example, timestep), mask-filtered
            b, c, t = labels.shape
            lab2 = labels.transpose(0, 2, 1).reshape(-1, c)
            pred2 = predictions.transpose(0, 2, 1).reshape(-1, c)
            meta2 = (
                [m for m in record_meta_data for _ in range(t)]
                if record_meta_data is not None else None
            )
            if mask is not None:
                keep = np.asarray(mask).reshape(-1).astype(bool)
                lab2, pred2 = lab2[keep], pred2[keep]
                if meta2 is not None:
                    meta2 = [m for m, k in zip(meta2, keep) if k]
            self.eval(lab2, pred2, record_meta_data=meta2)
            return
        if labels.ndim == 2 and labels.shape[1] == 1:
            # single output column = binary with 0.5 threshold
            # (reference eval() nCols == 1 branch)
            self._ensure(2)
            actual = (labels[:, 0] > 0.5).astype(np.int64)
            guess = (predictions[:, 0] > 0.5).astype(np.int64)
        else:
            self._ensure(labels.shape[1])
            actual = labels.argmax(axis=1)
            guess = predictions.argmax(axis=1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            actual, guess = actual[keep], guess[keep]
            if record_meta_data is not None:
                record_meta_data = [
                    m for m, k in zip(record_meta_data, keep) if k
                ]
        for i, (a, g) in enumerate(zip(actual, guess)):
            a, g = int(a), int(g)
            self.confusion.add(a, g)
            if record_meta_data is not None and i < len(record_meta_data):
                self._meta[(a, g)].append(
                    Prediction(a, g, record_meta_data[i])
                )

    # -- record-metadata queries (reference Evaluation meta methods) ----

    def get_prediction_errors(self) -> List[Prediction]:
        """All misclassified predictions (reference
        ``getPredictionErrors``)."""
        out: List[Prediction] = []
        for (a, g), preds in sorted(self._meta.items()):
            if a != g:
                out.extend(preds)
        return out

    def get_predictions_by_actual_class(self, c: int) -> List[Prediction]:
        out: List[Prediction] = []
        for (a, _), preds in sorted(self._meta.items()):
            if a == c:
                out.extend(preds)
        return out

    def get_predictions_by_predicted_class(self, c: int) -> List[Prediction]:
        out: List[Prediction] = []
        for (_, g), preds in sorted(self._meta.items()):
            if g == c:
                out.extend(preds)
        return out

    def get_predictions(self, actual: int, predicted: int
                        ) -> List[Prediction]:
        return list(self._meta.get((actual, predicted), ()))

    # -- metrics -------------------------------------------------------

    def accuracy(self) -> float:
        m = self.confusion.matrix
        tot = m.sum()
        return float(np.trace(m) / tot) if tot else 0.0

    def precision(self, c: Optional[int] = None) -> float:
        if c is not None:
            pt = self.confusion.predicted_total(c)
            return self.confusion.get_count(c, c) / pt if pt else 0.0
        vals = [self.precision(i) for i in range(self.n_classes)
                if self.confusion.predicted_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c: Optional[int] = None) -> float:
        if c is not None:
            at = self.confusion.actual_total(c)
            return self.confusion.get_count(c, c) / at if at else 0.0
        vals = [self.recall(i) for i in range(self.n_classes)
                if self.confusion.actual_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c: Optional[int] = None) -> float:
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, c: int) -> float:
        fp = self.confusion.predicted_total(c) - self.confusion.get_count(c, c)
        neg = self.confusion.total() - self.confusion.actual_total(c)
        return fp / neg if neg else 0.0

    def merge(self, other: "Evaluation") -> "Evaluation":
        """Combine partial evaluations (reference: distributed eval
        ``EvaluationReduceFunction``)."""
        if other.confusion is None:
            return self
        self._ensure(other.n_classes)
        self.confusion.matrix += other.confusion.matrix
        for key, preds in other._meta.items():
            self._meta[key].extend(preds)
        return self

    def _label(self, c: int) -> str:
        if self.labels and c < len(self.labels):
            return str(self.labels[c])
        return str(c)

    def stats(self, print_confusion: bool = True) -> str:
        """Summary + per-class breakdown + confusion matrix (reference
        ``Evaluation.stats()`` layout)."""
        lines = [
            "========================Evaluation======================",
            f" Examples:  {self.confusion.total()}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "",
            " Per-class:",
        ]
        for c in range(self.n_classes):
            lines.append(
                f"  {self._label(c):>12}: precision={self.precision(c):.4f} "
                f"recall={self.recall(c):.4f} f1={self.f1(c):.4f} "
                f"(n={self.confusion.actual_total(c)})"
            )
        if print_confusion:
            lines += ["", " Confusion matrix (rows=actual, cols=predicted):"]
            # identical prefix + field widths as the data rows so the
            # column headers sit over their counts
            header = " ".join(
                f"{self._label(c):>8}" for c in range(self.n_classes)
            )
            lines.append(f"  {'':>12} {header}")
            for a in range(self.n_classes):
                row = " ".join(
                    f"{self.confusion.get_count(a, p):>8d}"
                    for p in range(self.n_classes)
                )
                lines.append(f"  {self._label(a):>12} {row}")
        lines.append(
            "========================================================"
        )
        return "\n".join(lines)


class RegressionEvaluation:
    """MSE/MAE/RMSE/R^2 per column (reference
    ``eval/RegressionEvaluation.java``)."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n_columns = n_columns
        self._sum_sq = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_lp = None
        self._count = 0

    def eval(self, labels, predictions,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            c = labels.shape[1]
            labels = labels.transpose(0, 2, 1).reshape(-1, c)
            predictions = predictions.transpose(0, 2, 1).reshape(-1, c)
            if mask is not None:
                keep = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[keep], predictions[keep]
        n = labels.shape[1]
        if self._sum_sq is None:
            self.n_columns = n
            self._sum_sq = np.zeros(n)
            self._sum_abs = np.zeros(n)
            self._sum_label = np.zeros(n)
            self._sum_label_sq = np.zeros(n)
            self._sum_pred = np.zeros(n)
            self._sum_lp = np.zeros(n)
        d = predictions - labels
        self._sum_sq += (d * d).sum(axis=0)
        self._sum_abs += np.abs(d).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label_sq += (labels * labels).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_lp += (labels * predictions).sum(axis=0)
        self._count += labels.shape[0]

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_sq[col] / self._count)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs[col] / self._count)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def correlation_r2(self, col: int) -> float:
        """Pearson correlation between labels and predictions
        (reference ``RegressionEvaluation.correlationR2``)."""
        n = self._count
        num = n * self._sum_lp[col] - self._sum_label[col] * self._sum_pred[col]
        den_l = n * self._sum_label_sq[col] - self._sum_label[col] ** 2
        # n*sum(p^2) reconstructed from sum_sq = sum((p-l)^2):
        # sum(p^2) = sum_sq + 2*sum(lp) - sum(l^2)
        sum_pred_sq = self._sum_sq[col] + 2 * self._sum_lp[col] - \
            self._sum_label_sq[col]
        den_p = n * sum_pred_sq - self._sum_pred[col] ** 2
        den = np.sqrt(den_l * den_p) if den_l * den_p > 0 else 0.0
        return float(num / den) if den else 0.0

    def r_squared(self, col: int) -> float:
        """Coefficient of determination 1 - SSres/SStot."""
        n = self._count
        ss_res = self._sum_sq[col]
        ss_tot = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self._sum_sq / self._count))

    def stats(self) -> str:
        cols = range(self.n_columns)
        return "\n".join(
            f"col {c}: MSE={self.mean_squared_error(c):.6f} "
            f"MAE={self.mean_absolute_error(c):.6f} "
            f"RMSE={self.root_mean_squared_error(c):.6f} "
            f"R2={self.correlation_r2(c):.4f}"
            for c in cols
        )
