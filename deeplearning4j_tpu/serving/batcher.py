"""Cross-request micro-batching for the serving hot path.

The PR-2 serving tier ran one predict per admitted request: an
8-worker server executed 8 single-row XLA programs instead of one
wide one, and every previously-unseen row count compiled a fresh
executable inside a request's deadline budget. Batching many small
requests into one accelerator dispatch is the canonical fix
(TensorFlow's serving design centers on it, PAPERS.md), and TVM's
ahead-of-time shape specialization motivates compiling a small fixed
set of *bucketed* shapes up front instead of on the request path.

Two pieces live here:

- ``BucketLadder``: the fixed set of row counts the server compiles
  for — powers of two up to ``max_batch_size`` by default. A batch of
  n valid rows pads to the smallest bucket >= n, so steady traffic
  touches only ``len(buckets)`` executables, all compiled during
  warmup (``compile_cache.py``) before the version takes traffic.
- ``MicroBatcher``: the coalescing policy the batch-drain workers
  run. Given the first queued item, it keeps draining until
  ``max_batch_size`` rows are collected or ``batch_timeout_ms``
  elapses — whichever first — and it is *adaptive*: when nothing else
  is in the system (admitted count == collected count) it dispatches
  immediately instead of sleeping out the timeout, so p50 at
  concurrency 1 pays no coalescing tax.

Stack/pad/slice helpers (``pad_rows``, ``fill_chunks``) are pure
functions so the padding contract is testable without a server.
"""

from __future__ import annotations

import queue
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


class BucketLadder:
    """The compiled-shape ladder: sorted row-count buckets.

    Default is powers of two up to ``max_batch_size`` (1, 2, 4, ...,
    max). ``bucket_for(n)`` returns the smallest bucket that holds n
    rows, or None when n overflows the ladder (the caller falls back
    to the solo path and pays its own compile).
    """

    def __init__(self, buckets: Optional[Sequence[int]] = None,
                 max_batch_size: int = 32):
        if buckets is None:
            if max_batch_size < 1:
                raise ValueError("max_batch_size must be >= 1")
            buckets = []
            b = 1
            while b < max_batch_size:
                buckets.append(b)
                b *= 2
            buckets.append(max_batch_size)
        self.buckets: List[int] = sorted({int(b) for b in buckets})
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("bucket ladder needs positive row counts")

    @property
    def max(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> Optional[int]:
        if rows < 1:
            raise ValueError("rows must be >= 1")
        for b in self.buckets:
            if b >= rows:
                return b
        return None

    def __repr__(self) -> str:
        return f"BucketLadder({self.buckets})"


def pad_rows(stacked: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a [n, ...] array with zero rows to [bucket, ...]. Zeros —
    not repeats — so a bug that reads a padding row produces visibly
    wrong output instead of a silently-duplicated neighbor."""
    n = stacked.shape[0]
    if n == bucket:
        return stacked
    if n > bucket:
        raise ValueError(f"{n} rows do not fit bucket {bucket}")
    pad = np.zeros((bucket - n,) + stacked.shape[1:], stacked.dtype)
    return np.concatenate([stacked, pad], axis=0)


def fill_chunks(pairs: List[Tuple[object, np.ndarray]],
                max_rows: int) -> List[List[Tuple[object, np.ndarray]]]:
    """Greedily pack (item, features) pairs into chunks of at most
    ``max_rows`` total rows, preserving arrival order. A single item
    wider than ``max_rows`` gets a chunk of its own (the caller routes
    it to the solo path)."""
    chunks: List[List[Tuple[object, np.ndarray]]] = []
    cur: List[Tuple[object, np.ndarray]] = []
    rows = 0
    for item, feats in pairs:
        r = int(feats.shape[0])
        if cur and rows + r > max_rows:
            chunks.append(cur)
            cur, rows = [], 0
        cur.append((item, feats))
        rows += r
    if cur:
        chunks.append(cur)
    return chunks


class MicroBatcher:
    """Coalescing policy for the batch-drain loop.

    ``collect(q, first, inflight)`` returns ``(items, carry)``: the
    items to batch now, plus at most one item that would have
    overflowed ``ladder.max`` rows (the caller starts the next batch
    with it instead of re-queueing, which would reorder).

    The wait is adaptive — continuous batching, not fixed windows.
    After draining everything immediately available, the batcher
    dispatches AT ONCE unless ``inflight()`` reports more admitted
    requests than it has collected — i.e. items are provably queued
    or mid-admission, so a short wait trades microseconds for a wider
    dispatch. The wait is one *blocking* ``get`` (it wakes the moment
    the straggler lands — never a poll loop burning the GIL the
    forward needs), bounded by ``batch_timeout_ms`` from the first
    empty read. Saturated closed-loop load therefore self-organizes:
    each dispatch collects everything in the system, the queue
    refills DURING the forward, and the next drain takes the lot. At
    concurrency 1 the inflight test fails immediately and solo-load
    p50 pays no coalescing tax.
    """

    def __init__(self, ladder: BucketLadder,
                 batch_timeout_ms: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        if batch_timeout_ms < 0:
            raise ValueError("batch_timeout_ms must be >= 0")
        self.ladder = ladder
        self.batch_timeout_ms = batch_timeout_ms
        self._clock = clock

    def collect(self, q: "queue.Queue", first,
                inflight: Callable[[], int]):
        items = [first]
        rows = first.rows
        give_up_at: Optional[float] = None
        while rows < self.ladder.max:
            try:
                nxt = q.get_nowait()
            except queue.Empty:
                if inflight() <= len(items):
                    break  # nothing else in the system: go now
                now = self._clock()
                if give_up_at is None:
                    give_up_at = now + self.batch_timeout_ms / 1000.0
                remaining = give_up_at - now
                if remaining <= 0:
                    break
                try:
                    nxt = q.get(timeout=remaining)
                except queue.Empty:
                    break  # window exhausted
            if rows + nxt.rows > self.ladder.max:
                return items, nxt  # overflow: starts the next batch
            items.append(nxt)
            rows += nxt.rows
        return items, None
