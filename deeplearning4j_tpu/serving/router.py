"""Thin HTTP router front for a fleet of ``ModelServer`` processes.

One serving process maxes out one dispatch stream; the next order of
magnitude is horizontal — N processes behind a router (the
TensorFlow-paper deployment story, PAPERS.md). This router is
deliberately thin: no model code, no jax import, just placement and
retries.

Routing policy, in order:

- **consistent hash on model id** (rendezvous / highest-random-
  weight hashing): each model name deterministically prefers one
  backend, so a tenant's traffic concentrates where its weights are
  already device-resident and its executables warm — adding or
  removing a backend only remaps the tenants that hashed to it,
  never the whole fleet;
- **health-aware**: a background thread polls every backend's
  ``/readyz``; unready backends drop out of candidate order until
  they recover (a backend that refuses connections is marked
  unhealthy immediately, without waiting for the next poll);
- **least-loaded fallback**: when the hash owner is carrying
  materially more router-side in-flight requests than the least
  loaded healthy backend (> ``spread_after`` extra), the request
  goes to the least loaded one instead — one hot tenant cannot
  starve a backend's other tenants while idle capacity sits nearby;
- **retry-next-on-shed**: a 503 (shed / quota / draining) or a
  connection failure moves to the next candidate; only when every
  healthy backend declined does the client see a 503 — so killing a
  backend mid-load costs zero requests, they finish on the survivors
  (``tests/test_fleet.py`` + the fleet chaos storm assert exactly
  that).

Predicts are idempotent, which is what makes blind connection-error
retries safe.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from deeplearning4j_tpu.observability.export import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    parse_format_query,
    prometheus_text,
)
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.serving.envelope import error_envelope

logger = logging.getLogger(__name__)

MAX_BODY = 64 * 1024 * 1024

# connection-level failures that mean "this backend never processed
# the request" — always safe to retry on the next backend
_RETRIABLE_ERRORS = (ConnectionError, http.client.HTTPException,
                     TimeoutError, OSError)


class _Backend:
    """Router-side view of one serving process."""

    __slots__ = ("host", "port", "healthy", "outstanding", "_lock")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.healthy = True  # optimistic until the first poll
        self.outstanding = 0
        self._lock = threading.Lock()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def enter(self) -> None:
        with self._lock:
            self.outstanding += 1

    def exit(self) -> None:
        with self._lock:
            self.outstanding -= 1


def _parse_backend(spec) -> Tuple[str, int]:
    if isinstance(spec, (tuple, list)):
        return str(spec[0]), int(spec[1])
    host, _, port = str(spec).rpartition(":")
    return host or "127.0.0.1", int(port)


class ServingRouter:
    """Spread requests across N backend ``ModelServer`` processes.

    ``backends`` is a list of ``"host:port"`` strings (or
    ``(host, port)`` pairs). The router serves::

        POST /predict   forwarded per the routing policy (module
                        docstring); the backend's response relays
                        verbatim, Retry-After included
        GET  /healthz   router process liveness
        GET  /readyz    200 iff at least one backend is ready
        GET  /metrics   routing counters + per-backend states (JSON
                        default, ?format=prometheus supported)

    ``retries`` bounds how many candidates one request may try
    (default: every backend once). ``spread_after`` is the
    outstanding-requests gap that triggers the least-loaded
    fallback.
    """

    def __init__(self, backends, host: str = "127.0.0.1",
                 port: int = 0, *,
                 health_interval: float = 0.25,
                 health_jitter: float = 0.2,
                 probe_timeout: float = 2.0,
                 request_timeout: float = 30.0,
                 retries: Optional[int] = None,
                 spread_after: int = 8,
                 seed: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        if not backends:
            raise ValueError("router needs at least one backend")
        if not 0.0 <= health_jitter < 1.0:
            raise ValueError("health_jitter must be in [0, 1)")
        self.backends = [_Backend(*_parse_backend(b)) for b in backends]
        self.health_interval = health_interval
        self.health_jitter = health_jitter
        self.probe_timeout = probe_timeout
        # seeded jitter: N routers polling the same backends must not
        # synchronize their /readyz probes into one thundering herd —
        # each waits interval * (1 ± jitter), deterministic per seed
        self._jitter_rng = random.Random(seed)
        self.request_timeout = request_timeout
        self.retries = (retries if retries is not None
                        else len(self.backends))
        self.spread_after = spread_after
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        reg = self.registry
        self._requests_total = reg.counter(
            "router_requests_total",
            help="router: requests accepted for forwarding",
        )._default()
        self._retries_total = reg.counter(
            "router_retries_total",
            help="router: forward attempts after the first "
                 "(shed or backend failure)",
        )._default()
        self._unroutable_total = reg.counter(
            "router_unroutable_total",
            help="router: 503s — every healthy backend declined",
        )._default()
        self._forwarded = reg.counter(
            "router_forwarded_total",
            help="router: responses relayed, by backend",
            labels=("backend",),
        )
        self._healthy_gauge = reg.gauge(
            "router_backend_healthy",
            help="router: backend readiness (1 ready / 0 not)",
            labels=("backend",),
        )
        self._outstanding_gauge = reg.gauge(
            "router_backend_outstanding",
            help="router: in-flight requests per backend",
            labels=("backend",),
        )
        self._httpd = _RouterHTTPServer((host, port),
                                        _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ServingRouter":
        self.check_health()  # honest /readyz from the first request
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="dl4j-router-health",
        )
        self._health_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-router",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=2)
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        self._httpd.server_close()

    def install_preemption_drain(self, handler=None) -> "ServingRouter":
        """Translate a preemption notice (SIGTERM/SIGINT or a
        simulated one) into this router's shutdown: in-flight
        forwards complete (``_httpd.shutdown`` waits out active
        handlers), then the listener closes. Uses the active
        ``resilience.preemption.PreemptionHandler``, installing a
        default one if none exists."""
        from deeplearning4j_tpu.resilience import preemption

        h = handler if handler is not None else preemption.active_handler()
        if h is None:
            h = preemption.PreemptionHandler().install()
        h.on_preemption(lambda reason: self.stop())
        return self

    # -- health ---------------------------------------------------------

    def _next_interval(self) -> float:
        """Jittered poll interval: ``health_interval * (1 ± jitter)``
        from the seeded RNG, so a fleet of routers decorrelates its
        probe times deterministically."""
        if self.health_jitter <= 0.0:
            return self.health_interval
        spread = self.health_jitter * (2.0 * self._jitter_rng.random()
                                       - 1.0)
        return self.health_interval * (1.0 + spread)

    def _health_loop(self) -> None:
        while not self._stop.wait(self._next_interval()):
            try:
                self.check_health()
            except Exception:
                logger.exception("router health poll failed")

    def check_health(self) -> int:
        """One poll of every backend's ``/readyz``; returns the
        healthy count. A probe timeout — the backend accepted the
        connection but never answered within ``probe_timeout`` — is
        treated exactly like a connection failure: immediately
        unhealthy, no benefit of the doubt until a probe succeeds."""
        n = 0
        for b in self.backends:
            ok = False
            try:
                conn = http.client.HTTPConnection(
                    b.host, b.port, timeout=self.probe_timeout
                )
                try:
                    conn.request("GET", "/readyz")
                    ok = conn.getresponse().status == 200
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException):
                # covers refused connections, socket timeouts
                # (TimeoutError is an OSError), and torn/invalid
                # responses from a wedged backend alike
                ok = False
            b.healthy = ok
            self._healthy_gauge.labels(b.address).set(1 if ok else 0)
            self._outstanding_gauge.labels(b.address).set(
                b.outstanding
            )
            n += ok
        return n

    # -- placement ------------------------------------------------------

    def candidates(self, model: str) -> List[_Backend]:
        """Healthy backends in try-order for ``model``: rendezvous-
        hash order, with the least-loaded backend promoted to the
        front when the hash owner is materially busier."""

        def weight(b: _Backend) -> int:
            h = hashlib.sha1(
                f"{model}|{b.address}".encode()
            ).digest()
            return int.from_bytes(h[:8], "big")

        healthy = [b for b in self.backends if b.healthy]
        if not healthy:
            return []
        order = sorted(healthy, key=weight, reverse=True)
        least = min(healthy, key=lambda b: b.outstanding)
        if (order[0] is not least
                and order[0].outstanding
                - least.outstanding > self.spread_after):
            order.remove(least)
            order.insert(0, least)
        return order

    # -- forwarding -----------------------------------------------------

    def forward(self, body: bytes
                ) -> "tuple[int, bytes, dict]":
        """Route one ``/predict`` body: pick candidates by the
        payload's model id, try each in order, relay the first
        non-shed response. Returns ``(status, body_bytes,
        headers)``."""
        model = ""
        try:
            payload = json.loads(body)
            if isinstance(payload, dict):
                model = str(payload.get("model") or "")
        except ValueError:
            pass  # backends own payload validation (400 envelope)
        self._requests_total.inc()
        order = self.candidates(model)
        attempts = 0
        last_shed = None
        for b in order:
            if attempts >= self.retries:
                break
            if attempts:
                self._retries_total.inc()
            attempts += 1
            b.enter()
            try:
                result = self._try_backend(b, body)
            finally:
                b.exit()
            if result is None:  # connection-level failure
                b.healthy = False  # next poll may restore it
                continue
            status, data, headers = result
            if status == 503 and len(order) > 1:
                last_shed = result  # shed here may succeed elsewhere
                continue
            self._forwarded.labels(b.address).inc()
            return result
        if last_shed is not None:
            return last_shed
        self._unroutable_total.inc()
        return 503, json.dumps(error_envelope(
            "no_backend", 503,
            "no healthy backend accepted the request",
            retry_after=1.0,
        )).encode(), {"Content-Type": "application/json",
                      "Retry-After": "1"}

    def _try_backend(self, b: _Backend, body: bytes):
        """One forward attempt; None means the backend never
        processed the request (safe to retry elsewhere)."""
        try:
            conn = http.client.HTTPConnection(
                b.host, b.port, timeout=self.request_timeout
            )
            try:
                conn.request("POST", "/predict", body=body, headers={
                    "Content-Type": "application/json",
                    "Content-Length": str(len(body)),
                })
                resp = conn.getresponse()
                data = resp.read()
                headers = {
                    k: v for k, v in resp.getheaders()
                    if k.lower() in ("content-type", "retry-after")
                }
                return resp.status, data, headers
            finally:
                conn.close()
        except _RETRIABLE_ERRORS:
            logger.warning("backend %s failed mid-request; retrying "
                           "on the next candidate", b.address)
            return None

    # -- introspection --------------------------------------------------

    def ready(self) -> bool:
        return any(b.healthy for b in self.backends)

    def metrics_snapshot(self) -> dict:
        return {
            "router_requests_total": self._requests_total.value,
            "router_retries_total": self._retries_total.value,
            "router_unroutable_total": self._unroutable_total.value,
            "backends": [
                {
                    "address": b.address,
                    "healthy": b.healthy,
                    "outstanding": b.outstanding,
                    "forwarded": self._forwarded.labels(
                        b.address
                    ).value,
                }
                for b in self.backends
            ],
        }

    def prometheus_metrics(self) -> str:
        return prometheus_text(self.registry)


class _RouterHTTPServer(ThreadingHTTPServer):
    # same rationale as the serving tier: bursts beyond the stdlib
    # backlog of 5 must reach the router's policy, not TCP resets
    request_queue_size = 128
    daemon_threads = True


def _make_handler(router: ServingRouter):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code: int, data: bytes, headers=None):
            try:
                self.send_response(code)
                hdrs = {"Content-Type": "application/json"}
                hdrs.update(headers or {})
                hdrs["Content-Length"] = str(len(data))
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)
            except OSError:
                pass  # client went away

        def _json(self, obj, code: int = 200):
            self._reply(code, json.dumps(obj).encode())

        def do_GET(self):
            route, fmt = parse_format_query(self.path)
            if route == "/healthz":
                self._json({"status": "ok",
                            "backends": len(router.backends)})
                return
            if route == "/readyz":
                if router.ready():
                    self._json({"status": "ready"})
                else:
                    self._json({"status": "unready",
                                "reasons": ["no_healthy_backend"]},
                               503)
                return
            if route == "/metrics":
                if fmt == "prometheus":
                    data = router.prometheus_metrics().encode()
                    self._reply(200, data, {
                        "Content-Type": PROMETHEUS_CONTENT_TYPE,
                    })
                else:
                    self._json(router.metrics_snapshot())
                return
            self._json(error_envelope("not_found", 404, "not found"),
                       404)

        def do_POST(self):
            if self.path != "/predict":
                self._json(error_envelope("not_found", 404,
                                          "not found"), 404)
                return
            raw = self.headers.get("Content-Length")
            try:
                length = int(raw) if raw is not None else -1
            except ValueError:
                length = -1
            if length < 0:
                self._json(error_envelope(
                    "length_required", 411,
                    "POST requires a Content-Length header",
                ), 411)
                return
            if length > MAX_BODY:
                self._json(error_envelope(
                    "payload_too_large", 413,
                    "request body exceeds the router cap",
                ), 413)
                return
            body = self.rfile.read(length)
            code, data, headers = router.forward(body)
            self._reply(code, data, headers)

    return Handler
