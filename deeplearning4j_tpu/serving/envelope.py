"""Shared JSON error envelope + strict HTTP body reading.

Every error any serving-tier endpoint (and the UI server's POST
routes) returns is ONE shape:

    {"error": {"status": "<machine-readable slug>", "code": <http>,
               "message": "...", ...detail}}

so clients branch on ``error.status`` instead of parsing prose, and a
chaos run can assert "every response is a well-formed envelope"
uniformly. Server-side faults (model/transform exceptions) carry an
*opaque* ``error_id`` — never the exception text or a stack trace —
derived deterministically from the exception (sha-1 of type+message),
so (a) nothing internal leaks to clients, (b) operators can grep logs
for the id, and (c) a seeded chaos storm reproduces the same bodies
bit-for-bit.

``read_request_body`` fixes two classic stdlib-handler bugs: a single
``rfile.read(n)`` may legally return fewer than ``n`` bytes (short
read -> the tail of the JSON silently vanishes), and a missing
Content-Length used to be treated as an empty body. Here POSTs
without Content-Length get ``411``, short reads get ``400`` with
expected/got byte counts, and oversize bodies get ``413`` before any
bytes are buffered.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Optional

logger = logging.getLogger(__name__)


def error_envelope(status: str, code: int, message: Optional[str] = None,
                   **detail) -> dict:
    """Build the shared error envelope. ``detail`` keys (e.g.
    ``expected=``/``got=`` for 422, ``elapsed=``/``budget=`` for 504,
    ``retry_after=`` for 503) merge into the error object."""
    err = {"status": status, "code": int(code)}
    if message is not None:
        err["message"] = message
    err.update(detail)
    return {"error": err}


def deadline_envelope(deadline,
                      message: str = "request exceeded its deadline",
                      ) -> dict:
    """The one 504 shape every expiry site shares — queued-expired,
    handler-wait expiry, and drop-before-stacking in the micro-batch
    drain loop — so clients see identical ``elapsed``/``budget``
    detail regardless of where in the pipeline the budget ran out."""
    return error_envelope(
        "deadline_exceeded", 504, message,
        elapsed=round(deadline.elapsed(), 4),
        budget=deadline.budget,
    )


def error_id_for(exc: BaseException) -> str:
    """Opaque, deterministic id for a server-side exception:
    stable across runs for the same fault (chaos replays bit-for-bit)
    yet revealing nothing about it. The full exception belongs in the
    server log next to this id, never in the response."""
    digest = hashlib.sha1(
        f"{type(exc).__name__}:{exc}".encode("utf-8", "replace")
    ).hexdigest()
    return f"e{digest[:12]}"


class HttpBodyError(Exception):
    """A request body failed to arrive intact; carries the response
    the handler should write."""

    def __init__(self, code: int, envelope: dict):
        super().__init__(envelope["error"].get("message", ""))
        self.code = code
        self.envelope = envelope


def read_request_body(handler, max_body: int) -> bytes:
    """Read exactly Content-Length bytes from a
    ``BaseHTTPRequestHandler``, or raise ``HttpBodyError`` with the
    right status: 411 (no Content-Length), 400 (unparseable length or
    short read), 413 (over ``max_body``)."""
    raw = handler.headers.get("Content-Length")
    if raw is None:
        raise HttpBodyError(411, error_envelope(
            "length_required", 411,
            "POST requires a Content-Length header",
        ))
    try:
        length = int(raw)
        if length < 0:
            raise ValueError
    except ValueError:
        raise HttpBodyError(400, error_envelope(
            "bad_request", 400, f"bad Content-Length: {raw!r}",
        )) from None
    if length > max_body:
        raise HttpBodyError(413, error_envelope(
            "payload_too_large", 413,
            "request body exceeds the server cap",
            limit=max_body, got=length,
        ))
    chunks = []
    remaining = length
    while remaining:
        b = handler.rfile.read(min(remaining, 1 << 20))
        if not b:  # EOF before Content-Length bytes arrived
            raise HttpBodyError(400, error_envelope(
                "short_body", 400,
                "connection closed before the full body arrived",
                expected=length, got=length - remaining,
            ))
        chunks.append(b)
        remaining -= len(b)
    return b"".join(chunks)
