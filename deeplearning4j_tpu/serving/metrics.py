"""Serving observability, rebased onto the shared
``observability.MetricsRegistry``.

The robustness behaviors (shedding, deadline kills, breaker trips,
reloads) are only trustworthy if they are *observable*: the
``/metrics`` endpoint serves this snapshot as JSON (and, since the
observability subsystem landed, Prometheus text exposition via
``/metrics?format=prometheus``) so a saturation test — or an
operator — can see exactly how many requests were shed vs admitted
vs timed out, and what the latency quantiles were.

``ServingMetrics`` keeps its original surface (``incr``/``get``/
``record_latency``/``try_enter``/``snapshot`` — the admission bound
and every call site are unchanged) but every instrument now lives in
a per-server ``MetricsRegistry``: counters are registry counters,
the latency and queue-delay reservoirs are registry summaries, the
batch-occupancy histogram a registry histogram, and ``inflight`` is
mirrored into a gauge — so one exporter renders the whole set. The
canonical ``Reservoir`` and ``Histogram`` primitives moved to
``observability/metrics.py``; they are re-exported here so existing
imports (``from deeplearning4j_tpu.serving.metrics import
Reservoir``) keep working.

The micro-batching layer (``batcher.py``) adds two more instruments:
a **batch-occupancy histogram** (valid rows per dispatch, bucketed on
the shape ladder — the direct readout of how well coalescing is
working), a **queue-delay reservoir** (admission to batch-drain
pickup — the latency cost requests pay for coalescing), and the
compile counters ``xla_compiles_total`` /
``post_warmup_compiles_total`` (``compile_cache.py``) that make
"zero compiles under steady bucketed load" falsifiable from
``/metrics`` alone.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    Histogram,
    MetricsRegistry,
    Reservoir,
)

# name -> help, rendered into the Prometheus HELP lines and the
# ARCHITECTURE.md signal catalog (scripts/lint_metrics.py keeps the
# two in sync)
COUNTER_HELP = {
    "requests_total": "every HTTP request seen",
    "predictions_total": "successful predicts",
    "shed_total": "503: queue full / draining",
    "breaker_rejected_total": "503: circuit open",
    "deadline_timeout_total": "504: deadline exceeded",
    "client_error_total": "4xx responses",
    "server_error_total": "5xx from model/transform faults",
    "abandoned_total": "worker finished after caller's 504",
    "reload_total": "successful hot swaps",
    "reload_failure_total": "failed reload attempts (old kept)",
    "reload_skipped_total": "no-op reloads (step already serving)",
    "batches_total": "batched dispatches executed",
    "batched_predictions_total": "requests answered via a batch",
    "solo_fallback_total": "requests too wide for the ladder",
    "batch_expired_total": "dropped (504) before stacking",
    "xla_compiles_total": "forwards on a never-seen shape",
    "post_warmup_compiles_total": "ladder escapes (recompile guard)",
    "warmup_predicts_total": "eager bucket warmup forwards",
    "quota_rejected_total": "503: a tenant exceeded its own quota",
}

# per-tenant mirrors of the request-outcome counters, labeled by
# model name so one /metrics scrape reads every tenant's health. The
# unlabeled process totals above are unchanged (dashboards and the
# admission bound keep their meaning); these fan the same events out
# per model. scripts/lint_metrics.py reads this table too.
MODEL_COUNTER_HELP = {
    "model_requests_total": "per-model: requests routed to the tenant",
    "model_predictions_total": "per-model: successful predicts",
    "model_shed_total": "per-model: 503s (quota / queue / draining)",
    "model_deadline_timeout_total": "per-model: 504 deadline exceeded",
}


class ServingMetrics:
    """Thread-safe counter set + latency reservoir for one server,
    backed by a per-server ``MetricsRegistry`` (pass ``registry=`` to
    share one, e.g. a disabled ``NULL_REGISTRY`` for overhead-free
    serving)."""

    COUNTERS = tuple(COUNTER_HELP)

    def __init__(self, reservoir_size: int = 1024,
                 occupancy_buckets: Optional[Sequence[int]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self._lock = threading.Lock()
        # store the resolved unlabeled instruments, not the family
        # proxies: one attribute hop fewer per update on the serving
        # hot path (the overhead bench notices)
        self._counters = {
            name: self.registry.counter(
                name, help=COUNTER_HELP[name]
            )._default()
            for name in self.COUNTERS
        }
        self._latency = self.registry.summary(
            "latency_ms", reservoir_size=reservoir_size,
            help="end-to-end request latency (ms), recent window",
        )._default()
        self._queue_delay = self.registry.summary(
            "queue_delay_ms", reservoir_size=reservoir_size,
            help="admission to batch-drain pickup (ms), recent window",
        )._default()
        self._occupancy = (
            self.registry.histogram(
                "batch_occupancy_rows", occupancy_buckets,
                help="valid rows per batched dispatch "
                     "(buckets = the shape ladder)",
            )._default()
            if occupancy_buckets else None
        )
        self._inflight_gauge = self.registry.gauge(
            "inflight", help="admitted requests not yet answered"
        )._default()
        self.inflight = 0  # admitted, response not yet written
        # per-tenant labeled families ("model" label). Instruments
        # resolve lazily per tenant and cache in a plain dict — the
        # hot path pays one dict get after the first request
        self._model_counters = {
            name: self.registry.counter(
                name, help=MODEL_COUNTER_HELP[name], labels=("model",)
            )
            for name in MODEL_COUNTER_HELP
        }
        self._model_latency = self.registry.summary(
            "model_latency_ms", reservoir_size=reservoir_size,
            help="per-model end-to-end latency (ms), recent window",
            labels=("model",),
        )
        self._model_occupancy = (
            self.registry.histogram(
                "model_batch_occupancy_rows", occupancy_buckets,
                help="per-model valid rows per batched dispatch",
                labels=("model",),
            )
            if occupancy_buckets else None
        )
        self._model_cache: dict = {}
        # completion timestamps feed the adaptive Retry-After: the
        # drain rate is completions-per-second over this window
        self._completions: "deque[float]" = deque(maxlen=128)
        self._completions_lock = threading.Lock()

    def incr(self, name: str, n: int = 1) -> None:
        if not self.registry.enabled:
            self._counters[name]  # unknown names still KeyError
            return
        self._counters[name].inc(n)

    def get(self, name: str) -> int:
        return self._counters[name].value

    def record_latency(self, seconds: float) -> None:
        if self.registry.enabled:
            self._latency.observe(seconds * 1000.0)

    def record_queue_delay(self, seconds: float) -> None:
        if self.registry.enabled:
            self._queue_delay.observe(seconds * 1000.0)

    def record_batch(self, n_valid: int, bucket: int,
                     model: Optional[str] = None) -> None:
        """One batched dispatch: ``n_valid`` real rows padded to
        ``bucket``. Occupancy is recorded in rows (the histogram's
        boundaries are the ladder), fill ratio rides in the mean."""
        if not self.registry.enabled:
            return
        self._counters["batches_total"].inc()
        if self._occupancy is not None:
            self._occupancy.observe(n_valid)
        if model is not None and self._model_occupancy is not None:
            self._model_instrument(
                self._model_occupancy, model
            ).observe(n_valid)

    # -- per-tenant ("model" label) instruments -------------------------

    def _model_instrument(self, family, model: str):
        key = (family.name, model)
        inst = self._model_cache.get(key)
        if inst is None:
            inst = family.labels(model)
            self._model_cache[key] = inst
        return inst

    def incr_model(self, name: str, model: str, n: int = 1) -> None:
        if not self.registry.enabled:
            self._model_counters[name]  # unknown names still KeyError
            return
        self._model_instrument(self._model_counters[name], model).inc(n)

    def get_model(self, name: str, model: str) -> int:
        return self._model_instrument(
            self._model_counters[name], model
        ).value

    def record_model_latency(self, model: str, seconds: float) -> None:
        if self.registry.enabled:
            self._model_instrument(self._model_latency, model).observe(
                seconds * 1000.0
            )

    # -- drain rate (adaptive Retry-After input) ------------------------

    def note_completion(self, now: float) -> None:
        """One request left the system (answered, not shed at the
        door). The recent completion rate IS the drain rate a shed
        client should pace its retry by — exact even in no-op
        registry mode, like the admission bound."""
        with self._completions_lock:
            self._completions.append(now)

    def drain_rate(self) -> Optional[float]:
        """Completions per second over the recent window; None until
        two completions exist (callers fall back to the static
        knob)."""
        with self._completions_lock:
            if len(self._completions) < 2:
                return None
            span = self._completions[-1] - self._completions[0]
            if span <= 0:
                return None
            return (len(self._completions) - 1) / span

    # NB: inflight accounting below is the ADMISSION BOUND, not
    # telemetry — it stays exact in no-op mode; only the gauge
    # mirror (export-facing) is skipped when the registry is off.

    def enter(self) -> None:
        with self._lock:
            self.inflight += 1
            if self.registry.enabled:
                self._inflight_gauge.set(self.inflight)

    def try_enter(self, limit: int) -> bool:
        """Atomic admission check: admit only while fewer than
        ``limit`` requests are in the system (workers + wait queue).
        This counter — not the queue's own size — is the admission
        bound, so k executing + q queued is exactly the capacity."""
        with self._lock:
            if self.inflight >= limit:
                return False
            self.inflight += 1
            if self.registry.enabled:
                self._inflight_gauge.set(self.inflight)
            return True

    def exit(self) -> None:
        with self._lock:
            self.inflight -= 1
            if self.registry.enabled:
                self._inflight_gauge.set(self.inflight)

    def snapshot(self) -> dict:
        out = {name: c.value for name, c in self._counters.items()}
        with self._lock:
            out["inflight"] = self.inflight
        out["latency_ms"] = self._latency.snapshot()
        out["queue_delay_ms"] = self._queue_delay.snapshot()
        if self._occupancy is not None:
            out["batch_occupancy_rows"] = self._occupancy.snapshot()
        models = self.model_snapshot()
        if models:
            out["models"] = models
        return out

    def model_snapshot(self) -> dict:
        """{model: {counter values + latency quantiles}} — per-tenant
        p50/p99 from one scrape."""
        out: dict = {}
        for name, fam in self._model_counters.items():
            for inst in fam.children():
                model = inst.label_values[0]
                out.setdefault(model, {})[name] = inst.value
        for inst in self._model_latency.children():
            out.setdefault(inst.label_values[0], {})[
                "latency_ms"
            ] = inst.snapshot()
        return out
