"""Serving observability: counters + a fixed-size latency reservoir.

The robustness behaviors (shedding, deadline kills, breaker trips,
reloads) are only trustworthy if they are *observable*: the
``/metrics`` endpoint serves this snapshot as JSON so a saturation
test — or an operator — can see exactly how many requests were shed
vs admitted vs timed out, and what the latency quantiles were.

The reservoir is a fixed-size ring of the most recent latencies:
bounded memory however long the server runs, quantiles computed on
demand from a sorted copy (nearest-rank). Recency bias is the point —
serving dashboards want "how slow is it NOW", not a since-boot
average.

The micro-batching layer (``batcher.py``) adds two more instruments:
a **batch-occupancy histogram** (valid rows per dispatch, bucketed on
the shape ladder — the direct readout of how well coalescing is
working) plus mean fill ratio, a **queue-delay reservoir** (admission
to batch-drain pickup — the latency cost requests pay for
coalescing), and the compile counters ``xla_compiles_total`` /
``post_warmup_compiles_total`` (``compile_cache.py``) that make
"zero compiles under steady bucketed load" falsifiable from
``/metrics`` alone.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence


class Reservoir:
    """Ring buffer of the last ``size`` observations with
    nearest-rank quantiles."""

    def __init__(self, size: int = 1024):
        if size < 1:
            raise ValueError("size must be >= 1")
        self.size = size
        self._ring: List[float] = []
        self._next = 0
        self.count = 0  # total ever recorded

    def record(self, value: float) -> None:
        if len(self._ring) < self.size:
            self._ring.append(value)
        else:
            self._ring[self._next] = value
        self._next = (self._next + 1) % self.size
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        if not self._ring:
            return None
        s = sorted(self._ring)
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "max": max(self._ring) if self._ring else None,
        }


class Histogram:
    """Fixed-boundary counting histogram: ``record(v)`` counts v into
    the first boundary >= v (an overflow bin catches the rest).
    Bounded memory, O(log b) record — the batch-occupancy instrument
    (boundaries = the shape-bucket ladder)."""

    def __init__(self, boundaries: Sequence[float]):
        if not boundaries:
            raise ValueError("histogram needs at least one boundary")
        self.boundaries = sorted(float(b) for b in boundaries)
        self._counts = [0] * (len(self.boundaries) + 1)
        self.count = 0
        self.total = 0.0

    def record(self, value: float) -> None:
        self._counts[bisect.bisect_left(self.boundaries, value)] += 1
        self.count += 1
        self.total += value

    def snapshot(self) -> dict:
        buckets = {}
        for b, c in zip(self.boundaries, self._counts):
            buckets[f"le_{b:g}"] = c
        buckets["overflow"] = self._counts[-1]
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else None,
            "buckets": buckets,
        }


class ServingMetrics:
    """Thread-safe counter set + latency reservoir for one server."""

    COUNTERS = (
        "requests_total",        # every HTTP request seen
        "predictions_total",     # successful predicts
        "shed_total",            # 503: queue full / draining
        "breaker_rejected_total",  # 503: circuit open
        "deadline_timeout_total",  # 504
        "client_error_total",    # 4xx
        "server_error_total",    # 5xx from model/transform faults
        "abandoned_total",       # worker finished after caller's 504
        "reload_total",          # successful hot swaps
        "reload_failure_total",  # failed reload attempts (old kept)
        # -- micro-batching + compile accounting --------------------
        "batches_total",           # batched dispatches executed
        "batched_predictions_total",  # requests answered via a batch
        "solo_fallback_total",     # requests too wide for the ladder
        "batch_expired_total",     # dropped (504) before stacking
        "xla_compiles_total",      # forwards on a never-seen shape
        "post_warmup_compiles_total",  # ladder escapes (guard)
        "warmup_predicts_total",   # eager bucket warmup forwards
    )

    def __init__(self, reservoir_size: int = 1024,
                 occupancy_buckets: Optional[Sequence[int]] = None):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self._latency = Reservoir(reservoir_size)
        self._queue_delay = Reservoir(reservoir_size)
        self._occupancy = (
            Histogram(occupancy_buckets) if occupancy_buckets else None
        )
        self.inflight = 0  # admitted, response not yet written

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters[name]

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._latency.record(seconds * 1000.0)

    def record_queue_delay(self, seconds: float) -> None:
        with self._lock:
            self._queue_delay.record(seconds * 1000.0)

    def record_batch(self, n_valid: int, bucket: int) -> None:
        """One batched dispatch: ``n_valid`` real rows padded to
        ``bucket``. Occupancy is recorded in rows (the histogram's
        boundaries are the ladder), fill ratio rides in the mean."""
        with self._lock:
            self._counters["batches_total"] += 1
            if self._occupancy is not None:
                self._occupancy.record(n_valid)

    def enter(self) -> None:
        with self._lock:
            self.inflight += 1

    def try_enter(self, limit: int) -> bool:
        """Atomic admission check: admit only while fewer than
        ``limit`` requests are in the system (workers + wait queue).
        This counter — not the queue's own size — is the admission
        bound, so k executing + q queued is exactly the capacity."""
        with self._lock:
            if self.inflight >= limit:
                return False
            self.inflight += 1
            return True

    def exit(self) -> None:
        with self._lock:
            self.inflight -= 1

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["inflight"] = self.inflight
            out["latency_ms"] = self._latency.snapshot()
            out["queue_delay_ms"] = self._queue_delay.snapshot()
            if self._occupancy is not None:
                out["batch_occupancy_rows"] = self._occupancy.snapshot()
            return out
