"""Multi-tenant model registry + LRU device-memory weight paging.

One serving process, N named models. The TensorFlow-paper deployment
story (PAPERS.md) is many models/versions sharing one accelerator:
the fleet's working set exceeds device memory, so weights page —
cold models' parameters live in host memory and fault back onto the
device on demand, exactly like an OS page cache with a pin list for
the tenants that must never miss.

- ``ModelEntry``: one tenant — its current ``ModelVersion`` (the
  same immutable snapshot object ``server.py`` always swapped on hot
  reload, so in-flight requests still finish on the version they
  started with), its admission quota and deadline override, its
  optional per-model bucket ladder, and its paging state
  (``device``/``host`` residency, parameter bytes, LRU timestamp).
- ``ModelRegistry``: the name -> entry map plus the paging policy.
  ``touch(entry)`` brackets every forward: it bumps the LRU clock,
  faults the weights back in when evicted (measured in
  ``weight_pagein_ms``), and marks the entry *executing* so the
  evictor never pages a model out from under a running forward.
  ``max_device_models`` / ``max_device_bytes`` bound the resident
  set; the victim is always the least-recently-used unpinned idle
  entry.

Paging moves ONLY the weights (``params`` + ``state`` pytrees):
device -> host is ``jax.device_get`` into numpy, host -> device is
``jax.device_put`` back. Shapes and dtypes never change, so the
jitted executables (and any AOT-installed ones, ``compile/aot.py``)
stay valid across a page-out/page-in cycle — a fault-in costs one
transfer, never a compile, and outputs are bitwise identical
(``tests/test_fleet.py`` asserts both).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

DEVICE = "device"
HOST = "host"

# paging moves these model attributes (pytrees of arrays); anything
# else a model carries (conf, updater defs, jit caches) stays put
_PAGEABLE_ATTRS = ("params", "state")


class ModelVersion:
    """One immutable (model, version) pair. Workers snapshot the
    reference at predict start, so an atomic swap never changes the
    model under an in-flight request. ``shapes`` is this version's
    compile-cache record (the set of input shapes it has executed,
    warmed over the bucket ladder before the version takes
    traffic)."""

    __slots__ = ("model", "version", "source", "shapes")

    def __init__(self, model, version: int, source: str, shapes=None):
        self.model = model
        self.version = version
        self.source = source
        self.shapes = shapes


def _tree_device_bytes(model) -> int:
    """Bytes of pageable weight arrays currently on ``model``."""
    import jax

    total = 0
    for attr in _PAGEABLE_ATTRS:
        tree = getattr(model, attr, None)
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            total += int(getattr(leaf, "nbytes", 0))
    return total


def page_out_model(model) -> int:
    """Move the model's weight pytrees device -> host (numpy).
    Returns the bytes moved. Models with no pageable arrays (stubs)
    move 0 bytes and are otherwise untouched."""
    import jax

    moved = 0
    for attr in _PAGEABLE_ATTRS:
        tree = getattr(model, attr, None)
        if tree is None:
            continue

        def to_host(leaf):
            nonlocal moved
            if isinstance(leaf, jax.Array):
                moved += int(leaf.nbytes)
                return np.asarray(jax.device_get(leaf))
            return leaf

        setattr(model, attr, jax.tree_util.tree_map(to_host, tree))
    return moved


def page_in_model(model) -> int:
    """Move the model's weight pytrees host -> device. Returns the
    bytes moved. Blocks until the transfer completes so the measured
    fault-in latency is the real transfer cost, not an async
    enqueue."""
    import jax

    moved = 0
    trees = []
    for attr in _PAGEABLE_ATTRS:
        tree = getattr(model, attr, None)
        if tree is None:
            continue

        def to_dev(leaf):
            nonlocal moved
            if isinstance(leaf, np.ndarray):
                moved += int(leaf.nbytes)
                return jax.device_put(leaf)
            return leaf

        new = jax.tree_util.tree_map(to_dev, tree)
        setattr(model, attr, new)
        trees.append(new)
    if moved:
        jax.block_until_ready(trees)
    return moved


class ModelEntry:
    """One tenant: current version + admission policy + paging state.
    Residency/LRU fields are guarded by the owning registry's lock;
    the admission counter has its own (it is touched on the handler
    fast path, never during paging)."""

    __slots__ = ("name", "current", "quota", "deadline", "pinned",
                 "ladder", "source_path", "resident", "nbytes",
                 "last_used", "executing", "inflight", "_adm_lock")

    def __init__(self, name: str, current: ModelVersion, *,
                 quota: Optional[int] = None,
                 deadline: Optional[float] = None,
                 pinned: bool = False, ladder=None,
                 source_path: Optional[str] = None):
        self.name = name
        self.current = current
        self.quota = quota
        self.deadline = deadline
        self.pinned = pinned
        self.ladder = ladder
        self.source_path = source_path
        self.resident = DEVICE
        self.nbytes = 0
        self.last_used = 0.0
        self.executing = 0   # forwards running now (evictor skips >0)
        self.inflight = 0    # admitted, not yet answered (quota bound)
        self._adm_lock = threading.Lock()

    # -- per-tenant admission (the quota bound) -------------------------

    def admit(self) -> bool:
        """Count one request against this tenant's quota; False sheds
        it. ``quota=None`` means the tenant only shares the global
        bound."""
        with self._adm_lock:
            if self.quota is not None and self.inflight >= self.quota:
                return False
            self.inflight += 1
            return True

    def exit_admission(self) -> None:
        with self._adm_lock:
            self.inflight -= 1


class ModelRegistry:
    """Name -> ``ModelEntry`` map + the LRU weight-paging policy.

    ``max_device_models`` / ``max_device_bytes`` bound the
    device-resident set (None = unbounded: nothing ever pages, the
    single-tenant behavior). Pinned entries never page out. All
    residency transitions happen under one lock; ``touch``/
    ``release`` bracket forwards so a model is never paged out while
    executing.
    """

    def __init__(self, *, max_device_models: Optional[int] = None,
                 max_device_bytes: Optional[int] = None,
                 metrics_registry=None,
                 clock=time.monotonic):
        if max_device_models is not None and max_device_models < 1:
            raise ValueError("max_device_models must be >= 1")
        self.max_device_models = max_device_models
        self.max_device_bytes = max_device_bytes
        self._entries: Dict[str, ModelEntry] = {}
        self._default_name: Optional[str] = None
        self._lock = threading.RLock()
        self._clock = clock
        reg = metrics_registry
        self._pagein_total = reg.counter(
            "weight_pagein_total",
            help="paging: cold-model fault-ins (host -> device)",
        ) if reg is not None else None
        self._evict_total = reg.counter(
            "weight_evict_total",
            help="paging: LRU weight evictions (device -> host)",
        ) if reg is not None else None
        self._pagein_ms = reg.summary(
            "weight_pagein_ms",
            help="paging: measured fault-in transfer latency",
        ) if reg is not None else None
        self._pageout_ms = reg.summary(
            "weight_pageout_ms",
            help="paging: measured eviction transfer latency",
        ) if reg is not None else None
        self._resident_models = reg.gauge(
            "device_resident_models",
            help="paging: models with device-resident weights",
        ) if reg is not None else None
        self._resident_bytes = reg.gauge(
            "device_resident_bytes",
            help="paging: bytes of device-resident weights",
        ) if reg is not None else None

    # -- membership -----------------------------------------------------

    def add(self, name: str, current: ModelVersion, *,
            quota: Optional[int] = None,
            deadline: Optional[float] = None,
            pinned: bool = False, ladder=None,
            source_path: Optional[str] = None,
            default: bool = False) -> ModelEntry:
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            entry = ModelEntry(
                name, current, quota=quota, deadline=deadline,
                pinned=pinned, ladder=ladder, source_path=source_path,
            )
            entry.nbytes = _tree_device_bytes(current.model)
            entry.last_used = self._clock()
            self._entries[name] = entry
            if default or self._default_name is None:
                self._default_name = name
            self._publish_gauges()
            return entry

    def entry(self, name: Optional[str] = None) -> ModelEntry:
        """Resolve a tenant by name (None = the default tenant).
        Raises ``KeyError`` with the known names for the 404 path."""
        with self._lock:
            if name is None:
                name = self._default_name
            e = self._entries.get(name)
            if e is None:
                raise KeyError(
                    f"unknown model {name!r}; serving "
                    f"{sorted(self._entries)}"
                )
            return e

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    @property
    def default_name(self) -> Optional[str]:
        return self._default_name

    def __len__(self) -> int:
        return len(self._entries)

    def pin(self, name: str, pinned: bool = True) -> None:
        """(Un)pin a tenant. Pinning faults the weights in NOW —
        a pinned tenant must never pay a miss on the request path."""
        with self._lock:
            entry = self.entry(name)
            entry.pinned = pinned
            if pinned:
                self._ensure_resident(entry)
                self._enforce_budget(protect=entry)

    # -- the forward bracket --------------------------------------------

    def touch(self, entry: ModelEntry) -> Optional[float]:
        """Called just before a forward on ``entry``: bump the LRU
        clock, mark it executing (evictor-proof), and fault the
        weights in when paged out. Returns the fault-in milliseconds
        (None = was already resident). Pair with ``release``."""
        with self._lock:
            entry.last_used = self._clock()
            entry.executing += 1
            try:
                ms = self._ensure_resident(entry)
                if ms is not None:
                    # the fault-in may have pushed the resident set
                    # over budget: evict coldest idle entries
                    self._enforce_budget(protect=entry)
            except BaseException:
                entry.executing -= 1  # a failed fault-in must not
                raise                 # wedge the entry as "executing"
            return ms

    def release(self, entry: ModelEntry) -> None:
        with self._lock:
            entry.executing -= 1

    def swap(self, entry: ModelEntry, new_version: ModelVersion) -> None:
        """Atomic hot-reload swap for one tenant. The new weights are
        device-resident (restore + warmup just ran them)."""
        with self._lock:
            entry.current = new_version
            entry.resident = DEVICE
            entry.nbytes = _tree_device_bytes(new_version.model)
            entry.last_used = self._clock()
            self._enforce_budget(protect=entry)
            self._publish_gauges()

    # -- paging policy (all under self._lock) ---------------------------

    def _ensure_resident(self, entry: ModelEntry) -> Optional[float]:
        if entry.resident == DEVICE:
            return None
        t0 = time.perf_counter()
        page_in_model(entry.current.model)
        ms = (time.perf_counter() - t0) * 1000.0
        entry.resident = DEVICE
        if self._pagein_total is not None:
            self._pagein_total.inc()
            self._pagein_ms.observe(ms)
        logger.info("paged model %r in (%d bytes, %.2f ms)",
                    entry.name, entry.nbytes, ms)
        self._publish_gauges()
        return ms

    def _evict(self, entry: ModelEntry) -> None:
        t0 = time.perf_counter()
        page_out_model(entry.current.model)
        ms = (time.perf_counter() - t0) * 1000.0
        entry.resident = HOST
        if self._evict_total is not None:
            self._evict_total.inc()
            self._pageout_ms.observe(ms)
        logger.info("evicted model %r to host (%d bytes, %.2f ms)",
                    entry.name, entry.nbytes, ms)

    def _resident_set(self) -> List[ModelEntry]:
        return [e for e in self._entries.values()
                if e.resident == DEVICE]

    def _over_budget(self) -> bool:
        res = self._resident_set()
        if (self.max_device_models is not None
                and len(res) > self.max_device_models):
            return True
        if (self.max_device_bytes is not None
                and sum(e.nbytes for e in res) > self.max_device_bytes):
            return True
        return False

    def _enforce_budget(self,
                        protect: Optional[ModelEntry] = None) -> int:
        """Evict least-recently-used unpinned idle entries until the
        resident set fits the budget. Returns evictions performed.
        Stops (over budget, logged) when every remaining candidate is
        pinned, executing, or the protected entry — correctness over
        the budget, never a forward on half-paged weights."""
        evicted = 0
        while self._over_budget():
            victims = [
                e for e in self._resident_set()
                if not e.pinned and e.executing == 0 and e is not protect
            ]
            if not victims:
                logger.warning(
                    "weight paging over budget but every resident "
                    "model is pinned or executing; not evicting"
                )
                break
            self._evict(min(victims, key=lambda e: e.last_used))
            evicted += 1
        if evicted:
            self._publish_gauges()
        return evicted

    def enforce_budget(self) -> int:
        """Public entry point (used after start()-time warmup, which
        intentionally runs every tenant once through the device)."""
        with self._lock:
            return self._enforce_budget()

    def _publish_gauges(self) -> None:
        if self._resident_models is None:
            return
        res = self._resident_set()
        self._resident_models.set(len(res))
        self._resident_bytes.set(sum(e.nbytes for e in res))

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        """The ``/metrics`` paging block + per-tenant states."""
        with self._lock:
            res = self._resident_set()
            now = self._clock()
            return {
                "max_device_models": self.max_device_models,
                "max_device_bytes": self.max_device_bytes,
                "device_resident_models": len(res),
                "device_resident_bytes": sum(e.nbytes for e in res),
                "weight_pagein_total": (
                    self._pagein_total.value
                    if self._pagein_total is not None else 0
                ),
                "weight_evict_total": (
                    self._evict_total.value
                    if self._evict_total is not None else 0
                ),
                "weight_pagein_ms": (
                    self._pagein_ms.snapshot()
                    if self._pagein_ms is not None else None
                ),
                "models": {
                    e.name: {
                        "version": e.current.version,
                        "resident": e.resident,
                        "nbytes": e.nbytes,
                        "pinned": e.pinned,
                        "quota": e.quota,
                        "deadline": e.deadline,
                        "inflight": e.inflight,
                        "idle_s": round(max(now - e.last_used, 0.0), 3),
                    }
                    for e in self._entries.values()
                },
            }
