"""Hardened model-serving tier (grown from the
``DL4jServeRouteBuilder`` analog in ``streaming/serve.py``, which now
re-exports from here).

- ``server.py`` — ``ModelServer``: bounded worker pool + bounded
  queue (shed with ``503`` + ``Retry-After`` at saturation),
  per-request ``Deadline`` budgets (``504`` with elapsed/budget),
  ``CircuitBreaker``-guarded predicts (``503 circuit_open`` when a
  poisoned model trips it), canary-validated atomic hot reload
  (``POST /admin/reload`` or checkpoint-watching), ``/readyz``
  readiness split from ``/healthz`` liveness, graceful drain, and a
  ``/metrics`` JSON endpoint;
- ``batcher.py`` — cross-request micro-batching: ``BucketLadder``
  (power-of-two compiled-shape buckets) and ``MicroBatcher``
  (adaptive coalescing: up to ``max_batch_size`` rows or
  ``batch_timeout_ms``, dispatch-now when nothing else is in
  flight);
- ``compile_cache.py`` — eager bucket warmup at start/reload, the
  ``xla_compiles_total`` counter, and the post-warmup recompile
  guard;
- ``envelope.py`` — the shared JSON error envelope
  (``error_envelope``), opaque deterministic error ids, and strict
  Content-Length body reading (``read_request_body``: 411/400/413);
- ``metrics.py`` — counters + fixed-size latency reservoir
  quantiles, queue-delay reservoir, batch-occupancy histogram — all
  registered in a per-server ``observability.MetricsRegistry``, so
  ``/metrics?format=prometheus`` serves text exposition alongside
  the JSON default; pass ``tracer=`` to ``ModelServer`` and one
  trace id follows each request across admission, queue wait, batch
  assembly, and predict (``deeplearning4j_tpu/observability/``);
- ``registry.py`` — multi-tenant ``ModelRegistry``: N named models
  per process with per-tenant admission quotas/deadlines and LRU
  device-memory weight paging (cold tenants evict to host, fault
  back in at transfer cost — never a compile — with a pin list);
- ``router.py`` — ``ServingRouter``: thin HTTP front over N server
  processes; rendezvous-hash placement on model id, least-loaded
  fallback, ``/readyz``-aware health, and retry-next-backend on
  503/connection failure (kill a backend mid-load, lose nothing).
"""

from deeplearning4j_tpu.serving.batcher import (  # noqa: F401
    BucketLadder,
    MicroBatcher,
    fill_chunks,
    pad_rows,
)
from deeplearning4j_tpu.serving.compile_cache import (  # noqa: F401
    CompileCache,
    ModelShapes,
    jit_cache_size,
)
from deeplearning4j_tpu.serving.envelope import (  # noqa: F401
    HttpBodyError,
    deadline_envelope,
    error_envelope,
    error_id_for,
    read_request_body,
)
from deeplearning4j_tpu.serving.metrics import (  # noqa: F401
    Histogram,
    Reservoir,
    ServingMetrics,
)
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelEntry,
    ModelRegistry,
    ModelVersion,
    page_in_model,
    page_out_model,
)
from deeplearning4j_tpu.serving.router import (  # noqa: F401
    ServingRouter,
)
from deeplearning4j_tpu.serving.server import (  # noqa: F401
    MAX_BODY,
    ModelServer,
)
