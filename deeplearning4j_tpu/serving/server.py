"""Production-hardened model serving tier.

The reference's serving story (``routes/DL4jServeRouteBuilder.java:1``
— a Camel route: load checkpoint -> transform -> predict) assumed the
route never saturates, never hangs, and never changes models. This
module grows that route into a serving tier built for the failure
modes production traffic actually has:

- **admission control**: predicts run on a bounded worker pool behind
  a bounded queue. When both are full the request is *shed* —
  ``503`` + ``Retry-After`` in microseconds — instead of piling
  threads until the process dies (load shedding beats load collapse);
- **per-request deadlines**: one ``Deadline`` budget spans queue wait
  + transform + predict; expiry returns ``504`` with elapsed/budget
  so clients can tell a slow model from a dead one;
- **circuit breaking**: a ``CircuitBreaker`` guards the predict path.
  A poisoned model (every predict raising) trips it after N
  consecutive failures and subsequent requests fail fast with ``503
  circuit_open`` until a half-open probe proves recovery;
- **hot reload**: ``POST /admin/reload`` (or a
  ``CheckpointManager``-watching mode) restores the new version on
  the admin thread — never a predict worker — validates it with a
  canary predict, then swaps it atomically; in-flight requests finish
  on the version they started with, and a failed reload keeps serving
  the old model;
- **readiness vs liveness**: ``/healthz`` answers "is the process
  up" (always ok while serving); ``/readyz`` answers "should a
  balancer route here" and flips during reload, breaker-open,
  queue-high-water, and drain;
- **graceful drain**: ``stop(drain_timeout=)`` stops admitting,
  finishes in-flight work, then closes;
- **micro-batching**: the workers are batch-drain loops. Queued
  requests coalesce — up to ``max_batch_size`` rows or
  ``batch_timeout_ms``, whichever first — into ONE padded forward on
  a bucketed shape (``batcher.py``), and each request's response is
  sliced back out and completed individually. Deadline-expired items
  are dropped (``504``) before stacking; a request wider than the
  largest bucket falls back to the solo path. Every ladder bucket is
  compiled eagerly at ``start()``/``reload()`` (``compile_cache.py``)
  so steady traffic never compiles on the request path, and a
  recompile guard logs + counts any shape that escapes the ladder;
- **observability**: ``/metrics`` serves shed/timeout/breaker/reload
  counters, latency + queue-delay quantiles, batch-occupancy
  histogram, and compile counters (``metrics.py``).

Error responses all use the shared JSON envelope (``envelope.py``):
``400`` malformed payload, ``411`` missing Content-Length, ``413``
over the body cap, ``422`` shape-invalid features (expected vs got),
``500`` model/transform fault with an opaque deterministic
``error_id`` (never a stack trace), ``503`` shed / circuit open /
draining, ``504`` deadline exceeded.
"""

from __future__ import annotations

import json
import logging
import math
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.observability.export import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    parse_format_query,
    prometheus_text,
)
from deeplearning4j_tpu.observability import flightrec, profiler
from deeplearning4j_tpu.observability.trace import Tracer
from deeplearning4j_tpu.resilience.breaker import OPEN, CircuitBreaker
from deeplearning4j_tpu.resilience.deadline import Deadline
from deeplearning4j_tpu.serving.batcher import (
    BucketLadder,
    MicroBatcher,
    fill_chunks,
    pad_rows,
)
from deeplearning4j_tpu.serving.compile_cache import CompileCache
from deeplearning4j_tpu.serving.envelope import (
    HttpBodyError,
    deadline_envelope,
    error_envelope,
    error_id_for,
    read_request_body,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.registry import (
    ModelEntry,
    ModelRegistry,
    ModelVersion,
)

logger = logging.getLogger(__name__)

MAX_BODY = 64 * 1024 * 1024

# adaptive Retry-After clamp: a shed client should pace by observed
# queue drain, never told "come back immediately" nor parked longer
# than any queue this tier is allowed to build
RETRY_AFTER_MIN = 0.05
RETRY_AFTER_MAX = 5.0


def _feature_dim(model) -> Optional[int]:
    """Input width from the model's config (first layer's n_in), when
    it declares one — drives 422 validation and the default canary."""
    try:
        n_in = getattr(model.conf.layers[0], "n_in", None)
    except (AttributeError, IndexError, TypeError):
        return None
    if isinstance(n_in, int) and n_in > 0:
        return n_in
    return None


# the immutable (model, version) snapshot moved to registry.py with
# the multi-tenant registry; the name stays importable from here
_ModelVersion = ModelVersion


class _NoReloadSource(ValueError):
    pass


class _ServingHTTPServer(ThreadingHTTPServer):
    """stdlib default listen backlog is 5: a burst of 30+ concurrent
    connects gets TCP resets before admission control ever sees the
    requests. Shedding is the server's job (503 + Retry-After), not
    the kernel's."""

    request_queue_size = 128


class _WorkItem:
    """One admitted predict: features + deadline in, response out.
    The handler thread owns the socket; the worker only fills
    ``response`` and sets ``done``. ``lock`` arbitrates the
    queue-expiry race (handler cancels vs worker starts)."""

    __slots__ = ("features", "deadline", "done", "response", "lock",
                 "started", "cancelled", "timed_out", "rows",
                 "squeeze", "enqueued_at", "span", "queue_span",
                 "assembly_span", "entry")

    def __init__(self, features, deadline: Deadline,
                 entry: Optional[ModelEntry] = None):
        self.entry = entry  # the tenant this predict belongs to
        # trace handoff: the handler thread sets ``span`` (the
        # request's root) and ``queue_span`` before enqueueing; the
        # drain thread ends the queue span and parents its batch/
        # predict spans on the root — one trace id across threads
        self.span = None
        self.queue_span = None
        self.assembly_span = None
        self.features = features
        self.deadline = deadline
        self.done = threading.Event()
        self.response = None  # (code, body_dict, headers_dict)
        self.lock = threading.Lock()
        self.started = False
        self.cancelled = False   # handler gave up before worker start
        self.timed_out = False   # handler wrote a 504 already
        shape = np.shape(features)
        self.rows = int(shape[0]) if len(shape) >= 2 else 1
        self.squeeze = len(shape) == 1  # 1-d request: 1-d response
        self.enqueued_at = time.monotonic()

    def finish(self, code: int, body: dict, headers=None) -> bool:
        """Record the worker's result; returns False when the handler
        already answered 504 (result abandoned)."""
        with self.lock:
            abandoned = self.timed_out
            self.response = (code, body, headers or {})
        self.done.set()
        return not abandoned


class ModelServer:
    """Serve a model over HTTP (grown from the
    ``DL4jServeRouteBuilder`` analog into a hardened tier — see
    module docstring).

    Endpoints::

        GET  /healthz       liveness: process up
        GET  /readyz        readiness: routable (flips under stress)
        GET  /metrics       counters + latency quantiles (JSON)
        GET  /models        per-tenant registry + paging states
        POST /predict       {"features": [[...]], "model": name?}
        POST /admin/reload  {} | {"path"|"key": ..., "model": name?}

    Multi-tenant mode: ``models={name: model | path | spec-dict}``
    serves N named models from this one process. Each tenant gets
    its own admission quota (``{"quota": k}`` — overload sheds 503
    ``tenant_quota`` against the tenant's own bound, never its
    neighbors'), deadline override, optional bucket ladder, and
    paging state; ``max_device_models`` / ``max_device_bytes``
    LRU-page cold tenants' weights to host memory (``registry.py``),
    faulted back in on demand at transfer cost — never a compile.

    ``model_or_path`` may be a model instance, a checkpoint zip path,
    or None with ``checkpoint_manager=`` (restores the latest
    version). ``deadline`` (seconds) bounds queue wait + transform +
    predict per request; None disables. ``store`` (an ObjectStore,
    typically ``RetryingObjectStore(breaker=...)``) enables reload by
    object key.

    Micro-batching (on by default): queued requests coalesce into one
    padded forward per shape bucket — up to ``max_batch_size`` rows
    or ``batch_timeout_ms`` per batch, buckets from ``bucket_ladder``
    (powers of two up to ``max_batch_size`` when None). The drain
    pool is ``batch_workers`` threads (default 1: one accelerator is
    one dispatch stream, and a single continuous-batching drain
    collects the widest batches — splitting arrivals over k drain
    threads just shrinks every batch k-fold); ``workers`` keeps its
    capacity meaning in the k+q admission bound. Pass
    ``micro_batch=False`` for the PR-2 one-predict-per-request solo
    loop.

    Compile once, run anywhere (``deeplearning4j_tpu/compile/``):
    ``compile_cache`` (default on) points JAX's persistent
    compilation cache at ``DL4J_TPU_COMPILE_CACHE_DIR`` (or a
    per-host default) so every warmup/restart compile after the
    first is a disk read; ``aot`` (default on) additionally installs
    AOT-exported executables bundled in the checkpoint manifest
    (``CheckpointManager.save(model, artifacts=...)``) so
    ``start()``/``reload()`` from such a checkpoint *deserialize*
    the bucket ladder instead of compiling it — with silent
    per-artifact fallback to JIT when an artifact is missing, stale,
    or corrupt.
    """

    def __init__(self, model_or_path=None, host: str = "127.0.0.1",
                 port: int = 0, transform=None,
                 output_classes: bool = False, *,
                 workers: int = 4, queue_depth: int = 32,
                 deadline: Optional[float] = None,
                 retry_after: float = 1.0,
                 breaker: Optional[CircuitBreaker] = None,
                 checkpoint_manager=None, store=None, canary=None,
                 queue_high_water: Optional[int] = None,
                 reservoir_size: int = 1024,
                 micro_batch: bool = True,
                 max_batch_size: int = 32,
                 batch_timeout_ms: float = 2.0,
                 bucket_ladder=None,
                 batch_workers: int = 1,
                 tracer: Optional[Tracer] = None,
                 compile_cache=True,
                 aot: bool = True,
                 models: Optional[dict] = None,
                 max_device_models: Optional[int] = None,
                 max_device_bytes: Optional[int] = None):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if queue_depth < 0:
            raise ValueError("queue_depth must be >= 0")
        self.transform = transform
        self.output_classes = output_classes
        self.workers = workers
        self.queue_depth = queue_depth
        self.deadline = deadline
        self.retry_after = retry_after
        self.breaker = breaker or CircuitBreaker(name="predict")
        self.checkpoint_manager = checkpoint_manager
        self.store = store
        self.canary = canary
        self.queue_high_water = (
            queue_high_water if queue_high_water is not None
            else max(queue_depth, 1)
        )
        if micro_batch:
            if batch_workers < 1:
                raise ValueError("batch_workers must be >= 1")
            ladder = (
                bucket_ladder
                if isinstance(bucket_ladder, BucketLadder)
                else BucketLadder(bucket_ladder, max_batch_size)
            )
            self.batcher = MicroBatcher(ladder, batch_timeout_ms)
            self.batch_workers = batch_workers
            occupancy = ladder.buckets
        else:
            self.batcher = None
            self.batch_workers = workers
            occupancy = None
        self.metrics = ServingMetrics(reservoir_size, occupancy)
        # hardware-truth accounting per serving bucket: cost models
        # built off the request path at warmup ((model, bucket) ->
        # CostModel or None), published as bucket-labeled gauges on
        # the per-server registry per dispatch
        self._bucket_costs: dict = {}
        self._peak_flops = profiler.peak_flops()[0]
        self._peak_bw = profiler.peak_bytes_per_sec()[0]
        try:
            reg = self.metrics.registry
            self._g_bucket_mfu = reg.gauge(
                "step_mfu", labels=("bucket",),
                help="per-bucket MFU of the last batched forward",
            )
            self._g_bucket_fps = reg.gauge(
                "step_flops_per_sec", labels=("bucket",),
                help="per-bucket achieved FLOP/s (XLA cost model / "
                     "forward wall)",
            )
            self._g_bucket_bps = reg.gauge(
                "step_bytes_per_sec", labels=("bucket",),
                help="per-bucket achieved memory bytes/s",
            )
            self._g_bucket_roofline = reg.gauge(
                "step_roofline_class", labels=("bucket",),
                help="per-bucket roofline class (0 unknown / 1 "
                     "compute / 2 memory bound)",
            )
        except Exception:  # registry already holds the unlabeled kind
            self._g_bucket_mfu = self._g_bucket_fps = None
            self._g_bucket_bps = self._g_bucket_roofline = None
        # disabled by default: every span operation is a no-op costing
        # one branch; pass a Tracer(sink=JsonlSink(...)) to record
        self.tracer = tracer if tracer is not None else Tracer(
            enabled=False
        )
        self.compile_cache = CompileCache(self.metrics, self.tracer)
        # tier-1 persistent XLA cache: on by default (dir resolved
        # from DL4J_TPU_COMPILE_CACHE_DIR / the per-host default) so
        # restarts hit disk instead of the compiler; pass
        # compile_cache=False to opt out, or a directory string to
        # pin one. Never raises — a cache problem costs compiles.
        self.compile_cache_dir: Optional[str] = None
        if compile_cache is not False:
            from deeplearning4j_tpu.compile.persistent import (
                enable_persistent_cache,
            )

            self.compile_cache_dir = enable_persistent_cache(
                compile_cache if isinstance(compile_cache, str)
                else None,
                registry=self.metrics.registry,
            )
        # tier-2 AOT: when the model comes from a CheckpointManager
        # whose manifest bundles exported executables, install them
        # so warmup deserializes instead of compiling
        self.aot = aot
        self._aot_buckets = 0

        self._source_path: Optional[str] = None
        self._watched_step: Optional[int] = None
        self._last_restore_info = None  # CheckpointInfo when manager-sourced
        # continuous-learning hook (loop/shadow.py): when set, every
        # successful default-tenant forward is offered to the scorer
        # AFTER the client responses complete — candidate results are
        # never returned to clients, and observe() never raises
        self.shadow = None
        # multi-tenant registry: the single-model constructor path
        # becomes the "default" tenant; ``models=`` adds named
        # tenants (instance | checkpoint path | spec dict with
        # quota/deadline/pinned/max_batch_size overrides). The
        # paging budget (max_device_models / max_device_bytes)
        # LRU-evicts cold tenants' weights to host memory.
        self.model_registry = ModelRegistry(
            max_device_models=max_device_models,
            max_device_bytes=max_device_bytes,
            metrics_registry=self.metrics.registry,
        )
        if (model_or_path is not None
                or self.checkpoint_manager is not None
                or not models):
            model, source = self._initial_model(model_or_path)
            self.model_registry.add(
                "default",
                _ModelVersion(model, 1, source,
                              self.compile_cache.register()),
                source_path=self._source_path, default=True,
            )
        for name, spec in (models or {}).items():
            self._add_model(name, spec)

        self._model_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._reloading = False
        self._draining = False
        self._stop_workers = False
        self._queue: "queue.Queue[_WorkItem]" = queue.Queue(
            maxsize=queue_depth + workers
        )
        self._worker_threads: List[threading.Thread] = []
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()

        self._httpd = _ServingHTTPServer(
            (host, port), _make_handler(self)
        )
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # back-compat: the pre-hardening server exposed ``.model``, and
    # the single-tenant tier exposed ``._active`` — both now resolve
    # through the default tenant's entry
    @property
    def _active(self) -> ModelVersion:
        return self.model_registry.entry().current

    @property
    def model(self):
        return self._active.model

    @property
    def model_version(self) -> int:
        return self._active.version

    def _add_model(self, name: str, spec) -> ModelEntry:
        """Register one named tenant. ``spec`` is a model instance, a
        checkpoint zip path, or a dict: ``{"model": ... | "path":
        ..., "quota": int, "deadline": s, "pinned": bool,
        "max_batch_size": int | "ladder": [...]}`` — quota/deadline
        default to the server-wide knobs, the ladder to the shared
        one."""
        opts = {}
        source_path = None
        if isinstance(spec, dict):
            opts = spec
            spec = opts.get("model", opts.get("path"))
            if spec is None:
                raise ValueError(
                    f"model {name!r}: spec dict needs a 'model' "
                    "instance or a 'path'"
                )
        if isinstance(spec, str):
            from deeplearning4j_tpu.util.model_serializer import (
                restore_model,
            )

            source_path = spec
            model = restore_model(spec, load_updater=False)
        else:
            model = spec
        ladder = None
        if opts.get("ladder") is not None:
            ladder = BucketLadder(opts["ladder"])
        elif opts.get("max_batch_size") is not None:
            ladder = BucketLadder(None, opts["max_batch_size"])
        return self.model_registry.add(
            name,
            _ModelVersion(model, 1, source_path or type(model).__name__,
                          self.compile_cache.register()),
            quota=opts.get("quota"),
            deadline=opts.get("deadline"),
            pinned=bool(opts.get("pinned", False)),
            ladder=ladder,
            source_path=source_path,
        )

    def set_shadow(self, scorer) -> None:
        """(Un)install a shadow scorer (``loop.ShadowScorer`` or any
        object with ``observe(features, live_output, live_ms)``).
        Atomic attribute swap; in-flight forwards finish against
        whichever scorer they snapshotted."""
        self.shadow = scorer

    def _offer_shadow(self, entry: ModelEntry, feats, out,
                      live_ms: float) -> None:
        """Mirror one successful live forward to the shadow scorer —
        after the live responses completed, default tenant only,
        faults logged and swallowed (the live path is done; nothing
        here may affect it)."""
        sh = self.shadow
        if sh is None or entry.name != self.model_registry.default_name:
            return
        try:
            sh.observe(feats, out, live_ms)
        except Exception:
            logger.exception("shadow observe failed (ignored)")

    def _ladder_for(self, entry: ModelEntry) -> Optional[BucketLadder]:
        if self.batcher is None:
            return None
        return entry.ladder or self.batcher.ladder

    def _initial_model(self, model_or_path):
        if isinstance(model_or_path, str):
            from deeplearning4j_tpu.util.model_serializer import (
                restore_model,
            )

            self._source_path = model_or_path
            return restore_model(model_or_path), model_or_path
        if model_or_path is not None:
            return model_or_path, type(model_or_path).__name__
        if self.checkpoint_manager is not None:
            model, info = self.checkpoint_manager.restore_latest(
                load_updater=False
            )
            self._watched_step = info.step
            self._last_restore_info = info
            return model, f"checkpoint-step-{info.step}"
        raise ValueError(
            "provide a model, a checkpoint path, or checkpoint_manager="
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ModelServer":
        # AOT first: executables bundled with the checkpoint install
        # before warmup, so warmup deserializes instead of compiling
        # (missing/stale/corrupt artifacts silently leave those
        # buckets on the JIT path)
        self._aot_buckets = self._install_aot(
            self._active.model, self._active.shapes,
            self._last_restore_info,
        )
        # eager warmup BEFORE the pool takes traffic: every tenant's
        # ladder buckets compile now, so the first requests never pay
        # an XLA compile inside their deadline budget. Best-effort
        # here — a faulty model/transform must keep surfacing as
        # per-request 500 envelopes, not kill start() (at reload()
        # the same failure DOES fail the reload and keeps the old
        # version)
        for name in self.model_registry.names():
            entry = self.model_registry.entry(name)
            try:
                self._warm_model(entry.current.model,
                                 entry.current.shapes,
                                 self._ladder_for(entry),
                                 name=entry.name)
            except Exception:
                logger.exception(
                    "bucket warmup failed for model %r; serving "
                    "unwarmed (requests will surface the fault "
                    "per-request)", name,
                )
        # warmup ran every tenant through the device on purpose (the
        # executables must exist); now page the over-budget tail out
        self.model_registry.enforce_budget()
        for i in range(self.batch_workers):
            t = threading.Thread(
                target=self._worker_loop, daemon=True,
                name=f"dl4j-serve-worker-{i}",
            )
            t.start()
            self._worker_threads.append(t)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-serve",
        )
        self._thread.start()
        return self

    def stop(self, drain_timeout: float = 5.0) -> bool:
        """Graceful drain: stop admitting (new work is shed with
        ``503 draining``), wait up to ``drain_timeout`` seconds for
        in-flight requests to finish, then close the listener and the
        pool. Returns True when the drain fully emptied."""
        self._draining = True
        deadline = time.monotonic() + max(drain_timeout, 0.0)
        drained = False
        while time.monotonic() < deadline:
            if self.metrics.inflight == 0 and self._queue.empty():
                drained = True
                break
            time.sleep(0.01)
        self.stop_watch()
        self._stop_workers = True
        for t in self._worker_threads:
            t.join(timeout=2)
        if self._thread is not None:  # shutdown() hangs if never served
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        self._httpd.server_close()
        return drained or (
            self.metrics.inflight == 0 and self._queue.empty()
        )

    def install_preemption_drain(self, handler=None,
                                 drain_timeout: float = 5.0
                                 ) -> "ModelServer":
        """Translate a preemption notice (SIGTERM/SIGINT or a
        simulated one) into the graceful drain above: new work sheds
        with ``503 draining``, in-flight requests finish, then the
        listener closes. Uses the active ``resilience.preemption.
        PreemptionHandler``, installing a default one if none
        exists — so a bare serving process gets signal handling by
        calling this once after ``start()``."""
        from deeplearning4j_tpu.resilience import preemption

        h = handler if handler is not None else preemption.active_handler()
        if h is None:
            h = preemption.PreemptionHandler().install()
        h.on_preemption(
            lambda reason: self.stop(drain_timeout=drain_timeout)
        )
        return self

    # -- worker pool ----------------------------------------------------

    def _worker_loop(self) -> None:
        carry: Optional[_WorkItem] = None
        while not self._stop_workers:
            if carry is not None:
                item, carry = carry, None
            else:
                try:
                    item = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            try:
                if self.batcher is None:
                    self._process(item)
                else:
                    items, carry = self.batcher.collect(
                        self._queue, item,
                        lambda: self.metrics.inflight,
                    )
                    self._process_batch(items)
            except Exception:  # never kill a pool thread
                logger.exception("serve worker crashed on a request")
                item.finish(500, error_envelope(
                    "internal", 500, "internal server error",
                ))

    def _process(self, item: _WorkItem) -> None:
        with item.lock:
            if item.cancelled:
                return
            item.started = True
        if item.queue_span is not None:
            item.queue_span.end()  # idempotent; batch path ends first
        entry = item.entry or self.model_registry.entry()
        if item.deadline.expired():
            # expired while queued: report without touching the model
            self.metrics.incr("deadline_timeout_total")
            self.metrics.incr_model("model_deadline_timeout_total",
                                    entry.name)
            item.finish(504, deadline_envelope(
                item.deadline, "deadline expired while queued",
            ))
            return
        if not self.breaker.try_acquire():
            self.metrics.incr("breaker_rejected_total")
            item.finish(503, error_envelope(
                "circuit_open", 503,
                "model circuit is open; failing fast",
                retry_after=round(self.breaker.retry_after(), 3),
            ), {"Retry-After": self._retry_after_header()})
            return
        # the forward bracket: bump the tenant's LRU clock, fault its
        # weights in when paged out, and hold the executing mark so
        # the evictor cannot page it out mid-forward
        pagein_ms = self.model_registry.touch(entry)
        mv = entry.current  # snapshot: reloads swap for later requests
        pspan = self.tracer.start_span(
            "serving.predict", parent=item.span,
            attrs={"mode": "solo", "model": entry.name,
                   "model_version": mv.version},
        )
        if pagein_ms is not None:
            pspan.set_attr("weight_pagein_ms", round(pagein_ms, 3))
        try:
            feats = item.features
            if self.transform is not None:
                feats = self.transform(feats)
            self.compile_cache.note(mv.shapes, np.shape(feats),
                                    model=entry.name)
            fwd_t0 = time.perf_counter()
            out = mv.model.output(feats)
            out = np.asarray(
                out[0] if isinstance(out, (list, tuple)) else out
            )
            fwd_ms = (time.perf_counter() - fwd_t0) * 1000.0
        except Exception as e:
            self.breaker.record_failure()
            eid = error_id_for(e)
            logger.error("predict failed (error_id=%s)", eid,
                         exc_info=True)
            self.metrics.incr("server_error_total")
            pspan.set_attr("error_id", eid).end("error")
            item.finish(500, error_envelope(
                "model_error", 500,
                "prediction failed; see server log",
                error_id=eid,
            ))
            return
        finally:
            self.model_registry.release(entry)
        pspan.end()
        self.breaker.record_success()
        body = {"output": out.tolist(), "model_version": mv.version}
        if len(self.model_registry) > 1:
            body["model"] = entry.name
        if self.output_classes and out.ndim == 2:
            body["classes"] = out.argmax(axis=1).tolist()
        self.metrics.incr("predictions_total")
        self.metrics.incr_model("model_predictions_total", entry.name)
        if not item.finish(200, body):
            self.metrics.incr("abandoned_total")
        self._offer_shadow(entry, feats, out, fwd_ms)

    # -- micro-batch drain path -----------------------------------------

    def _process_batch(self, items: "List[_WorkItem]") -> None:
        """One coalesced batch: drop the dead, route the oversized to
        the solo path, transform per request, then pack what remains
        into bucket-padded chunks and run ONE forward per chunk."""
        now = time.monotonic()
        ready: List[tuple] = []
        for item in items:
            entry = item.entry or self.model_registry.entry()
            with item.lock:
                if item.cancelled:
                    continue
                item.started = True
            if item.queue_span is not None:
                item.queue_span.end()
            item.assembly_span = self.tracer.start_span(
                "serving.batch_assembly", parent=item.span,
                attrs={"batch_items": len(items)},
            )
            self.metrics.record_queue_delay(now - item.enqueued_at)
            if item.deadline.expired():
                # dropped BEFORE stacking: never pads a dead request
                # into a live batch
                self.metrics.incr("deadline_timeout_total")
                self.metrics.incr_model("model_deadline_timeout_total",
                                        entry.name)
                self.metrics.incr("batch_expired_total")
                item.assembly_span.end("timeout")
                item.finish(504, deadline_envelope(
                    item.deadline,
                    "deadline expired while coalescing",
                ))
                continue
            if item.rows > self._ladder_for(entry).max:
                # wider than the largest bucket: solo path, own compile
                self.metrics.incr("solo_fallback_total")
                item.assembly_span.set_attr(
                    "outcome", "solo_fallback"
                ).end()
                self._process(item)
                continue
            try:
                feats = item.features
                if self.transform is not None:
                    feats = self.transform(feats)
                feats = np.asarray(feats)
                if feats.ndim == 1:
                    feats = feats[None, :]
            except Exception as e:
                # a bad transform poisons only ITS request (solo
                # semantics), never its batchmates
                self.breaker.record_failure()
                eid = error_id_for(e)
                logger.error("transform failed (error_id=%s)", eid,
                             exc_info=True)
                self.metrics.incr("server_error_total")
                item.assembly_span.set_attr("error_id", eid).end(
                    "error"
                )
                item.finish(500, error_envelope(
                    "model_error", 500,
                    "prediction failed; see server log",
                    error_id=eid,
                ))
                continue
            ready.append((item, feats))
        if not ready:
            return
        # group by tenant + trailing shape + dtype: only same-model,
        # same-width requests can share a stacked forward (width
        # varies only when the model declares no n_in for
        # parse_features to enforce)
        groups: dict = {}
        for item, feats in ready:
            entry = item.entry or self.model_registry.entry()
            key = (entry.name, feats.shape[1:], feats.dtype.str)
            groups.setdefault(key, (entry, []))[1].append((item, feats))
        for entry, pairs in groups.values():
            ladder = self._ladder_for(entry)
            for chunk in fill_chunks(pairs, ladder.max):
                self._predict_chunk(entry, ladder, chunk)

    def _predict_chunk(self, entry: ModelEntry, ladder: BucketLadder,
                       chunk) -> None:
        """ONE padded forward for a chunk of (item, features) pairs
        of one tenant, sliced back out and completed per request."""
        for item, _ in chunk:
            if item.assembly_span is not None:
                item.assembly_span.end()
        if not self.breaker.try_acquire():
            self.metrics.incr("breaker_rejected_total", len(chunk))
            body = error_envelope(
                "circuit_open", 503,
                "model circuit is open; failing fast",
                retry_after=round(self.breaker.retry_after(), 3),
            )
            headers = {"Retry-After": self._retry_after_header()}
            for item, _ in chunk:
                item.finish(503, body, headers)
            return
        n_valid = sum(int(f.shape[0]) for _, f in chunk)
        bucket = ladder.bucket_for(n_valid)
        pagein_ms = self.model_registry.touch(entry)
        mv = entry.current  # snapshot: reloads swap for later requests
        pspans = [
            self.tracer.start_span(
                "serving.predict", parent=item.span,
                attrs={"mode": "batched", "bucket": bucket,
                       "n_valid": n_valid, "chunk_size": len(chunk),
                       "model": entry.name,
                       "model_version": mv.version},
            )
            for item, _ in chunk
        ]
        if pagein_ms is not None and pspans:
            pspans[0].set_attr("weight_pagein_ms",
                               round(pagein_ms, 3))
        try:
            stacked = (
                chunk[0][1] if len(chunk) == 1
                else np.concatenate([f for _, f in chunk], axis=0)
            )
            padded = pad_rows(stacked, bucket)
            self.compile_cache.note(mv.shapes, padded.shape,
                                    model=entry.name)
            fwd_t0 = time.perf_counter()
            out = self._padded_forward(mv.model, padded, n_valid)
            fwd_ms = (time.perf_counter() - fwd_t0) * 1000.0
        except Exception as e:
            self.breaker.record_failure()
            eid = error_id_for(e)
            logger.error("batched predict failed (error_id=%s)", eid,
                         exc_info=True)
            self.metrics.incr("server_error_total", len(chunk))
            body = error_envelope(
                "model_error", 500,
                "prediction failed; see server log",
                error_id=eid,
            )
            for sp in pspans:
                sp.set_attr("error_id", eid).end("error")
            for item, _ in chunk:
                item.finish(500, body)
            return
        finally:
            self.model_registry.release(entry)
        for sp in pspans:
            sp.end()
        self.breaker.record_success()
        self._publish_bucket_cost(entry.name, bucket, fwd_ms)
        self.metrics.record_batch(n_valid, bucket, entry.name)
        self.metrics.incr("batched_predictions_total", len(chunk))
        self.metrics.incr("predictions_total", len(chunk))
        self.metrics.incr_model("model_predictions_total", entry.name,
                                len(chunk))
        off = 0
        abandoned = 0
        multi = len(self.model_registry) > 1
        for item, feats in chunk:
            rows = int(feats.shape[0])
            o = out[off:off + rows]
            off += rows
            if item.squeeze:
                o = o[0]
            body = {"output": o.tolist(), "model_version": mv.version}
            if multi:
                body["model"] = entry.name
            if self.output_classes and o.ndim == 2:
                body["classes"] = o.argmax(axis=1).tolist()
            if not item.finish(200, body):
                abandoned += 1
        if abandoned:
            self.metrics.incr("abandoned_total", abandoned)
        self._offer_shadow(entry, stacked, out[:n_valid], fwd_ms)

    def _publish_bucket_cost(self, name: str, bucket: int,
                             fwd_ms: float) -> None:
        """Publish bucket-labeled MFU/throughput gauges from the
        warmup-built cost model; a dict lookup + a division on the
        dispatch path, nothing when no cost model exists."""
        cm = self._bucket_costs.get((name, bucket))
        if cm is None or self._g_bucket_mfu is None:
            return
        try:
            got = cm.achieved(fwd_ms / 1e3, self._peak_flops)
            label = str(bucket)
            self._g_bucket_fps.labels(label).set(
                got["flops_per_sec"]
            )
            self._g_bucket_bps.labels(label).set(
                got["bytes_per_sec"]
            )
            if got["mfu"] is not None:
                self._g_bucket_mfu.labels(label).set(got["mfu"])
            self._g_bucket_roofline.labels(label).set(
                cm.roofline_class(self._peak_flops, self._peak_bw)
            )
        except Exception:  # accounting must never fail a predict
            logger.debug("bucket cost publish failed", exc_info=True)

    def _padded_forward(self, model, padded, n_valid: int):
        """Run the model on a bucket-padded batch and return the valid
        rows. Engines expose ``output_padded`` (same jitted program as
        ``output``, masks composed over padding rows); plain models
        fall back to ``output`` + slice — valid because inference
        forwards are row-independent (the contract
        ``tests/test_batching.py`` enforces bitwise)."""
        fn = getattr(model, "output_padded", None)
        if fn is not None:
            out = fn(padded, n_valid=n_valid)
            out = out[0] if isinstance(out, (list, tuple)) else out
            return np.asarray(out)
        out = model.output(padded)
        out = out[0] if isinstance(out, (list, tuple)) else out
        return np.asarray(out)[:n_valid]

    def _warm_model(self, model, shapes, ladder=None,
                    name=None) -> int:
        """Eagerly run every ladder bucket through the padded forward
        so all steady-state executables exist BEFORE the model takes
        traffic. Returns the number of warmup forwards (0 when
        batching is off or the input width is unknowable)."""
        if self.batcher is None:
            return 0
        if ladder is None:
            ladder = self.batcher.ladder
        feats = self._canary_features(model)
        if feats is None:
            logger.info(
                "bucket warmup skipped: model declares no input width "
                "and no canary= was provided"
            )
            return 0
        if self.transform is not None:
            feats = self.transform(feats)
        feats = np.asarray(feats, np.float32)
        if feats.ndim == 1:
            feats = feats[None, :]
        n = 0
        for b in ladder.buckets:
            padded = pad_rows(feats[:b], b)
            self.compile_cache.note(shapes, padded.shape)
            self._padded_forward(model, padded, padded.shape[0])
            self.metrics.incr("warmup_predicts_total")
            # hardware-truth bucket accounting: the cost model is
            # built HERE, off the request path; per-dispatch MFU is
            # then a dict lookup + division
            try:
                self._bucket_costs[(name, b)] = (
                    profiler.output_cost_model(
                        model, padded.shape, str(padded.dtype)
                    )
                )
            except Exception:
                self._bucket_costs[(name, b)] = None
            n += 1
        shapes.mark_warmed()
        return n

    def _install_aot(self, model, shapes, info) -> int:
        """Install AOT-exported forward executables bundled with a
        checkpoint (manifest ``artifacts`` map) onto ``model`` and
        pre-mark their shapes compiled in the recompile-guard record.
        Returns the number installed; 0 — silently — when AOT is off,
        the model has no bundle, or every artifact is stale/corrupt
        (those buckets JIT at warmup exactly as without a bundle)."""
        if (not self.aot or info is None
                or self.checkpoint_manager is None
                or getattr(model, "aot_install_output", None) is None):
            return 0
        try:
            blobs = self.checkpoint_manager.load_artifacts(info)
            if not blobs:
                return 0
            from deeplearning4j_tpu.compile.aot import (
                install_serving_bundle,
            )

            installed = install_serving_bundle(
                model, blobs, registry=self.metrics.registry
            )
        except Exception:
            logger.exception(
                "AOT artifact install failed; serving will JIT-"
                "compile at warmup instead"
            )
            return 0
        if installed and shapes is not None:
            # first runs of these shapes are disk loads, not
            # compiles: keep xla_compiles_total flat for them. The
            # shape record tracks the (single) feature array's shape,
            # so unwrap the graph engine's nested 1-tuple keys.
            shapes.preload([
                k[0] if k and isinstance(k[0], tuple) else k
                for k in installed
            ])
        if installed:
            logger.info(
                "installed %d AOT executable(s) from checkpoint "
                "step %s", len(installed), info.step,
            )
        return len(installed)

    def _canary_features(self, model):
        if self.canary is not None:
            return np.asarray(self.canary, np.float32)
        n_in = _feature_dim(model)
        if n_in is None:
            return None
        return np.zeros((1, n_in), np.float32)

    def retry_after_value(self) -> float:
        """Adaptive Retry-After: how long until a retry would find a
        slot, estimated as queue depth over the observed drain rate
        (recent completions per second), clamped to
        [``RETRY_AFTER_MIN``, min(``RETRY_AFTER_MAX``, knob)]. Before
        any completion exists (cold start, wedged pool) the knob is
        the answer — it remains the upper bound, never the constant.
        """
        cap = min(RETRY_AFTER_MAX, self.retry_after)
        cap = max(cap, RETRY_AFTER_MIN)
        rate = self.metrics.drain_rate()
        if rate is None or rate <= 0:
            return cap
        est = self._queue.qsize() / rate
        return min(cap, max(RETRY_AFTER_MIN, est))

    def _retry_after_header(self) -> str:
        # HTTP Retry-After is integer seconds: round the adaptive
        # value up so the header never understates the JSON body's
        # precise ``retry_after`` float
        return str(max(1, int(math.ceil(self.retry_after_value()))))

    # -- admission (called from handler threads) ------------------------

    def submit(self, features,
               model: Optional[str] = None) -> "tuple[int, dict, dict]":
        """Admit one predict through the bounded pool and wait for its
        result under the request deadline. ``model`` routes to a
        named tenant (None = the default). Returns
        ``(status, body, headers)``. One root span brackets the whole
        request; the admission decision, queue wait, batch assembly,
        and predict are children sharing its trace id."""
        try:
            entry = self.model_registry.entry(model)
        except KeyError:
            self.metrics.incr("client_error_total")
            return 404, error_envelope(
                "model_not_found", 404,
                f"no model named {model!r}",
                models=self.model_registry.names(),
            ), {}
        started = time.monotonic()
        shape = np.shape(features)
        root = self.tracer.start_span("serving.request", attrs={
            "rows": int(shape[0]) if len(shape) >= 2 else 1,
            "model": entry.name,
        })
        adm = self.tracer.start_span("serving.admission",
                                     parent=root)
        self.metrics.incr_model("model_requests_total", entry.name)
        if self._draining:
            self.metrics.incr("shed_total")
            self.metrics.incr_model("model_shed_total", entry.name)
            adm.set_attr("outcome", "draining").end("shed")
            root.set_attr("status_code", 503).end("shed")
            return 503, error_envelope(
                "draining", 503, "server is draining; not admitting",
                retry_after=round(self.retry_after_value(), 3),
            ), {"Retry-After": self._retry_after_header()}
        if self.breaker.state == OPEN:
            # fail fast at admission: no queue slot for a doomed call
            self.metrics.incr("breaker_rejected_total")
            adm.set_attr("outcome", "circuit_open").end("shed")
            root.set_attr("status_code", 503).end("shed")
            return 503, error_envelope(
                "circuit_open", 503,
                "model circuit is open; failing fast",
                retry_after=round(self.breaker.retry_after(), 3),
            ), {"Retry-After": self._retry_after_header()}
        # per-tenant quota FIRST: one tenant at 10x its quota sheds
        # against its own bound and never consumes global slots its
        # neighbors are entitled to
        if not entry.admit():
            self.metrics.incr("shed_total")
            self.metrics.incr("quota_rejected_total")
            self.metrics.incr_model("model_shed_total", entry.name)
            adm.set_attr("outcome", "tenant_quota").end("shed")
            root.set_attr("status_code", 503).end("shed")
            return 503, error_envelope(
                "tenant_quota", 503,
                "model admission quota exceeded",
                model=entry.name, quota=entry.quota,
                retry_after=round(self.retry_after_value(), 3),
            ), {"Retry-After": self._retry_after_header()}
        # global admission bound: at most workers + queue_depth
        # requests in the system (executing + queued); excess sheds NOW
        if not self.metrics.try_enter(self.workers + self.queue_depth):
            entry.exit_admission()
            self.metrics.incr("shed_total")
            self.metrics.incr_model("model_shed_total", entry.name)
            adm.set_attr("outcome", "shed").end("shed")
            root.set_attr("status_code", 503).end("shed")
            return 503, error_envelope(
                "shed", 503,
                "worker pool and queue are full",
                retry_after=round(self.retry_after_value(), 3),
            ), {"Retry-After": self._retry_after_header()}
        adm.set_attr("outcome", "admitted").end()
        deadline = (entry.deadline if entry.deadline is not None
                    else self.deadline)
        item = _WorkItem(features, Deadline.after(deadline), entry)
        item.span = root
        item.queue_span = self.tracer.start_span("serving.queue",
                                                 parent=root)
        try:
            try:
                self._queue.put_nowait(item)
            except queue.Full:  # unreachable: sized to the bound
                self.metrics.incr("shed_total")
                self.metrics.incr_model("model_shed_total", entry.name)
                item.queue_span.end("shed")
                root.set_attr("status_code", 503).end("shed")
                return 503, error_envelope(
                    "shed", 503,
                    "worker pool and queue are full",
                    retry_after=round(self.retry_after_value(), 3),
                ), {"Retry-After": self._retry_after_header()}
            remaining = item.deadline.remaining()
            finished = item.done.wait(
                None if remaining is None else max(remaining, 0.0)
            )
            if not finished:
                with item.lock:
                    item.timed_out = True
                    if not item.started:
                        item.cancelled = True
                        item.queue_span.end("timeout")
                self.metrics.incr("deadline_timeout_total")
                self.metrics.incr_model("model_deadline_timeout_total",
                                        entry.name)
                root.set_attr("status_code", 504).end("timeout")
                return 504, deadline_envelope(item.deadline), {}
            code = item.response[0]
            root.set_attr("status_code", code).end(
                "ok" if code < 400 else "error"
            )
            return item.response
        finally:
            entry.exit_admission()
            self.metrics.exit()
            now = time.monotonic()
            self.metrics.note_completion(now)
            self.metrics.record_model_latency(entry.name,
                                              now - started)

    # -- hot reload -----------------------------------------------------

    def reload(self, spec: Optional[dict] = None) -> "tuple[int, dict]":
        """Restore a new model version (off the worker pool), canary-
        validate it, and swap atomically. ``spec`` may name a tenant
        (``{"model": name}``, default tenant otherwise) or pin a
        checkpoint version (``{"step": N}``, manager-backed default
        tenant); a failure at any stage keeps that tenant's current
        version serving — and never touches the others.

        Reloading the checkpoint step that is ALREADY serving is a
        counted no-op (``reload_skipped_total``, ``200 skipped``)
        instead of a full canary + warmup cycle — a polling promoter
        must not churn the server. ``{"force": true}`` overrides.
        Returns ``(status, body)``."""
        spec = dict(spec or {})
        name = spec.pop("model", None)
        force = bool(spec.pop("force", False))
        try:
            entry = self.model_registry.entry(name)
        except KeyError:
            return 404, error_envelope(
                "model_not_found", 404, f"no model named {name!r}",
                models=self.model_registry.names(),
            )
        if not self._reload_lock.acquire(blocking=False):
            return 409, error_envelope(
                "reload_in_progress", 409,
                "another reload is already running",
            )
        try:
            # idempotence: resolve the target checkpoint step WITHOUT
            # restoring anything; already serving it -> counted no-op
            # (never re-runs canary/warmup, never bumps the version)
            if not force:
                target = self._reload_target_step(spec, entry)
                if (target is not None
                        and target == self._watched_step):
                    self.metrics.incr("reload_skipped_total")
                    body = {"status": "skipped",
                            "step": int(target),
                            "version": entry.current.version,
                            "reason": "already serving this "
                                      "checkpoint step"}
                    if name is not None:
                        body["name"] = entry.name
                    return 200, body
            self._reloading = True  # /readyz flips for the duration
            try:
                model, source, info = self._load_for_reload(spec, entry)
                shapes = self.compile_cache.register()
                # AOT before canary/warmup: when the checkpoint
                # bundles exported executables, both the canary and
                # the bucket warmup run the deserialized programs —
                # a reload from a warm bundle performs zero compiles
                n_aot = self._install_aot(model, shapes, info)
                self._canary_check(model, self._ladder_for(entry))
                # warm every bucket on the ADMIN thread before the
                # swap: the new version has compiled all its shapes
                # before it sees its first request
                self._warm_model(model, shapes,
                                 self._ladder_for(entry),
                                 name=entry.name)
            except _NoReloadSource as e:
                return 400, error_envelope("no_reload_source", 400,
                                           str(e))
            except Exception as e:
                eid = error_id_for(e)
                logger.error("reload failed (error_id=%s)", eid,
                             exc_info=True)
                self.metrics.incr("reload_failure_total")
                return 503, error_envelope(
                    "reload_failed", 503,
                    "model reload failed; previous version still "
                    "serving", error_id=eid,
                )
            with self._model_lock:
                version = entry.current.version + 1
                self.model_registry.swap(
                    entry,
                    _ModelVersion(model, version, source, shapes),
                )
            self._aot_buckets = n_aot
            if info is not None:  # manager-sourced: step now serving
                self._watched_step = info.step
                self._last_restore_info = info
            self.metrics.incr("reload_total")
            body = {"status": "reloaded", "version": version,
                    "model": type(model).__name__,
                    "source": source}
            if name is not None:
                body["name"] = entry.name
            if n_aot:  # legacy response shape unless AOT landed
                body["aot_buckets"] = n_aot
            return 200, body
        finally:
            self._reloading = False
            self._reload_lock.release()

    def _reload_target_step(self, spec: dict,
                            entry: ModelEntry) -> Optional[int]:
        """The checkpoint step ``spec`` would load, resolvable without
        restoring — None when the source is not step-addressable
        (path/key/instance reloads never skip)."""
        if "path" in spec or "key" in spec:
            return None
        if (entry.name != self.model_registry.default_name
                or self.checkpoint_manager is None):
            return None
        if "step" in spec:
            try:
                return int(spec["step"])
            except (TypeError, ValueError):
                return None
        return self.checkpoint_manager.latest_step()

    def _load_for_reload(self, spec: dict, entry: ModelEntry):
        """(model, source, checkpoint_info_or_None) — the info rides
        along so reload can install the checkpoint's AOT bundle. The
        checkpoint manager and constructor path only back the DEFAULT
        tenant; named tenants reload from an explicit spec or the
        path they were registered from."""
        from deeplearning4j_tpu.util.model_serializer import (
            restore_model,
            restore_model_from_bytes,
        )

        if "step" in spec:
            # a specific published version (the promoter's path: the
            # candidate under promotion may no longer be the newest)
            if self.checkpoint_manager is None:
                raise _NoReloadSource(
                    "reload by step requires the server's "
                    "checkpoint_manager="
                )
            step = int(spec["step"])
            info = next(
                (i for i in self.checkpoint_manager.available()
                 if i.step == step), None,
            )
            if info is None:
                raise _NoReloadSource(
                    f"no checkpoint at step {step} in the store"
                )
            model = self.checkpoint_manager.restore(
                info, load_updater=False
            )
            return model, f"checkpoint-step-{step}", info
        if "path" in spec:
            return (
                restore_model(spec["path"], load_updater=False),
                str(spec["path"]), None,
            )
        if "key" in spec:
            if self.store is None:
                raise _NoReloadSource(
                    "reload by key requires the server's store="
                )
            data = self.store.read(spec["key"])
            return (
                restore_model_from_bytes(data, load_updater=False),
                str(spec["key"]), None,
            )
        is_default = entry.name == self.model_registry.default_name
        if is_default and self.checkpoint_manager is not None:
            model, info = self.checkpoint_manager.restore_latest(
                load_updater=False
            )
            return model, f"checkpoint-step-{info.step}", info
        source_path = entry.source_path or (
            self._source_path if is_default else None
        )
        if source_path is not None:
            return (
                restore_model(source_path, load_updater=False),
                source_path, None,
            )
        raise _NoReloadSource(
            "no reload source: pass {\"path\": ...} / {\"key\": ...} "
            "or construct the server with checkpoint_manager="
        )

    def _canary_check(self, model, ladder=None) -> None:
        """One predict on the candidate BEFORE it takes traffic — a
        restorable-but-broken checkpoint must fail the reload, not the
        next thousand user requests. With micro-batching on, the
        canary runs through the SAME bucketed padded path traffic
        uses (padded to the smallest bucket of the TENANT's ladder
        that fits), so a canary pass proves the shapes production
        requests will execute, not just a bespoke 1-row program."""
        feats = self._canary_features(model)
        if feats is None:
            return  # shape unknown and no canary provided: skip
        if self.transform is not None:
            feats = self.transform(feats)
        feats = np.asarray(feats, np.float32)
        if self.batcher is not None:
            if ladder is None:
                ladder = self.batcher.ladder
            if feats.ndim == 1:
                feats = feats[None, :]
            rows = int(feats.shape[0])
            bucket = ladder.bucket_for(rows)
            if bucket is not None:
                out = self._padded_forward(
                    model, pad_rows(feats, bucket), rows
                )
            else:
                out = self._padded_forward(model, feats, rows)
        else:
            out = model.output(feats)
            out = np.asarray(out[0] if isinstance(out, (list, tuple))
                             else out)
        if not np.all(np.isfinite(out)):
            raise ValueError("canary predict produced non-finite output")

    # -- checkpoint watching --------------------------------------------

    def check_for_update(self) -> bool:
        """One poll of the checkpoint manager: reload iff a newer step
        than the last loaded one exists. Returns True on a swap."""
        if self.checkpoint_manager is None:
            return False
        step = self.checkpoint_manager.last_step()
        if step is None or step == self._watched_step:
            return False
        code, _ = self.reload({})
        if code == 200:
            self._watched_step = step
            return True
        return False

    def watch(self, interval: float = 1.0) -> "ModelServer":
        """Poll the checkpoint manager every ``interval`` seconds on a
        daemon thread and hot-swap when a new version lands."""
        if self.checkpoint_manager is None:
            raise ValueError("watch() requires checkpoint_manager=")
        if self._watch_thread is not None:
            return self
        self._watch_stop.clear()

        def _loop():
            while not self._watch_stop.wait(interval):
                try:
                    self.check_for_update()
                except Exception:
                    logger.exception("checkpoint watch poll failed")

        self._watch_thread = threading.Thread(
            target=_loop, daemon=True, name="dl4j-serve-watch",
        )
        self._watch_thread.start()
        return self

    def stop_watch(self) -> None:
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None

    # -- health / metrics -----------------------------------------------

    def health(self) -> dict:
        out = {
            "status": "ok",
            "model": type(self._active.model).__name__,
            "version": self._active.version,
        }
        if len(self.model_registry) > 1:
            out["models"] = self.model_registry.names()
        return out

    def models_snapshot(self) -> dict:
        """``GET /models``: per-tenant registry + paging states, with
        each tenant's counter/latency view merged in."""
        stats = self.model_registry.stats()
        per_model = self.metrics.model_snapshot()
        for name, block in stats["models"].items():
            if name in per_model:
                block["metrics"] = per_model[name]
        stats["default"] = self.model_registry.default_name
        return stats

    def readiness(self) -> "tuple[int, dict]":
        reasons = []
        if self._draining:
            reasons.append("draining")
        if self._reloading:
            reasons.append("reloading")
        if self.breaker.state == OPEN:
            reasons.append("breaker_open")
        if self._queue.qsize() >= self.queue_high_water:
            reasons.append("queue_high_water")
        if reasons:
            return 503, {"status": "unready", "reasons": reasons}
        return 200, {"status": "ready",
                     "version": self._active.version}

    def prometheus_metrics(self) -> str:
        """Registry contents in Prometheus text exposition format
        (``GET /metrics?format=prometheus``). Scrape-time gauges
        mirror the snapshot-only fields so the exposition is
        self-contained."""
        reg = self.metrics.registry
        reg.gauge("queue_depth",
                  help="requests waiting in the bounded queue").set(
            self._queue.qsize()
        )
        reg.gauge("model_version",
                  help="active model version (bumps on reload)").set(
            self._active.version
        )
        reg.gauge("breaker_state",
                  help="predict breaker: 0 closed, 1 open, "
                       "2 half-open").set(
            {"closed": 0, "open": 1, "half_open": 2}[self.breaker.state]
        )
        return prometheus_text(reg)

    def metrics_snapshot(self) -> dict:
        out = self.metrics.snapshot()
        out["queue_depth"] = self._queue.qsize()
        out["queue_capacity"] = self.queue_depth
        out["workers"] = self.workers
        out["breaker"] = self.breaker.snapshot()
        out["model_version"] = self._active.version
        out["draining"] = self._draining
        out["retry_after"] = round(self.retry_after_value(), 3)
        out["paging"] = self.model_registry.stats()
        if self.batcher is not None:
            out["batching"] = {
                "enabled": True,
                "max_batch_size": self.batcher.ladder.max,
                "batch_timeout_ms": self.batcher.batch_timeout_ms,
                "buckets": list(self.batcher.ladder.buckets),
                "batch_workers": self.batch_workers,
                "warmed": bool(self._active.shapes.warmed),
            }
        else:
            out["batching"] = {"enabled": False}
        from deeplearning4j_tpu.compile.persistent import cache_stats

        out["compile"] = {
            "persistent_cache_dir": self.compile_cache_dir,
            "aot_enabled": self.aot,
            "aot_buckets_installed": self._aot_buckets,
            **cache_stats(),
        }
        return out

    def debug_snapshot(self) -> dict:
        """``GET /debugz``: one read-only, bounded JSON page with
        everything a first responder wants before attaching a
        debugger — versions, config, per-model state, the
        hardware-truth cost models, and the flight-recorder tail
        (capped at ``flightrec.DEBUG_TAIL_LIMIT`` records)."""
        import jax
        import jaxlib

        from deeplearning4j_tpu import __version__ as pkg_version

        out: dict = {
            "versions": {
                "deeplearning4j_tpu": pkg_version,
                "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
            },
            "backend": jax.default_backend(),
            "config": {
                "host": self._httpd.server_address[0],
                "port": self.port,
                "workers": self.workers,
                "queue_depth": self.queue_depth,
                "aot_enabled": self.aot,
                "compile_cache_dir": self.compile_cache_dir,
                "batching": self.batcher is not None,
            },
            "models": self.models_snapshot(),
            "metrics": self.metrics_snapshot(),
            "roofline": {
                "peak_flops": self._peak_flops,
                "peak_bytes_per_sec": self._peak_bw,
                "bucket_cost_models": {
                    f"{name}:{bucket}": {
                        "key": cm.key,
                        "flops": cm.flops,
                        "bytes_accessed": cm.bytes_accessed,
                        "arithmetic_intensity": round(
                            cm.arithmetic_intensity, 3),
                    }
                    for (name, bucket), cm
                    in sorted(self._bucket_costs.items())
                    if cm is not None
                },
            },
        }
        prof = profiler.get_active_profiler()
        if prof is not None:
            out["profiler"] = prof.snapshot()
        rec = flightrec.get_flight_recorder()
        if rec is not None:
            out["flight_recorder"] = {
                "capacity": rec.capacity,
                "last_step": rec.last_step(),
                "tail": flightrec._jsonable(
                    rec.tail(flightrec.DEBUG_TAIL_LIMIT)
                ),
            }
        return out

    # -- request validation ---------------------------------------------

    def parse_predict(self, data: bytes):
        """Body bytes -> ``(model_name_or_None, float32 features)``,
        or raise ``HttpBodyError`` with the right envelope: 400 for
        malformed payloads, 404 for an unknown ``"model"``, 422 for
        well-formed-but-shape-invalid features (expected vs got in
        the body). Width validates against the TARGET tenant's
        model."""
        try:
            payload = json.loads(data)
        except (ValueError, UnicodeDecodeError) as e:
            raise HttpBodyError(400, error_envelope(
                "malformed_json", 400, f"body is not valid JSON: {e}",
            )) from None
        if not isinstance(payload, dict) or "features" not in payload:
            raise HttpBodyError(400, error_envelope(
                "bad_request", 400,
                'body must be a JSON object with a "features" key',
            ))
        name = payload.get("model")
        if name is not None and not isinstance(name, str):
            raise HttpBodyError(400, error_envelope(
                "bad_request", 400,
                '"model" must be a string when present',
            ))
        try:
            entry = self.model_registry.entry(name)
        except KeyError:
            raise HttpBodyError(404, error_envelope(
                "model_not_found", 404, f"no model named {name!r}",
                models=self.model_registry.names(),
            )) from None
        try:
            feats = np.asarray(payload["features"], np.float32)
        except (ValueError, TypeError):
            raise HttpBodyError(422, error_envelope(
                "invalid_features", 422,
                "features are not a numeric array",
                expected="numeric array [n, d]",
                got=type(payload["features"]).__name__,
            )) from None
        if feats.ndim not in (1, 2) or feats.size == 0:
            raise HttpBodyError(422, error_envelope(
                "invalid_features", 422,
                "features must be a non-empty 1-d or 2-d array",
                expected="[n, d]", got=list(feats.shape),
            ))
        n_in = _feature_dim(entry.current.model)
        if n_in is not None and feats.shape[-1] != n_in:
            raise HttpBodyError(422, error_envelope(
                "invalid_features", 422,
                "feature width does not match the model input",
                expected=[int(feats.shape[0]) if feats.ndim == 2
                          else 1, n_in],
                got=list(feats.shape),
            ))
        return name, feats

    def parse_features(self, data: bytes):
        """Back-compat wrapper: features only, default tenant."""
        return self.parse_predict(data)[1]


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, obj, code: int = 200, headers=None):
            body = json.dumps(obj).encode()
            try:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
            except OSError:
                pass  # client went away; nothing to tell it

        def _text(self, body: str, content_type: str,
                  code: int = 200):
            data = body.encode()
            try:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except OSError:
                pass

        def do_GET(self):
            server.metrics.incr("requests_total")
            route, fmt = parse_format_query(self.path)
            if route == "/healthz":
                self._json(server.health())
                return
            if route == "/readyz":
                code, body = server.readiness()
                self._json(body, code)
                return
            if route == "/metrics":
                if fmt == "prometheus":
                    self._text(server.prometheus_metrics(),
                               PROMETHEUS_CONTENT_TYPE)
                else:  # JSON stays the default
                    self._json(server.metrics_snapshot())
                return
            if route == "/models":
                self._json(server.models_snapshot())
                return
            if route == "/debugz":
                try:
                    self._json(server.debug_snapshot())
                except Exception as e:
                    eid = error_id_for(e)
                    logger.error(
                        "debugz failed (error_id=%s)", eid,
                        exc_info=True,
                    )
                    self._json(error_envelope(
                        "debug_error", 500,
                        "debug snapshot failed; see server log",
                        error_id=eid,
                    ), 500)
                return
            self._json(error_envelope("not_found", 404, "not found"),
                       404)

        def do_POST(self):
            server.metrics.incr("requests_total")
            if self.path == "/predict":
                started = time.monotonic()
                try:
                    data = read_request_body(self, MAX_BODY)
                    name, feats = server.parse_predict(data)
                except HttpBodyError as e:
                    server.metrics.incr("client_error_total")
                    self._json(e.envelope, e.code)
                    return
                code, body, headers = server.submit(feats, model=name)
                server.metrics.record_latency(
                    time.monotonic() - started
                )
                self._json(body, code, headers)
                return
            if self.path == "/admin/reload":
                try:
                    data = read_request_body(self, MAX_BODY)
                except HttpBodyError as e:
                    server.metrics.incr("client_error_total")
                    self._json(e.envelope, e.code)
                    return
                try:
                    spec = json.loads(data) if data.strip() else {}
                    if not isinstance(spec, dict):
                        raise ValueError("spec must be a JSON object")
                except ValueError as e:
                    server.metrics.incr("client_error_total")
                    self._json(error_envelope(
                        "malformed_json", 400,
                        f"reload spec is not valid JSON: {e}",
                    ), 400)
                    return
                code, body = server.reload(spec)
                self._json(body, code)
                return
            self._json(error_envelope("not_found", 404, "not found"),
                       404)

    return Handler
