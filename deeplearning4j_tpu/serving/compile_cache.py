"""Compile-cache accounting for the serving hot path.

XLA compiles one executable per input shape. On the request path that
is a disaster: the first request with a previously-unseen row count
pays seconds of compilation inside its deadline budget. The bucketed
micro-batcher (``batcher.py``) makes the shape set small and *known
in advance*, which makes compilation a **startup** cost instead of a
request-path cost:

- ``start()`` / ``reload()`` warm every ladder bucket eagerly, so a
  model version has compiled (and canary-validated) every shape
  traffic will use *before* it takes traffic — hot reload never pays
  a compile on the request path;
- every forward that runs a previously-unseen input shape increments
  ``xla_compiles_total`` (visible in ``/metrics``), so "zero compiles
  under steady load" is a falsifiable dashboard assertion;
- the **recompile guard**: once a version is marked warmed, any new
  shape is logged and counted in ``post_warmup_compiles_total`` —
  steady bucketed traffic must keep that counter flat, and a bump
  points at the exact shape that escaped the ladder.

Shape tracking is model-agnostic (a shape-set per model version), so
it also covers stub models with no jit underneath; for jax engines
the jitted forward's real cache size is additionally observable via
``jit_cache_size`` and asserted flat in tests.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Set, Tuple

logger = logging.getLogger(__name__)

NEW = "new"                  # first time this version ran this shape
WARM = "warm"                # shape already compiled (the steady state)
POST_WARMUP = "post_warmup"  # new shape AFTER warmup: ladder escape


class ModelShapes:
    """The shape set of one model version. Created by
    ``CompileCache.register()`` at load time, marked warmed once every
    ladder bucket has run."""

    __slots__ = ("seen", "warmed", "_lock")

    def __init__(self):
        self.seen: Set[Tuple[int, ...]] = set()
        self.warmed = False
        self._lock = threading.Lock()

    def note(self, shape: Tuple[int, ...]) -> str:
        with self._lock:
            if shape in self.seen:
                return WARM
            self.seen.add(shape)
            return POST_WARMUP if self.warmed else NEW

    def preload(self, shapes) -> None:
        """Mark shapes as already-compiled without counting them: an
        AOT-restored executable (compile/aot.py) arrives compiled, so
        its first run is a disk load, not an XLA compile —
        ``xla_compiles_total`` must stay flat for it."""
        with self._lock:
            for s in shapes:
                self.seen.add(tuple(int(d) for d in s))

    def mark_warmed(self) -> None:
        with self._lock:
            self.warmed = True


class CompileCache:
    """Per-server compile accounting: hands out a ``ModelShapes``
    record per model version and turns shape observations into the
    ``xla_compiles_total`` / ``post_warmup_compiles_total`` counters
    plus the recompile-guard log line."""

    def __init__(self, metrics=None, tracer=None):
        self.metrics = metrics
        self.tracer = tracer  # observability.Tracer; compile events

    def register(self) -> ModelShapes:
        return ModelShapes()

    def note(self, shapes: Optional[ModelShapes],
             shape: Tuple[int, ...],
             model: Optional[str] = None) -> str:
        if shapes is None:
            return WARM
        verdict = shapes.note(tuple(int(d) for d in shape))
        if verdict == WARM:
            return verdict
        if self.metrics is not None:
            self.metrics.incr("xla_compiles_total")
        if self.tracer is not None:
            # compile events join the trace stream: a slow request
            # whose trace window brackets an xla.compile event has
            # its explanation in one place
            attrs = {
                "shape": [int(d) for d in shape],
                "verdict": verdict,
            }
            if model is not None:
                attrs["model"] = model
            self.tracer.event("xla.compile", attrs=attrs)
        if verdict == POST_WARMUP:
            if self.metrics is not None:
                self.metrics.incr("post_warmup_compiles_total")
            logger.warning(
                "post-warmup compile: input shape %s%s was not "
                "covered by the warmed bucket ladder — this request "
                "paid the compilation on the serving path",
                tuple(shape),
                f" (model {model!r})" if model is not None else "",
            )
        return verdict


def jit_cache_size(model) -> Optional[int]:
    """Number of compiled entries behind a model's jitted inference
    forward, when the engine exposes one (None for stub models or
    before the first ``output``). Lets tests assert the REAL XLA
    cache — not just the shape-set proxy — stays flat under steady
    bucketed load."""
    fn = getattr(model, "_jit_output", None)
    if fn is None:
        return None
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None
