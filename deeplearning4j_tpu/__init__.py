"""deeplearning4j_tpu: a TPU-native deep-learning framework.

A ground-up rebuild of the capability surface of Deeplearning4j
(reference: jcastaldoFoodEssentials/deeplearning4j, surveyed in SURVEY.md)
designed idiomatically for TPU hardware on JAX/XLA/Pallas/pjit:

- builder-configured networks (sequential stacks + computation graphs)
  lowered to pure ``init``/``apply`` functions over parameter pytrees,
- a single jitted train step per (model, shape) pair instead of
  per-op JNI dispatch,
- data/tensor parallelism via ``jax.sharding.Mesh`` + XLA collectives
  instead of Spark parameter averaging / ParallelWrapper threads,
- zip checkpoints (config JSON + params + updater state) mirroring
  ModelSerializer's layout,
- embeddings (Word2Vec/GloVe/ParagraphVectors), graph embeddings
  (DeepWalk), evaluation, early stopping, Keras import and training
  observability.

The tensor substrate (the reference's nd4j/libnd4j, SURVEY.md L0) is
jax.numpy/lax; accelerated kernels (the reference's deeplearning4j-cuda
cuDNN helpers) are XLA lowerings plus Pallas kernels for fusion wins.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401

try:  # graph engine lands with the ComputationGraph milestone
    from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401
except ImportError:  # pragma: no cover
    ComputationGraph = None  # type: ignore[assignment]

from deeplearning4j_tpu.exceptions import (  # noqa: F401
    CheckpointCorruptedException,
    CircuitOpenException,
    DeadlineExceededException,
    DL4JException,
    DL4JFaultException,
    DL4JInvalidConfigException,
    DL4JInvalidInputException,
    RetryExhaustedException,
)

from deeplearning4j_tpu.resilience import (  # noqa: F401
    CheckpointListener,
    CheckpointManager,
    CircuitBreaker,
    Deadline,
    DivergenceGuard,
    RetryPolicy,
    retry_call,
)

# Lazy-import table: serving pulls in the HTTP tier, which training
# jobs never need — resolve on first attribute access instead of at
# package import. The observability substrate rides the same table.
_LAZY_IMPORTS = {
    "ModelServer": "deeplearning4j_tpu.serving.server",
    "ModelRegistry": "deeplearning4j_tpu.serving.registry",
    "ServingRouter": "deeplearning4j_tpu.serving.router",
    "ServingMetrics": "deeplearning4j_tpu.serving.metrics",
    "error_envelope": "deeplearning4j_tpu.serving.envelope",
    "BucketLadder": "deeplearning4j_tpu.serving.batcher",
    "MicroBatcher": "deeplearning4j_tpu.serving.batcher",
    "CompileCache": "deeplearning4j_tpu.serving.compile_cache",
    "enable_persistent_cache": "deeplearning4j_tpu.compile",
    "export_serving_bundle": "deeplearning4j_tpu.compile",
    "install_serving_bundle": "deeplearning4j_tpu.compile",
    "ShardedEmbeddingTable": "deeplearning4j_tpu.embeddings",
    "ShardedWord2Vec": "deeplearning4j_tpu.embeddings",
    "ShardedDeepWalk": "deeplearning4j_tpu.embeddings",
    "ContinualTrainer": "deeplearning4j_tpu.loop",
    "ShadowScorer": "deeplearning4j_tpu.loop",
    "Promoter": "deeplearning4j_tpu.loop",
    "PromotionGates": "deeplearning4j_tpu.loop",
    "PromotionJournal": "deeplearning4j_tpu.loop",
    "MetricsRegistry": "deeplearning4j_tpu.observability",
    "Tracer": "deeplearning4j_tpu.observability",
    "JsonlSink": "deeplearning4j_tpu.observability",
    "TelemetryListener": "deeplearning4j_tpu.observability",
    "prometheus_text": "deeplearning4j_tpu.observability",
    "set_global_tracer": "deeplearning4j_tpu.observability",
    "get_tracer": "deeplearning4j_tpu.observability",
}


def __getattr__(name):
    target = _LAZY_IMPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    value = getattr(importlib.import_module(target), name)
    globals()[name] = value  # cache: resolve once
    return value
