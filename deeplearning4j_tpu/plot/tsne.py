"""t-SNE (reference ``plot/Tsne.java`` and ``plot/BarnesHutTsne.java:63``).

Two implementations, mirroring the reference split:

- ``Tsne`` — exact O(N²) gradient, but as ONE jitted XLA program per
  iteration: the full [N, N] student-t kernel is a matmul-shaped op
  that maps straight onto the MXU, so "exact" is the FAST path on TPU
  for the N ≤ ~20k regime the reference UI uses.
- ``BarnesHutTsne`` — O(N log N) via SPTree (host-side numpy),
  matching the reference's structure for large N: sparse kNN-P from a
  VPTree + theta-gated cell approximation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.sptree import SPTree
from deeplearning4j_tpu.clustering.vptree import VPTree


def _binary_search_betas(d2: np.ndarray, perplexity: float,
                         tol: float = 1e-5, max_tries: int = 50):
    """Per-row binary search of precision beta so that the conditional
    distribution's entropy hits log(perplexity). d2: [N, K] squared
    distances to candidate neighbors (self excluded). Vectorized over
    all rows at once (the reference searches row-by-row in
    ``Tsne.hBeta``)."""
    n = d2.shape[0]
    beta = np.ones(n)
    beta_min = np.full(n, -np.inf)
    beta_max = np.full(n, np.inf)
    log_u = np.log(perplexity)
    p = np.zeros_like(d2)
    for _ in range(max_tries):
        p = np.exp(-d2 * beta[:, None])
        sum_p = np.maximum(p.sum(axis=1), 1e-12)
        # H = log(sum_p) + beta * <d2>_p
        h = np.log(sum_p) + beta * (d2 * p).sum(axis=1) / sum_p
        p = p / sum_p[:, None]
        diff = h - log_u
        done = np.abs(diff) < tol
        if done.all():
            break
        hi = (diff > 0) & ~done
        lo = (diff < 0) & ~done
        beta_min[hi] = beta[hi]
        beta[hi] = np.where(
            np.isinf(beta_max[hi]), beta[hi] * 2,
            (beta[hi] + beta_max[hi]) / 2,
        )
        beta_max[lo] = beta[lo]
        beta[lo] = np.where(
            np.isinf(beta_min[lo]), beta[lo] / 2,
            (beta[lo] + beta_min[lo]) / 2,
        )
    return p, beta


@functools.partial(jax.jit, donate_argnums=(1, 2, 3))
def _tsne_step(p, y, y_inc, gains, momentum, lr):
    """One exact t-SNE gradient step ([N, N] kernel on the MXU) with
    gains + momentum (reference ``Tsne.step``)."""
    d2 = jnp.sum((y[:, None, :] - y[None, :, :]) ** 2, axis=-1)
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(y.shape[0], dtype=y.dtype))
    q = jnp.maximum(num / jnp.sum(num), 1e-12)
    pq = (p - q) * num                               # [N, N]
    grad = 4.0 * (
        jnp.sum(pq, axis=1, keepdims=True) * y - pq @ y
    )
    same_sign = jnp.sign(grad) == jnp.sign(y_inc)
    gains = jnp.maximum(
        jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
    )
    y_inc = momentum * y_inc - lr * gains * grad
    y = y + y_inc
    y = y - jnp.mean(y, axis=0, keepdims=True)
    kl = jnp.sum(p * jnp.log(jnp.maximum(p, 1e-12) / q))
    return y, y_inc, gains, kl


class Tsne:
    """Exact t-SNE, jitted per-iteration (reference ``plot/Tsne.java``
    builder: maxIter, perplexity, learningRate, useAdaGrad off →
    gains+momentum)."""

    def __init__(self, max_iter: int = 1000, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_dims: int = 2,
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 250,
                 stop_lying_iteration: int = 100, lie_factor: float = 4.0,
                 seed: int = 12345):
        self.max_iter = max_iter
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_dims = n_dims
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.lie_factor = lie_factor
        self.seed = seed
        self.y: Optional[np.ndarray] = None
        self.kl: float = float("nan")

    def _joint_p(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        d2 = (
            np.sum(x * x, 1)[:, None] + np.sum(x * x, 1)[None, :]
            - 2.0 * (x @ x.T)
        )
        np.fill_diagonal(d2, 0.0)
        # exclude self: search over the off-diagonal entries
        mask = ~np.eye(n, dtype=bool)
        d2_off = d2[mask].reshape(n, n - 1)
        p_cond, _ = _binary_search_betas(d2_off, self.perplexity)
        p = np.zeros((n, n))
        p[mask] = p_cond.ravel()
        p = (p + p.T) / (2.0 * n)
        return np.maximum(p, 1e-12)

    def fit(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        p = self._joint_p(x)
        rng = np.random.RandomState(self.seed)
        y = jnp.asarray(rng.randn(n, self.n_dims) * 1e-4, jnp.float32)
        y_inc = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        p_lied = jnp.asarray(p * self.lie_factor, jnp.float32)
        p_true = jnp.asarray(p, jnp.float32)
        kl = jnp.float32(0)
        for it in range(self.max_iter):
            momentum = (
                self.initial_momentum
                if it < self.switch_momentum_iteration
                else self.final_momentum
            )
            cur_p = p_lied if it < self.stop_lying_iteration else p_true
            y, y_inc, gains, kl = _tsne_step(
                cur_p, y, y_inc, gains, jnp.float32(momentum),
                jnp.float32(self.learning_rate),
            )
        self.kl = float(kl)
        self.y = np.asarray(y)
        return self.y


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (reference ``plot/BarnesHutTsne.java:63`` —
    implements the same knobs plus ``theta``; gradient via SPTree,
    sparse input similarities via VPTree kNN)."""

    def __init__(self, theta: float = 0.5, perplexity: float = 30.0,
                 max_iter: int = 1000, learning_rate: float = 200.0,
                 n_dims: int = 2, **kw):
        super().__init__(max_iter=max_iter, perplexity=perplexity,
                         learning_rate=learning_rate, n_dims=n_dims, **kw)
        self.theta = theta

    def _sparse_p(self, x: np.ndarray):
        """Sparse symmetric P over 3·perplexity nearest neighbors
        (reference ``BarnesHutTsne.computeGaussianPerplexity``)."""
        n = x.shape[0]
        k = min(int(3 * self.perplexity), n - 1)
        tree = VPTree(x)
        cols = np.zeros((n, k), np.int64)
        d2 = np.zeros((n, k))
        for i in range(n):
            idx, dist = tree.search(x[i], k + 1)
            # drop self (distance 0 to itself is always found first)
            pairs = [(j, dv) for j, dv in zip(idx, dist) if j != i][:k]
            cols[i] = [j for j, _ in pairs]
            d2[i] = [dv * dv for _, dv in pairs]
        p_cond, _ = _binary_search_betas(d2, self.perplexity)
        # symmetrize: P = (P + P^T) / (2n) over the union sparsity
        from collections import defaultdict
        entries = defaultdict(float)
        for i in range(n):
            for j, v in zip(cols[i], p_cond[i]):
                entries[(i, int(j))] += v / (2.0 * n)
                entries[(int(j), i)] += v / (2.0 * n)
        rows_counts = np.zeros(n, np.int64)
        for (i, _j) in entries:
            rows_counts[i] += 1
        rows = np.zeros(n + 1, np.int64)
        np.cumsum(rows_counts, out=rows[1:])
        cols_flat = np.zeros(len(entries), np.int64)
        vals_flat = np.zeros(len(entries))
        fill = rows[:-1].copy()
        for (i, j), v in sorted(entries.items()):
            cols_flat[fill[i]] = j
            vals_flat[fill[i]] = v
            fill[i] += 1
        return rows, cols_flat, vals_flat

    def fit(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        rows, cols, vals = self._sparse_p(x)
        vals = vals / max(vals.sum(), 1e-12)
        rng = np.random.RandomState(self.seed)
        y = rng.randn(n, self.n_dims) * 1e-4
        y_inc = np.zeros_like(y)
        gains = np.ones_like(y)
        lied = vals * self.lie_factor
        for it in range(self.max_iter):
            cur_vals = lied if it < self.stop_lying_iteration else vals
            momentum = (
                self.initial_momentum
                if it < self.switch_momentum_iteration
                else self.final_momentum
            )
            pos_f = np.zeros_like(y)
            SPTree.compute_edge_forces(y, rows, cols, cur_vals, pos_f)
            tree = SPTree(y)
            neg_f = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                row_neg = np.zeros(self.n_dims)
                sum_q += tree.compute_non_edge_forces(
                    i, self.theta, row_neg
                )
                neg_f[i] = row_neg
            grad = pos_f - neg_f / max(sum_q, 1e-12)
            same_sign = np.sign(grad) == np.sign(y_inc)
            gains = np.maximum(
                np.where(same_sign, gains * 0.8, gains + 0.2), 0.01
            )
            y_inc = momentum * y_inc - self.learning_rate * gains * grad
            y = y + y_inc
            y = y - y.mean(axis=0, keepdims=True)
        self.y = y
        return y
