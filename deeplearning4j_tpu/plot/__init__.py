"""Dimensionality-reduction / plotting models (reference
``deeplearning4j-core/.../plot`` — SURVEY.md §2.2)."""

from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne

__all__ = ["BarnesHutTsne", "Tsne"]
