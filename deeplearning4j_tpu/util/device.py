"""Device-memory introspection shared by the two engines' HBM-resident
dataset caches (reference analog: workspace sizing around the nd4j
backends — here the budget bounds how much training data the fused
multi-epoch fit keeps device-resident)."""

from __future__ import annotations

from typing import Optional

_FALLBACK_BYTES = 4 << 30  # no memory_stats(): assume a 16 GiB part
_CACHE_FRACTION = 0.25     # leave the rest for params/acts/workspaces
_cached: Optional[int] = None


def device_cache_budget_bytes(device=None, refresh: bool = False) -> int:
    """Bytes of training data the HBM cache may pin: a quarter of the
    device's reported memory limit, with a 4 GiB fallback when the
    runtime exposes no ``memory_stats()`` (e.g. a tunneled v5e, or the
    CPU backend). Cached per process — device memory size is static."""
    global _cached
    if _cached is not None and not refresh and device is None:
        return _cached
    budget = _FALLBACK_BYTES
    try:
        import jax

        d = device if device is not None else jax.devices()[0]
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        limit = (stats or {}).get("bytes_limit")
        if limit:
            budget = max(256 << 20, int(limit * _CACHE_FRACTION))
    except Exception:
        pass
    if device is None:
        _cached = budget
    return budget
