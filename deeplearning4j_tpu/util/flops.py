"""Absolute-performance accounting: XLA cost-analysis FLOPs for one
train step, plus device peak-FLOP/s lookup, so throughput numbers can
be stated as achieved TFLOP/s and MFU rather than bare examples/sec.

The reference's only performance instrument is relative —
``optimize/listeners/PerformanceListener.java:71-86`` prints
examples/sec — so "fast" is unfalsifiable there. Here the compiled
train step itself is the source of truth: ``jit(step).lower(args)
.compile().cost_analysis()`` returns the FLOPs XLA actually scheduled
(forward + backward + updater), and MFU = achieved / chip peak.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Dense bf16 peak FLOP/s per chip, by device_kind substring (public
# cloud specs). Matching is ordered: first hit wins.
_PEAKS: Tuple[Tuple[str, float], ...] = (
    ("v6 lite", 918e12),  # Trillium / v6e
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def device_peak_flops(device=None) -> Tuple[Optional[float], str]:
    """(bf16 peak FLOP/s, device_kind) for ``device`` (default: the
    first addressable device). Peak is None off-TPU — MFU is only
    defined against a known roofline."""
    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform)
    if d.platform == "tpu":
        low = kind.lower()
        for key, peak in _PEAKS:
            if key in low:
                return peak, kind
    return None, kind


def _cost_dict(compiled) -> dict:
    ca = compiled.cost_analysis()
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def train_step_cost(model, ds) -> dict:
    """Lower ONE jitted train step (forward + loss + backward +
    updater) for ``model`` on minibatch ``ds`` and return XLA's cost
    analysis: ``{"flops", "bytes_accessed", "batch",
    "flops_per_example"}``.

    Uses the model's own ``_build_step`` program — the same XLA
    program ``fit_minibatch`` executes (the scan-fused multi-step path
    runs this body k times), so the count is what actually runs, not an
    analytic estimate. For TBPTT models pass a ds whose sequence length
    equals the tbptt window; per-example cost then scales by
    (full_seq / tbptt_len) chunks.
    """
    if model.params is None:
        model.init()
    if model._jit_step is None:
        model._jit_step = model._build_step()
    is_graph = hasattr(model.conf, "vertices")
    lrs = {
        k: jnp.asarray(v, jnp.float32)
        for k, v in model.updater_def.scheduled_lrs(0).items()
    }
    t = jnp.asarray(1, jnp.float32)
    rng = jax.random.fold_in(model._base_key, 0)
    if is_graph:
        dtype = model._dtype()

        def aslist(v):
            if v is None:
                return None
            seq = v if isinstance(v, (list, tuple)) else [v]
            out = [
                jnp.asarray(a, dtype) if a is not None else None
                for a in seq
            ]
            return out if any(a is not None for a in out) else None

        x = aslist(ds.features)
        y = aslist(ds.labels)
        lmask = aslist(getattr(ds, "labels_masks", None)
                       or getattr(ds, "labels_mask", None))
        fmask = aslist(getattr(ds, "features_masks", None)
                       or getattr(ds, "features_mask", None))
        batch = int(x[0].shape[0])
    else:
        from deeplearning4j_tpu.nn.core import dtype_of, to_device

        dtype = dtype_of(model.conf)
        x = to_device(ds.features, dtype)
        y = to_device(ds.labels, dtype)
        lmask = getattr(ds, "labels_mask", None)
        fmask = getattr(ds, "features_mask", None)
        lmask = jnp.asarray(lmask, dtype) if lmask is not None else None
        fmask = jnp.asarray(fmask, dtype) if fmask is not None else None
        batch = int(x.shape[0])
    lowered = model._jit_step.lower(
        model.params, model.updater_state, model.state,
        x, y, lmask, fmask, lrs, t, rng,
    )
    cost = _cost_dict(lowered.compile())
    flops = float(cost.get("flops", 0.0))
    return {
        "flops": flops,
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "batch": batch,
        "flops_per_example": flops / batch if batch else 0.0,
    }


def jit_cost(jitted, *args, **kwargs) -> dict:
    """Cost analysis of an arbitrary jitted callable on concrete args
    (for paths that don't go through an engine ``_build_step`` — e.g.
    the word2vec fused skip-gram update)."""
    cost = _cost_dict(jitted.lower(*args, **kwargs).compile())
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
    }
