"""Checkpoint save/restore (reference: ``util/ModelSerializer.java:
35,:47,:74-111`` — a zip holding ``configuration.json`` +
``coefficients.bin`` + ``updaterState.bin``).

Same three-part logical layout here (config JSON == the builder's JSON,
params, updater state), with params stored as an npz of named arrays
(``layer/param``) instead of one flat binary — the names make
checkpoints self-describing and shard-assignable under pjit, while
``params_flat`` remains available for flat-view parity. Layer state
(batch-norm running stats, absent in the reference's format because
its BN state lives inside params) is a fourth member.

Writes are atomic **and durable** (temp file + ``fsync`` +
``os.replace`` + directory ``fsync`` — rename alone survives a
process crash but not a power loss). Versioned training checkpoints
(``resilience/checkpoint.py``) pair each zip with a sibling JSON
manifest — ``{"format": 1, "step", "epoch", "file", "crc32",
"size"}`` — so restores verify the zip's CRC-32 before trusting it
and can fall back to an earlier version.

The save path is split in two so write-behind checkpointing can run
the expensive half off the training thread: ``snapshot_model`` takes
buffer-isolated host copies of everything a checkpoint holds (the
only part that must run on the training thread, against a quiescent
model), and ``write_snapshot`` serializes that snapshot to a zip
from any thread. ``snapshot_flat_arrays`` / ``model_from_flat``
expose the same state as one flat ``{section/layer/param: array}``
map — the unit of sharding for the multi-host
``checkpoint-<step>/shard-<rank>.npz`` layout.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import zipfile
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

CONFIG_NAME = "configuration.json"
COEFFICIENTS_NAME = "coefficients.npz"
UPDATER_NAME = "updaterState.npz"
LAYER_STATE_NAME = "layerState.npz"

# snapshot_flat_arrays section prefixes (zip member name sans ".npz")
_FLAT_SECTIONS = ("coefficients", "layerState", "updaterState")


def fsync_dir(path) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    Best-effort: some filesystems refuse O_RDONLY opens of
    directories; losing the directory fsync there degrades to the
    pre-existing crash-only guarantee rather than failing the save.
    """
    try:
        fd = os.open(os.fspath(path) or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path, writer) -> None:
    """Durably write a file: stage to a temp file in the destination
    directory, ``writer(f)`` fills it, fsync the temp file, rename
    into place, fsync the directory. A crash or power loss at any
    point leaves either the old file or the new one — never a torn
    mix."""
    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _flatten_params(params: dict) -> dict:
    out = {}
    for ln, lp in params.items():
        for pn, arr in lp.items():
            out[f"{ln}/{pn}"] = np.asarray(arr)
    return out


def _unflatten_params(d) -> dict:
    out: dict = {}
    keys = d.files if hasattr(d, "files") else d.keys()
    for key in keys:
        # rsplit: layer/vertex names may contain '/', param names never do
        ln, pn = key.rsplit("/", 1)
        out.setdefault(ln, {})[pn] = jnp.asarray(d[key])
    return out


def _flatten_updater(state: dict) -> dict:
    out = {}
    for ln, lp in state.items():
        for pn, tup in lp.items():
            for i, arr in enumerate(tup):
                out[f"{ln}/{pn}/{i}"] = np.asarray(arr)
    return out


def _unflatten_updater(d, template: dict) -> dict:
    out: dict = {}
    for ln, lp in template.items():
        out[ln] = {}
        for pn, tup in lp.items():
            out[ln][pn] = tuple(
                jnp.asarray(d[f"{ln}/{pn}/{i}"]) for i in range(len(tup))
            )
    return out


def _write_npz(zf: zipfile.ZipFile, name: str, arrays: dict) -> None:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    zf.writestr(name, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, name: str):
    return np.load(io.BytesIO(zf.read(name)), allow_pickle=False)


def snapshot_model(model, save_updater: bool = True) -> dict:
    """Buffer-isolated host snapshot of everything ``write_model``
    persists — config doc, params, layer state, canonical updater
    moments (ZeRO shards gathered back to param shapes so the
    checkpoint stays mesh-independent). Every array is a fresh host
    copy sharing no buffers with the live model, so the model may
    keep training while a background thread serializes the snapshot.
    This is the only part of a save that must run on the training
    thread (against a quiescent model)."""
    from deeplearning4j_tpu.nn import core
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        mtype = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        mtype = "ComputationGraph"
    else:
        raise ValueError(f"Cannot serialize {type(model).__name__}")
    upd = None
    if save_updater and model.updater_state is not None:
        upd = model.updater_state
        if getattr(model, "_zero_layout", None):
            # ZeRO-sharded moments: gather the flat shards back to
            # canonical param shapes so the checkpoint is
            # mesh-independent (restore re-shards onto whatever mesh
            # is present — 8-wide, 4-wide, or replicated)
            upd = core.zero_gather_updater_state(upd, model.params)
        upd = core.host_snapshot_tree(upd)
    return {
        "model_type": mtype,
        "configuration": model.conf.to_dict(),
        "iteration_count": model.iteration_count,
        "epoch_count": model.epoch_count,
        "params": core.host_snapshot_tree(model.params),
        "state": core.host_snapshot_tree(
            {ln: st for ln, st in model.state.items() if st}
        ),
        "updater": upd,
    }


def snapshot_conf_doc(snap: dict) -> dict:
    """The ``configuration.json`` document for a snapshot — also what
    a sharded ``manifest.json`` embeds so shard npz files stay pure
    array containers."""
    return {
        "model_type": snap["model_type"],
        "configuration": snap["configuration"],
        "iteration_count": snap["iteration_count"],
        "epoch_count": snap["epoch_count"],
    }


def write_snapshot(snap: dict, path) -> None:
    """Serialize a ``snapshot_model`` dict to a checkpoint zip. Pure
    host-array work — safe on any thread. Path destinations get the
    durable temp + fsync + rename treatment; file-like destinations
    stream directly (no rename to do)."""

    def _write_to(dest) -> None:
        with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(
                CONFIG_NAME, json.dumps(snapshot_conf_doc(snap), indent=2)
            )
            _write_npz(zf, COEFFICIENTS_NAME, _flatten_params(snap["params"]))
            if snap["state"]:
                _write_npz(
                    zf, LAYER_STATE_NAME, _flatten_params(snap["state"])
                )
            if snap["updater"] is not None:
                _write_npz(zf, UPDATER_NAME, _flatten_updater(snap["updater"]))

    if hasattr(path, "write"):
        _write_to(path)
        return
    atomic_write(path, _write_to)


def write_model(model, path, save_updater: bool = True) -> None:
    """Reference ``ModelSerializer.writeModel``, made crash-safe and
    power-loss durable: snapshot on the calling thread, then stage
    the zip to a temp file, fsync, ``os.replace`` into place, and
    fsync the directory — a crash or power loss mid-save can never
    leave a truncated zip where the last good checkpoint was."""
    write_snapshot(snapshot_model(model, save_updater=save_updater), path)


def snapshot_flat_arrays(snap: dict) -> Dict[str, np.ndarray]:
    """A snapshot as one flat ``{section/layer/param: array}`` map
    (sections: ``coefficients``, ``layerState``, ``updaterState``) —
    the unit of sharding for multi-host checkpoints: sorted keys are
    dealt round-robin across ranks, each rank persists only its
    slice, and restore merges the slices back by name."""
    flat: Dict[str, np.ndarray] = {}
    for k, v in _flatten_params(snap["params"]).items():
        flat[f"coefficients/{k}"] = v
    for k, v in _flatten_params(snap["state"]).items():
        flat[f"layerState/{k}"] = v
    if snap["updater"] is not None:
        for k, v in _flatten_updater(snap["updater"]).items():
            flat[f"updaterState/{k}"] = v
    return flat


def model_from_flat(conf_doc: dict, flat, load_updater: bool = True):
    """Rebuild a model from a config document plus the merged flat
    array map of ``snapshot_flat_arrays`` — the restore half of the
    sharded layout, independent of how many shards the map was
    reassembled from."""
    sections: Dict[str, dict] = {s: {} for s in _FLAT_SECTIONS}
    for key, arr in flat.items():
        section, rest = key.split("/", 1)
        if section not in sections:
            raise ValueError(f"Unknown checkpoint shard section: {key}")
        sections[section][rest] = arr
    model = _build_model(conf_doc, expect=None)
    model.init(params=_unflatten_params(sections["coefficients"]))
    for ln, s in _unflatten_params(sections["layerState"]).items():
        model.state[ln] = s
    if load_updater and sections["updaterState"]:
        model.updater_state = _unflatten_updater(
            sections["updaterState"], model.updater_state
        )
    model.iteration_count = conf_doc.get("iteration_count", 0)
    model.epoch_count = conf_doc.get("epoch_count", 0)
    return model


def restore_multi_layer_network(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreMultiLayerNetwork``."""
    return _restore(path, load_updater, expect="MultiLayerNetwork")


def restore_computation_graph(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreComputationGraph``."""
    return _restore(path, load_updater, expect="ComputationGraph")


def restore_model(path, load_updater: bool = True):
    return _restore(path, load_updater, expect=None)


def restore_model_from_bytes(data: bytes, load_updater: bool = True):
    """Restore a model from an in-memory checkpoint zip — the path
    object-store reads take (``store.read(key)`` ->
    ``restore_model_from_bytes``), so serving-tier hot reloads never
    stage a temp file."""
    return _restore(io.BytesIO(data), load_updater, expect=None)


def _build_model(doc: dict, expect: Optional[str]):
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.multi_layer import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    mtype = doc["model_type"]
    if expect is not None and mtype != expect:
        raise ValueError(f"Checkpoint holds a {mtype}, not a {expect}")
    if mtype == "MultiLayerNetwork":
        conf = MultiLayerConfiguration.from_dict(doc["configuration"])
        return MultiLayerNetwork(conf)
    conf = ComputationGraphConfiguration.from_dict(doc["configuration"])
    return ComputationGraph(conf)


def _restore(path, load_updater: bool, expect: Optional[str]):
    with zipfile.ZipFile(path, "r") as zf:
        doc = json.loads(zf.read(CONFIG_NAME))
        model = _build_model(doc, expect)
        params = _unflatten_params(_read_npz(zf, COEFFICIENTS_NAME))
        model.init(params=params)
        names = set(zf.namelist())
        if LAYER_STATE_NAME in names:
            st = _unflatten_params(_read_npz(zf, LAYER_STATE_NAME))
            for ln, s in st.items():
                model.state[ln] = s
        if load_updater and UPDATER_NAME in names:
            model.updater_state = _unflatten_updater(
                _read_npz(zf, UPDATER_NAME), model.updater_state
            )
        model.iteration_count = doc.get("iteration_count", 0)
        model.epoch_count = doc.get("epoch_count", 0)
    return model
