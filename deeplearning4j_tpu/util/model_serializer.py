"""Checkpoint save/restore (reference: ``util/ModelSerializer.java:
35,:47,:74-111`` — a zip holding ``configuration.json`` +
``coefficients.bin`` + ``updaterState.bin``).

Same three-part logical layout here (config JSON == the builder's JSON,
params, updater state), with params stored as an npz of named arrays
(``layer/param``) instead of one flat binary — the names make
checkpoints self-describing and shard-assignable under pjit, while
``params_flat`` remains available for flat-view parity. Layer state
(batch-norm running stats, absent in the reference's format because
its BN state lives inside params) is a fourth member.

Writes are atomic (temp file + ``os.replace``). Versioned training
checkpoints (``resilience/checkpoint.py``) pair each zip with a
sibling JSON manifest — ``{"format": 1, "step", "epoch", "file",
"crc32", "size"}`` — so restores verify the zip's CRC-32 before
trusting it and can fall back to an earlier version.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import zipfile
from typing import Optional

import jax.numpy as jnp
import numpy as np

CONFIG_NAME = "configuration.json"
COEFFICIENTS_NAME = "coefficients.npz"
UPDATER_NAME = "updaterState.npz"
LAYER_STATE_NAME = "layerState.npz"


def _flatten_params(params: dict) -> dict:
    out = {}
    for ln, lp in params.items():
        for pn, arr in lp.items():
            out[f"{ln}/{pn}"] = np.asarray(arr)
    return out


def _unflatten_params(d) -> dict:
    out: dict = {}
    for key in d.files:
        # rsplit: layer/vertex names may contain '/', param names never do
        ln, pn = key.rsplit("/", 1)
        out.setdefault(ln, {})[pn] = jnp.asarray(d[key])
    return out


def _flatten_updater(state: dict) -> dict:
    out = {}
    for ln, lp in state.items():
        for pn, tup in lp.items():
            for i, arr in enumerate(tup):
                out[f"{ln}/{pn}/{i}"] = np.asarray(arr)
    return out


def _unflatten_updater(d, template: dict) -> dict:
    out: dict = {}
    for ln, lp in template.items():
        out[ln] = {}
        for pn, tup in lp.items():
            out[ln][pn] = tuple(
                jnp.asarray(d[f"{ln}/{pn}/{i}"]) for i in range(len(tup))
            )
    return out


def _write_npz(zf: zipfile.ZipFile, name: str, arrays: dict) -> None:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    zf.writestr(name, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, name: str):
    return np.load(io.BytesIO(zf.read(name)), allow_pickle=False)


def write_model(model, path, save_updater: bool = True) -> None:
    """Reference ``ModelSerializer.writeModel``, made crash-safe: the
    zip is staged to a temp file in the destination directory and
    ``os.replace``d into place, so a crash mid-save can never leave a
    truncated zip where the last good checkpoint was (rename is atomic
    within a filesystem; writing the temp next to the target keeps
    both on one). File-like destinations stream directly (no rename
    to do)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        mtype = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        mtype = "ComputationGraph"
    else:
        raise ValueError(f"Cannot serialize {type(model).__name__}")
    conf_doc = {
        "model_type": mtype,
        "configuration": model.conf.to_dict(),
        "iteration_count": model.iteration_count,
        "epoch_count": model.epoch_count,
    }

    def _write_to(dest) -> None:
        with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(CONFIG_NAME, json.dumps(conf_doc, indent=2))
            _write_npz(zf, COEFFICIENTS_NAME, _flatten_params(model.params))
            layer_state = {
                ln: st for ln, st in model.state.items() if st
            }
            if layer_state:
                _write_npz(
                    zf, LAYER_STATE_NAME, _flatten_params(layer_state)
                )
            if save_updater and model.updater_state is not None:
                upd = model.updater_state
                if getattr(model, "_zero_layout", None):
                    # ZeRO-sharded moments: gather the flat shards back
                    # to canonical param shapes so the checkpoint is
                    # mesh-independent (restore re-shards onto whatever
                    # mesh is present — 8-wide, 4-wide, or replicated)
                    from deeplearning4j_tpu.nn import core
                    upd = core.zero_gather_updater_state(
                        upd, model.params
                    )
                _write_npz(zf, UPDATER_NAME, _flatten_updater(upd))

    if hasattr(path, "write"):
        _write_to(path)
        return
    path = os.fspath(path)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            _write_to(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def restore_multi_layer_network(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreMultiLayerNetwork``."""
    return _restore(path, load_updater, expect="MultiLayerNetwork")


def restore_computation_graph(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreComputationGraph``."""
    return _restore(path, load_updater, expect="ComputationGraph")


def restore_model(path, load_updater: bool = True):
    return _restore(path, load_updater, expect=None)


def restore_model_from_bytes(data: bytes, load_updater: bool = True):
    """Restore a model from an in-memory checkpoint zip — the path
    object-store reads take (``store.read(key)`` ->
    ``restore_model_from_bytes``), so serving-tier hot reloads never
    stage a temp file."""
    return _restore(io.BytesIO(data), load_updater, expect=None)


def _restore(path, load_updater: bool, expect: Optional[str]):
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.multi_layer import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as zf:
        doc = json.loads(zf.read(CONFIG_NAME))
        mtype = doc["model_type"]
        if expect is not None and mtype != expect:
            raise ValueError(
                f"Checkpoint holds a {mtype}, not a {expect}"
            )
        if mtype == "MultiLayerNetwork":
            conf = MultiLayerConfiguration.from_dict(doc["configuration"])
            model = MultiLayerNetwork(conf)
        else:
            conf = ComputationGraphConfiguration.from_dict(
                doc["configuration"]
            )
            model = ComputationGraph(conf)
        params = _unflatten_params(_read_npz(zf, COEFFICIENTS_NAME))
        model.init(params=params)
        names = set(zf.namelist())
        if LAYER_STATE_NAME in names:
            st = _unflatten_params(_read_npz(zf, LAYER_STATE_NAME))
            for ln, s in st.items():
                model.state[ln] = s
        if load_updater and UPDATER_NAME in names:
            model.updater_state = _unflatten_updater(
                _read_npz(zf, UPDATER_NAME), model.updater_state
            )
        model.iteration_count = doc.get("iteration_count", 0)
        model.epoch_count = doc.get("epoch_count", 0)
    return model
