"""Load "whatever model file this is" (reference ``ModelGuesser``
utility pattern in dl4j tooling — try each known artifact format in
turn and return the right model object).

Order tried:
1. checkpoint zip (ModelSerializer layout: ``configuration.json`` +
   ``coefficients.npz``) → MultiLayerNetwork / ComputationGraph,
2. bare configuration JSON → un-initialized model from conf,
3. Keras HDF5 (.h5) → imported MultiLayerNetwork / ComputationGraph.
"""

from __future__ import annotations

import json
import os
import zipfile


class ModelGuessingException(ValueError):
    pass


def load_model_guess(path: str):
    """Return a model for any supported artifact (reference
    ``ModelGuesser.loadModelGuess``)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        if "configuration.json" in names:
            from deeplearning4j_tpu.util.model_serializer import (
                restore_model,
            )

            return restore_model(path)
    # HDF5 magic
    with open(path, "rb") as f:
        head = f.read(8)
    if head.startswith(b"\x89HDF\r\n\x1a\n"):
        from deeplearning4j_tpu.modelimport import keras as keras_import

        try:
            return keras_import.import_sequential_model(path)
        except Exception:
            return keras_import.import_functional_api_model(path)
    # conf JSON
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ModelGuessingException(
            f"{path!r} is not a checkpoint zip, Keras HDF5, or "
            f"configuration JSON ({e})"
        )
    return config_guess(d)


def config_guess(d: dict):
    """Model (un-initialized) from a conf dict/JSON (reference
    ``ModelGuesser.loadConfigGuess``)."""
    if isinstance(d, str):
        d = json.loads(d)
    fmt = d.get("format", "")
    if "MultiLayerConfiguration" in fmt:
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(
            MultiLayerConfiguration.from_dict(d)
        )
    if "ComputationGraphConfiguration" in fmt:
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(
            ComputationGraphConfiguration.from_dict(d)
        )
    raise ModelGuessingException(
        f"unrecognized configuration format {fmt!r}"
    )
