"""Utilities (reference ``deeplearning4j-nn/.../util``)."""

from deeplearning4j_tpu.util.model_serializer import (  # noqa: F401
    restore_computation_graph,
    restore_model,
    restore_multi_layer_network,
    write_model,
)
from deeplearning4j_tpu.util.model_guesser import (  # noqa: F401
    ModelGuessingException,
    config_guess,
    load_model_guess,
)
