"""Statistical part-of-speech tagging (reference:
``deeplearning4j-nlp-uima``'s ``PoStagger.java:54`` wraps a TRAINED
OpenNLP maxent model via UIMA; the capability is statistical sequence
tagging, not rule lookup).

TPU-era rebuild: an averaged-perceptron tagger — the standard
lightweight discriminative tagger (greedy left-to-right, contextual +
morphological features, averaged weights) — trained on a checked-in
mini-treebank (Penn-style tags, hand-annotated here; no external model
files, zero downloads). The honest divergence is training-set scale,
not algorithm class: a real deployment calls ``train()`` on a full
treebank through the same API.

``pos_tag`` in :mod:`deeplearning4j_tpu.nlp.treeparser` routes through
the default tagger; the old suffix heuristics remain as
``pos_tag_rules`` and as the final fallback for tokens whose feature
scores tie at zero.
"""

from __future__ import annotations

import json
import random
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


class AveragedPerceptronTagger:
    """Greedy averaged-perceptron POS tagger.

    Standard formulation: per-class weight vectors over sparse binary
    features; on a training mistake, +1 the gold class weights and -1
    the predicted class weights; final weights are the average over
    all update timesteps (which regularizes the late updates)."""

    START = ("-START-", "-START2-")

    def __init__(self):
        self.weights: Dict[str, Dict[str, float]] = {}
        self.classes: set = set()
        # single-tag words bypass scoring (fast path + precision)
        self.tagdict: Dict[str, str] = {}
        # averaging machinery
        self._totals = defaultdict(float)
        self._tstamps = defaultdict(int)
        self._i = 0

    # -- features ------------------------------------------------------

    @staticmethod
    def _normalize(word: str) -> str:
        if word.isdigit():
            return "!DIGIT"
        if any(c.isdigit() for c in word):
            return "!HASDIGIT"
        return word.lower()

    def _features(self, i: int, word: str, context: List[str],
                  prev: str, prev2: str) -> Dict[str, int]:
        """Sparse feature dict for position i (context is padded by
        two START entries)."""
        i += 2
        f: Dict[str, int] = {}

        def add(name, *args):
            f[" ".join((name,) + args)] = 1

        low = word.lower()
        add("bias")
        # the rule tagger's guess as a feature: a morphological prior
        # the perceptron learns to trust per context (and can
        # override) — worth ~15 points of held-out accuracy at
        # mini-treebank scale
        from deeplearning4j_tpu.nlp.treeparser import pos_tag_rules

        add("rule", pos_tag_rules([word])[0])
        add("w", self._normalize(word))
        add("suf3", low[-3:])
        add("suf2", low[-2:])
        add("suf1", low[-1:])
        add("pre1", low[:1])
        add("shape",
            "U" if word.isupper() else
            "T" if word[:1].isupper() else
            "d" if word.isdigit() else "l")
        if "-" in word[1:-1]:
            add("hyphen")
        add("t-1", prev)
        add("t-2", prev2)
        add("t-1t-2", prev, prev2)
        add("t-1w", prev, self._normalize(word))
        add("w-1", self._normalize(context[i - 1]))
        add("w-1suf3", context[i - 1].lower()[-3:])
        add("w-2", self._normalize(context[i - 2]))
        add("w+1", self._normalize(context[i + 1]))
        add("w+1suf3", context[i + 1].lower()[-3:])
        add("w+2", self._normalize(context[i + 2]))
        return f

    # -- scoring / prediction ------------------------------------------

    def _score(self, features: Dict[str, int]) -> Dict[str, float]:
        scores: Dict[str, float] = defaultdict(float)
        for feat in features:
            w = self.weights.get(feat)
            if not w:
                continue
            for cls, wt in w.items():
                scores[cls] += wt
        return scores

    def tag(self, tokens: Sequence[str]) -> List[Tuple[str, str]]:
        """Tag one tokenized sentence: [(word, tag), ...]."""
        prev, prev2 = self.START
        out: List[Tuple[str, str]] = []
        context = (list(self.START) + [
            self._normalize(t) for t in tokens
        ] + ["-END-", "-END2-"])
        for i, word in enumerate(tokens):
            tag = self.tagdict.get(word.lower())
            if tag is None:
                scores = self._score(
                    self._features(i, word, context, prev, prev2)
                )
                if scores:
                    tag = max(self.classes,
                              key=lambda c: (scores.get(c, 0.0), c))
                else:  # wholly unseen features: morphology fallback
                    from deeplearning4j_tpu.nlp.treeparser import (
                        pos_tag_rules,
                    )

                    tag = pos_tag_rules([word])[0]
            out.append((word, tag))
            prev2, prev = prev, tag
        return out

    # -- training ------------------------------------------------------

    def _update(self, truth: str, guess: str,
                features: Dict[str, int]) -> None:
        self._i += 1
        if truth == guess:
            return
        for feat in features:
            w = self.weights.setdefault(feat, {})
            for cls, delta in ((truth, 1.0), (guess, -1.0)):
                key = (feat, cls)
                self._totals[key] += (
                    (self._i - self._tstamps[key]) * w.get(cls, 0.0)
                )
                self._tstamps[key] = self._i
                w[cls] = w.get(cls, 0.0) + delta

    def _average_weights(self) -> None:
        for feat, w in self.weights.items():
            for cls in list(w):
                key = (feat, cls)
                total = self._totals[key] + (
                    (self._i - self._tstamps[key]) * w[cls]
                )
                avg = total / max(self._i, 1)
                if abs(avg) > 1e-9:
                    w[cls] = round(avg, 6)
                else:
                    del w[cls]

    def train(self, sentences: Iterable[List[Tuple[str, str]]],
              n_iter: int = 8, seed: int = 1) -> "AveragedPerceptronTagger":
        """``sentences``: [[(word, tag), ...], ...]."""
        sentences = list(sentences)
        self._make_tagdict(sentences)
        rng = random.Random(seed)
        for _ in range(n_iter):
            for sent in sentences:
                words = [w for w, _ in sent]
                context = (list(self.START) + [
                    self._normalize(w) for w in words
                ] + ["-END-", "-END2-"])
                prev, prev2 = self.START
                for i, (word, truth) in enumerate(sent):
                    guess = self.tagdict.get(word.lower())
                    if guess is None:
                        feats = self._features(
                            i, word, context, prev, prev2
                        )
                        scores = self._score(feats)
                        guess = (
                            max(self.classes,
                                key=lambda c: (scores.get(c, 0.0), c))
                            if scores else truth
                        )
                        self._update(truth, guess, feats)
                    prev2, prev = prev, guess
            rng.shuffle(sentences)
        self._average_weights()
        return self

    def _make_tagdict(self, sentences) -> None:
        counts: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int)
        )
        for sent in sentences:
            for word, tag in sent:
                counts[word.lower()][tag] += 1
                self.classes.add(tag)
        for word, tag_counts in counts.items():
            tag, mode = max(tag_counts.items(), key=lambda kv: kv[1])
            n = sum(tag_counts.values())
            # unambiguous + frequent enough -> closed entry
            if n >= 2 and mode / n >= 0.99:
                self.tagdict[word] = tag

    # -- persistence ----------------------------------------------------

    def save(self, path) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({
                "weights": self.weights,
                "tagdict": self.tagdict,
                "classes": sorted(self.classes),
            }, f)

    @classmethod
    def load(cls, path) -> "AveragedPerceptronTagger":
        t = cls()
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        t.weights = d["weights"]
        t.tagdict = d["tagdict"]
        t.classes = set(d["classes"])
        return t


# ---------------------------------------------------------------------------
# Checked-in mini-treebank (hand-annotated, Penn tags). Scale is the
# documented divergence: a real deployment trains on a full treebank
# through the same train() API.
# ---------------------------------------------------------------------------

_RAW = """
The/DT cat/NN sat/VBD on/IN the/DT mat/NN ./.
A/DT dog/NN barked/VBD at/IN the/DT mailman/NN ./.
She/PRP quickly/RB opened/VBD the/DT old/JJ door/NN ./.
He/PRP reads/VBZ a/DT book/NN every/DT night/NN ./.
They/PRP are/VBP running/VBG in/IN the/DT park/NN ./.
I/PRP have/VBP seen/VBN that/DT movie/NN twice/RB ./.
We/PRP will/MD visit/VB the/DT museum/NN tomorrow/NN ./.
The/DT children/NNS played/VBD with/IN small/JJ toys/NNS ./.
My/PRP$ sister/NN writes/VBZ long/JJ letters/NNS ./.
John/NNP lives/VBZ in/IN London/NNP ./.
Mary/NNP and/CC John/NNP went/VBD to/TO school/NN ./.
The/DT quick/JJ brown/JJ fox/NN jumps/VBZ over/IN the/DT lazy/JJ dog/NN ./.
This/DT model/NN trains/VBZ very/RB fast/RB ./.
The/DT network/NN learned/VBD useful/JJ features/NNS ./.
Researchers/NNS published/VBD three/CD new/JJ papers/NNS ./.
The/DT price/NN rose/VBD by/IN five/CD percent/NN ./.
It/PRP was/VBD raining/VBG heavily/RB yesterday/NN ./.
Birds/NNS fly/VBP south/RB in/IN winter/NN ./.
The/DT teacher/NN gave/VBD us/PRP difficult/JJ homework/NN ./.
You/PRP should/MD eat/VB more/JJR vegetables/NNS ./.
The/DT company/NN has/VBZ hired/VBN two/CD engineers/NNS ./.
Old/JJ houses/NNS often/RB need/VBP expensive/JJ repairs/NNS ./.
The/DT river/NN flows/VBZ through/IN the/DT valley/NN ./.
Students/NNS were/VBD studying/VBG for/IN their/PRP$ exams/NNS ./.
A/DT strong/JJ wind/NN blew/VBD from/IN the/DT north/NN ./.
He/PRP carefully/RB repaired/VBD the/DT broken/JJ clock/NN ./.
The/DT committee/NN approved/VBD the/DT budget/NN quickly/RB ./.
Many/JJ people/NNS enjoy/VBP walking/VBG on/IN the/DT beach/NN ./.
Her/PRP$ answer/NN surprised/VBD everyone/NN ./.
The/DT train/NN arrives/VBZ at/IN nine/CD ./.
Scientists/NNS discovered/VBD a/DT distant/JJ planet/NN ./.
We/PRP watched/VBD the/DT game/NN together/RB ./.
The/DT bread/NN smells/VBZ wonderful/JJ ./.
Workers/NNS built/VBD a/DT tall/JJ bridge/NN ./.
The/DT baby/NN slept/VBD peacefully/RB ./.
I/PRP can/MD hear/VB the/DT music/NN ./.
She/PRP has/VBZ finished/VBN her/PRP$ report/NN ./.
The/DT garden/NN looks/VBZ beautiful/JJ in/IN spring/NN ./.
Heavy/JJ rain/NN flooded/VBD the/DT streets/NNS ./.
They/PRP sell/VBP fresh/JJ fruit/NN at/IN the/DT market/NN ./.
The/DT engine/NN started/VBD immediately/RB ./.
A/DT famous/JJ author/NN signed/VBD my/PRP$ book/NN ./.
Children/NNS love/VBP sweet/JJ desserts/NNS ./.
The/DT manager/NN will/MD announce/VB the/DT results/NNS soon/RB ./.
Wolves/NNS hunt/VBP in/IN packs/NNS ./.
The/DT snow/NN melted/VBD slowly/RB ./.
He/PRP drives/VBZ an/DT electric/JJ car/NN ./.
The/DT lecture/NN was/VBD extremely/RB boring/JJ ./.
Farmers/NNS grow/VBP wheat/NN and/CC corn/NN ./.
The/DT team/NN won/VBD the/DT final/JJ match/NN ./.
She/PRP speaks/VBZ three/CD languages/NNS fluently/RB ./.
The/DT stars/NNS shine/VBP brightly/RB at/IN night/NN ./.
An/DT honest/JJ politician/NN is/VBZ rare/JJ ./.
The/DT chef/NN prepared/VBD a/DT delicious/JJ meal/NN ./.
Tourists/NNS visit/VBP the/DT ancient/JJ castle/NN ./.
The/DT phone/NN rang/VBD twice/RB ./.
I/PRP forgot/VBD my/PRP$ keys/NNS again/RB ./.
The/DT wall/NN was/VBD painted/VBN white/JJ ./.
Doctors/NNS recommend/VBP regular/JJ exercise/NN ./.
The/DT meeting/NN ended/VBD early/RB ./.
Strong/JJ coffee/NN keeps/VBZ me/PRP awake/JJ ./.
The/DT library/NN opens/VBZ at/IN eight/CD ./.
He/PRP threw/VBD the/DT ball/NN over/IN the/DT fence/NN ./.
The/DT old/JJ man/NN walks/VBZ his/PRP$ dog/NN daily/RB ./.
Prices/NNS are/VBP rising/VBG everywhere/RB ./.
The/DT actor/NN forgot/VBD his/PRP$ lines/NNS ./.
A/DT gentle/JJ breeze/NN cooled/VBD the/DT room/NN ./.
They/PRP have/VBP moved/VBN to/TO Paris/NNP ./.
The/DT student/NN asked/VBD a/DT clever/JJ question/NN ./.
Rivers/NNS carry/VBP water/NN to/TO the/DT sea/NN ./.
The/DT clock/NN stopped/VBD at/IN noon/NN ./.
She/PRP wears/VBZ a/DT red/JJ scarf/NN in/IN winter/NN ./.
The/DT bakery/NN sells/VBZ fresh/JJ bread/NN every/DT morning/NN ./.
Which/WDT road/NN leads/VBZ to/TO the/DT village/NN ?/.
Who/WP wrote/VBD this/DT letter/NN ?/.
There/EX is/VBZ a/DT problem/NN with/IN the/DT printer/NN ./.
The/DT results/NNS were/VBD better/JJR than/IN expected/VBN ./.
It/PRP is/VBZ the/DT tallest/JJS building/NN in/IN town/NN ./.
"""


def load_treebank() -> List[List[Tuple[str, str]]]:
    out = []
    for line in _RAW.strip().split("\n"):
        sent = []
        for pair in line.split():
            word, _, tag = pair.rpartition("/")
            sent.append((word, tag))
        out.append(sent)
    return out


_default: Optional[AveragedPerceptronTagger] = None


def default_tagger() -> AveragedPerceptronTagger:
    """The tagger trained on the bundled mini-treebank (cached;
    training takes well under a second)."""
    global _default
    if _default is None:
        _default = AveragedPerceptronTagger().train(load_treebank())
    return _default
