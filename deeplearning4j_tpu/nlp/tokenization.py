"""Text pipeline: sentence iterators + tokenizers (reference:
``text/sentenceiterator/*.java``, ``text/tokenization/**`` —
``DefaultTokenizerFactory`` splits on whitespace after an optional
token preprocessor; preprocessors live in
``tokenization/tokenizer/preprocessor/``).

Pure host-side code — no JAX. The heavy lifting (the training math)
consumes only the integer id streams this module produces.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Callable, Iterable, Iterator, List, Optional

# ---------------------------------------------------------------------------
# Token preprocessors (reference CommonPreprocessor / EndingPreProcessor)
# ---------------------------------------------------------------------------

_PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")


def common_preprocessor(token: str) -> str:
    """Reference ``CommonPreprocessor``: strip punctuation+digits,
    lowercase."""
    return _PUNCT.sub("", token).lower()


class Tokenizer:
    """One document's token stream (reference ``Tokenizer`` SPI)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            t = self._pre(t)
            if t:
                out.append(t)
        return out

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self):
        return iter(self.get_tokens())


class DefaultTokenizerFactory:
    """Whitespace tokenizer (reference
    ``DefaultTokenizerFactory.java``)."""

    def __init__(self):
        self._pre: Optional[Callable[[str], str]] = None

    def set_token_pre_processor(self, pre: Callable[[str], str]) -> None:
        self._pre = pre

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """N-gram over the base tokens (reference
    ``NGramTokenizerFactory.java``)."""

    def __init__(self, min_n: int = 1, max_n: int = 2):
        super().__init__()
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        base = Tokenizer(text.split(), self._pre).get_tokens()
        grams: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                grams.append(" ".join(base[i:i + n]))
        return Tokenizer(grams, None)


# ---------------------------------------------------------------------------
# TokenizerFactory registry — the SPI seam the reference fills with
# per-language modules (deeplearning4j-nlp-japanese's Kuromoji
# JapaneseTokenizer, -korean's KoreanTokenizer, -uima's UimaTokenizer).
# Those vendor third-party analyzers (6.9k LoC of Kuromoji); here the
# seam is an explicit registry: plug any object with
# ``create(text) -> Tokenizer`` and select it by name wherever a
# tokenizer_factory is accepted.
# ---------------------------------------------------------------------------

_TOKENIZER_REGISTRY: dict = {}


def register_tokenizer_factory(name: str, factory_cls) -> None:
    """Register a TokenizerFactory class under a language/name key
    (e.g. 'japanese' -> a Kuromoji-backed implementation)."""
    if not callable(factory_cls):
        raise TypeError("factory_cls must be callable (class or factory)")
    _TOKENIZER_REGISTRY[name.lower()] = factory_cls


def tokenizer_factory(name: str = "default", **kwargs):
    """Instantiate a registered TokenizerFactory by name."""
    key = name.lower()
    if key not in _TOKENIZER_REGISTRY:
        raise KeyError(
            f"no TokenizerFactory registered under {name!r}; known: "
            f"{sorted(_TOKENIZER_REGISTRY)}"
        )
    return _TOKENIZER_REGISTRY[key](**kwargs)


class RegexTokenizerFactory(DefaultTokenizerFactory):
    """Split on a regex (covers the reference's PosUimaTokenizer-style
    customization without UIMA)."""

    def __init__(self, pattern: str = r"\s+"):
        super().__init__()
        self._re = re.compile(pattern)

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(
            [t for t in self._re.split(text) if t], self._pre
        )


class CharTokenizerFactory(DefaultTokenizerFactory):
    """Character-level tokens — a working default for unsegmented CJK
    text until a morphological analyzer is registered (the honest
    stand-in for the vendored Kuromoji)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer([c for c in text if not c.isspace()], self._pre)


register_tokenizer_factory("default", DefaultTokenizerFactory)
register_tokenizer_factory("ngram", NGramTokenizerFactory)
register_tokenizer_factory("regex", RegexTokenizerFactory)
register_tokenizer_factory("char", CharTokenizerFactory)
# CJK entries are registered by deeplearning4j_tpu.nlp.cjk (script-
# class segmentation); replace via register_tokenizer_factory with a
# real morphological analyzer when available.


# ---------------------------------------------------------------------------
# Sentence iterators (reference text/sentenceiterator)
# ---------------------------------------------------------------------------


class SentenceIterator:
    """Resettable stream of sentences (reference ``SentenceIterator``).
    Subclasses implement ``_sentences()``."""

    def __init__(self):
        self.preprocessor: Optional[Callable[[str], str]] = None

    def _sentences(self) -> Iterator[str]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        for s in self._sentences():
            if self.preprocessor is not None:
                s = self.preprocessor(s)
            yield s

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        super().__init__()
        self._data = list(sentences)

    def _sentences(self):
        return iter(self._data)


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference
    ``LineSentenceIterator``)."""

    def __init__(self, path):
        super().__init__()
        self._path = Path(path)

    def _sentences(self):
        with open(self._path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory (reference
    ``FileSentenceIterator``)."""

    def __init__(self, root):
        super().__init__()
        self._root = Path(root)

    def _sentences(self):
        paths = (
            sorted(self._root.rglob("*")) if self._root.is_dir()
            else [self._root]
        )
        for p in paths:
            if not p.is_file():
                continue
            with open(p, "r", encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        yield line


class LabelledDocument:
    """A document with labels (reference ``LabelledDocument`` /
    ``LabelAwareSentenceIterator`` family)."""

    def __init__(self, content: str, labels: Optional[List[str]] = None):
        self.content = content
        self.labels = labels or []


class LabelAwareIterator:
    """Stream of LabelledDocuments for ParagraphVectors (reference
    ``LabelAwareIterator``)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)

    def __iter__(self):
        return iter(self._docs)

    def reset(self):
        pass

    @staticmethod
    def from_texts(texts: Iterable[str], labels: Iterable[str]
                   ) -> "LabelAwareIterator":
        return LabelAwareIterator([
            LabelledDocument(t, [l]) for t, l in zip(texts, labels)
        ])
