"""Inverted index over tokenized documents (reference
``text/invertedindex/InvertedIndex.java`` SPI — the in-memory Lucene
stand-in the reference uses for document sampling and batch iteration).

Host-side structure (posting lists are irregular; nothing here touches
the device). Supports the SPI surface: add docs, fetch documents for a
word, document numbers, batch iteration and a seeded sample generator.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InvertedIndex:
    """Word -> posting list of document ids (reference
    ``InvertedIndex.java``)."""

    def __init__(self, cache: Optional[VocabCache] = None,
                 batch_size: int = 1024):
        self.cache = cache
        self.batch_size = batch_size
        self._docs: List[List[str]] = []
        self._labels: List[Optional[str]] = []
        self._postings: Dict[str, List[int]] = {}

    # -- building --------------------------------------------------------

    def add_word_to_doc(self, doc: int, word: str) -> None:
        while doc >= len(self._docs):
            self._docs.append([])
            self._labels.append(None)
        self._docs[doc].append(word)
        self._postings.setdefault(word, []).append(doc)

    def add_doc(self, words: Sequence[str],
                label: Optional[str] = None) -> int:
        """Append a document; returns its doc number."""
        doc = len(self._docs)
        self._docs.append(list(words))
        self._labels.append(label)
        for w in set(words):
            self._postings.setdefault(w, []).append(doc)
        return doc

    def finish(self) -> None:
        """Posting lists sorted/deduped (reference finish())."""
        for w, lst in self._postings.items():
            self._postings[w] = sorted(set(lst))

    # -- queries ---------------------------------------------------------

    def num_documents(self) -> int:
        return len(self._docs)

    def document(self, doc: int) -> List[str]:
        return list(self._docs[doc])

    def document_label(self, doc: int) -> Optional[str]:
        return self._labels[doc]

    def documents(self, word: str) -> List[int]:
        return list(self._postings.get(word, ()))

    def doc_frequency(self, word: str) -> int:
        return len(set(self._postings.get(word, ())))

    def all_docs(self) -> Iterator[List[str]]:
        return iter(self._docs)

    def batch_iter(self) -> Iterator[List[List[str]]]:
        """Documents in batches of ``batch_size`` (reference
        ``batchIter``)."""
        for i in range(0, len(self._docs), self.batch_size):
            yield self._docs[i:i + self.batch_size]

    def sample(self, n: int, seed: int = 12345) -> List[List[str]]:
        """Seeded document sample (reference's random doc fetch)."""
        rng = np.random.RandomState(seed)
        if not self._docs:
            return []
        idx = rng.randint(0, len(self._docs), size=n)
        return [self._docs[i] for i in idx]

    def eachdoc_with_label(
        self,
    ) -> Iterable[tuple]:
        return zip(self._docs, self._labels)
