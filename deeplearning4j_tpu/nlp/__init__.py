"""NLP/embeddings subsystem (reference: ``deeplearning4j-nlp-parent``,
SURVEY.md §2.6): tokenization pipeline, vocabulary construction,
Word2Vec / GloVe / ParagraphVectors on batched XLA ops.

Architectural divergence from the reference (documented, deliberate):
the reference trains embeddings hogwild — N threads racing on shared
syn0/syn1 (``SequenceVectors.java:935,:1029``). On TPU the idiomatic
equivalent is large-batch synchronous updates: the host pipeline packs
(center, context, negatives) into fixed-shape batches and a single
jitted XLA program applies the fused gather → dot → sigmoid →
scatter-add update. Parity with the reference is therefore
statistical (similarity-task scores), not bitwise (SURVEY.md §7 hard
part 3).
"""

from deeplearning4j_tpu.nlp.tokenization import (  # noqa: F401
    CharTokenizerFactory,
    CollectionSentenceIterator,
    DefaultTokenizerFactory,
    FileSentenceIterator,
    LineSentenceIterator,
    NGramTokenizerFactory,
    RegexTokenizerFactory,
    register_tokenizer_factory,
    tokenizer_factory,
)
from deeplearning4j_tpu.nlp import cjk  # noqa: F401 — registers ja/ko
from deeplearning4j_tpu.nlp import japanese  # noqa: F401 — dict segmenter
from deeplearning4j_tpu.nlp.treeparser import (  # noqa: F401
    Tree,
    TreeParser,
    TreeVectorizer,
    porter_stem,
    pos_tag,
    segment_sentences,
)
from deeplearning4j_tpu.nlp.vectorizers import (  # noqa: F401
    BagOfWordsVectorizer,
    TfidfVectorizer,
)
from deeplearning4j_tpu.nlp.inverted_index import InvertedIndex  # noqa: F401
from deeplearning4j_tpu.nlp.static_word2vec import (  # noqa: F401
    StaticWord2Vec,
    save_static,
)
from deeplearning4j_tpu.nlp.model_utils import (  # noqa: F401
    BasicModelUtils,
    FlatModelUtils,
    TreeModelUtils,
)
from deeplearning4j_tpu.nlp.vocab import (  # noqa: F401
    Huffman,
    VocabCache,
    VocabConstructor,
    VocabWord,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec  # noqa: F401
from deeplearning4j_tpu.nlp.glove import Glove  # noqa: F401
from deeplearning4j_tpu.nlp.paragraph_vectors import (  # noqa: F401
    ParagraphVectors,
)
from deeplearning4j_tpu.nlp.serializer import (  # noqa: F401
    load_binary,
    load_txt,
    read_word_vectors,
    write_binary,
    write_txt,
    write_word_vectors,
)
