"""Text-annotation periphery (reference: `deeplearning4j-nlp-uima` —
`SentenceAnnotator`, `StemmerAnnotator` (Snowball), `PoStagger`
(ClearTK models), `corpora/treeparser/{TreeParser,TreeFactory,
BinarizeTreeTransformer,CollapseUnaries,TreeVectorizer}` — and the
recursive `Tree` structure in `deeplearning4j-nn`
`nn/layers/feedforward/autoencoder/recursive/Tree.java:1`).

The reference drives a UIMA pipeline with external statistical models;
this analog is dependency-free: rule-based sentence segmentation, the
published Porter (1980) stemming algorithm, a suffix-heuristic POS
tagger, and chunk-based constituency trees. The `Tree` node API
(label/children/value/vector, gold label, `is_leaf`, `yield_leaves`)
matches the reference contract so recursive models consume either."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# sentence segmentation (SentenceAnnotator analog)
# ---------------------------------------------------------------------------

_ABBREV = {
    "mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc",
    "e.g", "i.e", "fig", "al", "inc", "ltd", "co", "corp", "no",
    "a.m", "p.m",
}
_SENT_END = re.compile(r"([.!?]+)(\s+|$)")


def segment_sentences(text: str) -> List[str]:
    """Split text into sentences on ., !, ? — holding back common
    abbreviations and initials (reference SentenceAnnotator's UIMA
    segmenter)."""
    sentences: List[str] = []
    start = 0
    for m in _SENT_END.finditer(text):
        prev = text[start:m.start()].rstrip()
        last_word = prev.rsplit(None, 1)[-1].lower() if prev else ""
        last_word = last_word.rstrip(".")
        if last_word in _ABBREV or (
            len(last_word) == 1 and last_word.isalpha()
        ):
            continue  # "Dr." / middle initial — not a boundary
        sent = text[start:m.end()].strip()
        if sent:
            sentences.append(sent)
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences


# ---------------------------------------------------------------------------
# Porter stemmer (StemmerAnnotator analog) — implements the published
# Porter (1980) algorithm steps 1a-5b
# ---------------------------------------------------------------------------

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Porter's m: number of VC sequences."""
    forms = "".join(
        "c" if _is_cons(stem, i) else "v" for i in range(len(stem))
    )
    return len(re.findall("vc", forms))


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (
        len(word) >= 2
        and word[-1] == word[-2]
        and _is_cons(word, len(word) - 1)
    )


def _ends_cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (
        _is_cons(word, len(word) - 3)
        and not _is_cons(word, len(word) - 2)
        and _is_cons(word, len(word) - 1)
        and word[-1] not in "wxy"
    )


_STEP2 = [
    ("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
    ("anci", "ance"), ("izer", "ize"), ("abli", "able"), ("alli", "al"),
    ("entli", "ent"), ("eli", "e"), ("ousli", "ous"), ("ization", "ize"),
    ("ation", "ate"), ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
    ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
    ("iviti", "ive"), ("biliti", "ble"),
]
_STEP3 = [
    ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
    ("ical", "ic"), ("ful", ""), ("ness", ""),
]
_STEP4 = [
    "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
    "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
]


def porter_stem(word: str) -> str:
    """Porter (1980) stemmer, the classic Snowball-English ancestor
    (reference StemmerAnnotator wraps Snowball)."""
    w = word.lower()
    if len(w) <= 2:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif (w.endswith("ed") and _has_vowel(w[:-2])) or (
        w.endswith("ing") and _has_vowel(w[:-3])
    ):
        w = w[:-2] if w.endswith("ed") else w[:-3]
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and w[-1] not in "lsz":
            w = w[:-1]
        elif _measure(w) == 1 and _ends_cvc(w):
            w += "e"
    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in _STEP2:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # step 3
    for suf, rep in _STEP3:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # step 4
    for suf in _STEP4:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _measure(stem) > 1:
                w = stem
            break
    else:
        if w.endswith("ion") and _measure(w[:-3]) > 1 and \
                w[:-3].endswith(("s", "t")):
            w = w[:-3]
    # step 5a
    if w.endswith("e"):
        m = _measure(w[:-1])
        if m > 1 or (m == 1 and not _ends_cvc(w[:-1])):
            w = w[:-1]
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


# ---------------------------------------------------------------------------
# POS-lite tagger (PoStagger analog)
# ---------------------------------------------------------------------------

_CLOSED = {
    "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
    "these": "DT", "those": "DT", "every": "DT", "each": "DT",
    "and": "CC", "or": "CC", "but": "CC", "nor": "CC",
    "in": "IN", "on": "IN", "at": "IN", "of": "IN", "for": "IN",
    "with": "IN", "to": "TO", "by": "IN", "from": "IN", "over": "IN",
    "under": "IN", "through": "IN", "than": "IN", "as": "IN",
    "into": "IN", "about": "IN", "after": "IN", "before": "IN",
    # be/have/do — the irregular auxiliaries every tagger ships as
    # closed-class entries (OpenNLP's dictionaries do the same)
    "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD", "be": "VB",
    "been": "VBN", "being": "VBG", "am": "VBP",
    "have": "VBP", "has": "VBZ", "had": "VBD",
    "do": "VBP", "does": "VBZ", "did": "VBD", "done": "VBN",
    # modals
    "can": "MD", "could": "MD", "may": "MD", "might": "MD",
    "must": "MD", "shall": "MD", "should": "MD", "will": "MD",
    "would": "MD",
    # pronouns / possessives / wh
    "he": "PRP", "she": "PRP", "it": "PRP", "they": "PRP", "we": "PRP",
    "i": "PRP", "you": "PRP", "me": "PRP", "him": "PRP", "her": "PRP",
    "us": "PRP", "them": "PRP",
    "my": "PRP$", "your": "PRP$", "his": "PRP$", "its": "PRP$",
    "our": "PRP$", "their": "PRP$",
    "who": "WP", "what": "WP", "which": "WDT", "whose": "WP$",
    "there": "EX",
    # small numerals (larger ones hit the digit rule)
    "one": "CD", "two": "CD", "three": "CD", "four": "CD",
    "five": "CD", "six": "CD", "seven": "CD", "eight": "CD",
    "nine": "CD", "ten": "CD",
    # frequent irregular verb forms
    "went": "VBD", "gone": "VBN", "came": "VBD", "come": "VB",
    "saw": "VBD", "seen": "VBN", "sat": "VBD", "said": "VBD",
    "made": "VBD", "took": "VBD", "taken": "VBN", "got": "VBD",
    "gave": "VBD", "given": "VBN", "knew": "VBD", "known": "VBN",
    "found": "VBD", "thought": "VBD", "told": "VBD", "became": "VBD",
    "left": "VBD", "kept": "VBD", "held": "VBD", "brought": "VBD",
    "wrote": "VBD", "written": "VBN", "stood": "VBD", "heard": "VBD",
    "met": "VBD", "ran": "VBD", "won": "VBD", "threw": "VBD",
    "blew": "VBD", "grew": "VBD", "flew": "VBD", "drove": "VBD",
    "rose": "VBD", "fell": "VBD", "built": "VBD", "slept": "VBD",
    "spoke": "VBD", "broke": "VBD", "broken": "VBN", "bought": "VBD",
    "caught": "VBD", "taught": "VBD", "felt": "VBD", "lost": "VBD",
    "rang": "VBD", "sang": "VBD", "swam": "VBD", "forgot": "VBD",
    # frequent adverbs that morphology misses
    "not": "RB", "very": "RB", "too": "RB", "also": "RB", "often": "RB",
    "never": "RB", "always": "RB", "again": "RB", "soon": "RB",
    "twice": "RB", "once": "RB", "here": "RB", "now": "RB",
    "then": "RB", "together": "RB", "away": "RB",
}


def pos_tag(tokens: Sequence[str]) -> List[str]:
    """Statistical POS tags via the averaged-perceptron tagger
    (reference ``PoStagger.java:54`` wraps a trained OpenNLP model;
    :mod:`deeplearning4j_tpu.nlp.pos_tagger` is the trained-model
    analog). The rule tagger below stays as the dependency-free
    fallback (``pos_tag_rules``) and backstops unseen feature sets."""
    if not tokens:
        return []
    from deeplearning4j_tpu.nlp.pos_tagger import default_tagger

    return [t for _, t in default_tagger().tag(list(tokens))]


def pos_tag_rules(tokens: Sequence[str]) -> List[str]:
    """Suffix-heuristic POS tags (closed-class lexicon + morphology) —
    the pre-statistical fallback."""
    tags = []
    for tok in tokens:
        low = tok.lower()
        if low in _CLOSED:
            tags.append(_CLOSED[low])
        elif re.fullmatch(r"[-+]?\d[\d.,]*", tok):
            tags.append("CD")
        elif low.endswith("ly"):
            tags.append("RB")
        elif low.endswith("ing"):
            tags.append("VBG")
        elif low.endswith("ed"):
            tags.append("VBD")
        elif low.endswith(("ous", "ful", "ive", "able", "al", "ic")):
            tags.append("JJ")
        elif tok[:1].isupper():
            tags.append("NNP")
        elif low.endswith("s") and not low.endswith("ss"):
            tags.append("NNS")
        else:
            tags.append("NN")
    return tags


# ---------------------------------------------------------------------------
# Tree structure + parser + vectorizer (Tree.java / treeparser analog)
# ---------------------------------------------------------------------------


@dataclass
class Tree:
    """Recursive constituency node (reference `Tree.java:1` — label,
    children, value/vector slots for recursive autoencoders, gold
    label, tokens)."""

    label: str = ""
    children: List["Tree"] = field(default_factory=list)
    value: Optional[str] = None          # surface token for leaves
    vector: Optional[np.ndarray] = None  # attached by TreeVectorizer
    gold_label: int = 0
    prediction: Optional[np.ndarray] = None

    def is_leaf(self) -> bool:
        return not self.children

    def is_preterminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def yield_leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.yield_leaves())
        return out

    def tokens(self) -> List[str]:
        return [leaf.value for leaf in self.yield_leaves()
                if leaf.value is not None]

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def clone(self) -> "Tree":
        return Tree(
            label=self.label,
            children=[c.clone() for c in self.children],
            value=self.value,
            vector=None if self.vector is None else self.vector.copy(),
            gold_label=self.gold_label,
        )


def binarize(tree: Tree) -> Tree:
    """Right-binarize n-ary nodes (reference
    ``BinarizeTreeTransformer``)."""
    if tree.is_leaf():
        return tree.clone()  # fresh leaves: vectorizing the binarized
        # tree must not mutate the input tree's nodes
    kids = [binarize(c) for c in tree.children]
    while len(kids) > 2:
        right = Tree(label=f"@{tree.label}", children=kids[-2:])
        kids = kids[:-2] + [right]
    return Tree(label=tree.label, children=kids, value=tree.value,
                gold_label=tree.gold_label)


def collapse_unaries(tree: Tree) -> Tree:
    """Collapse unary chains X->Y->... (reference
    ``CollapseUnaries``), keeping preterminal->leaf."""
    t = tree
    while (
        len(t.children) == 1
        and not t.children[0].is_leaf()
        and not t.children[0].is_preterminal()
    ):
        t = t.children[0]
    return Tree(label=tree.label, children=[
        collapse_unaries(c) for c in t.children
    ], value=t.value, gold_label=tree.gold_label)


class TreeParser:
    """Sentence -> chunked constituency Tree (reference ``TreeParser``
    drives a UIMA/OpenNLP parser; the analog builds flat NP/VP/PP
    chunks from POS-lite tags under a sentence root)."""

    _CHUNK_OF = {
        "DT": "NP", "JJ": "NP", "NN": "NP", "NNS": "NP", "NNP": "NP",
        "PRP": "NP", "CD": "NP",
        "VB": "VP", "VBZ": "VP", "VBP": "VP", "VBD": "VP", "VBG": "VP",
        "RB": "VP",
        "IN": "PP", "TO": "PP",
    }

    def __init__(self, tokenizer_factory=None):
        if tokenizer_factory is None:
            from deeplearning4j_tpu.nlp.tokenization import (
                DefaultTokenizerFactory,
            )
            tokenizer_factory = DefaultTokenizerFactory()
        self.tf = tokenizer_factory

    def parse(self, sentence: str) -> Tree:
        tokens = list(self.tf.create(sentence).get_tokens())
        tags = pos_tag(tokens)
        root = Tree(label="S")
        chunk: Optional[Tree] = None
        chunk_kind = None
        for tok, tag in zip(tokens, tags):
            kind = self._CHUNK_OF.get(tag, "X")
            if chunk is None or kind != chunk_kind:
                chunk = Tree(label=kind)
                root.children.append(chunk)
                chunk_kind = kind
            chunk.children.append(
                Tree(label=tag, children=[Tree(value=tok, label=tok)])
            )
        return root

    def trees(self, text: str) -> List[Tree]:
        """All sentences of ``text`` parsed (reference
        ``TreeParser.getTrees``)."""
        return [self.parse(s) for s in segment_sentences(text)]


class TreeVectorizer:
    """Attach word vectors to every leaf (reference ``TreeVectorizer``
    feeds trees to the recursive autoencoder). ``lookup`` is any
    ``word -> vector | None`` callable — e.g. ``Word2Vec.
    get_word_vector`` — unknown words get zeros."""

    def __init__(self, lookup: Callable[[str], Optional[np.ndarray]],
                 layer_size: int, *, stem: bool = True):
        self.lookup = lookup
        self.layer_size = layer_size
        self.stem = stem

    def vectorize(self, tree: Tree) -> Tree:
        for leaf in tree.yield_leaves():
            word = leaf.value or ""
            v = self.lookup(word)
            if v is None and self.stem:
                # vocabularies hold surface forms; only fall back to
                # the Porter stem ("happi") when the word itself misses
                v = self.lookup(porter_stem(word))
            leaf.vector = (
                np.zeros(self.layer_size, np.float32)
                if v is None else np.asarray(v, np.float32)
            )
        return tree

    def trees_with_vectors(self, text: str,
                           parser: Optional[TreeParser] = None):
        parser = parser or TreeParser()
        return [self.vectorize(t) for t in parser.trees(text)]
