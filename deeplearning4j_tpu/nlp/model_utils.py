"""Similarity / nearest-word utilities over a trained embedding model
(reference ``models/embeddings/reader/impl/BasicModelUtils.java``,
``FlatModelUtils.java``, ``TreeModelUtils.java:1`` — the pluggable
``ModelUtils`` SPI behind ``wordsNearest``).

- ``FlatModelUtils``: exact brute-force cosine scan (one [V, D] @ [D]
  matvec — MXU-friendly, exact).
- ``BasicModelUtils``: flat scan + the reference's extras
  (``words_nearest_sum`` analogy arithmetic, similarity).
- ``TreeModelUtils``: VPTree-backed approximate k-NN over normalized
  vectors (reference builds the tree once and searches it; right call
  for repeated queries over very large vocabs on host).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


def _resolve(model) -> Tuple:
    """(cache, matrix) from a trained model / lookup / pair (same duck
    typing as nlp.serializer)."""
    from deeplearning4j_tpu.nlp.serializer import _resolve as r

    return r(model)


class BasicModelUtils:
    """Exact cosine utilities (reference ``BasicModelUtils.java``)."""

    def __init__(self, model=None):
        self.cache = None
        self._m = None
        self._norm = None
        if model is not None:
            self.init(model)

    def init(self, model) -> None:
        self.cache, m = _resolve(model)
        self._m = np.asarray(m, np.float32)
        norms = np.linalg.norm(self._m, axis=1, keepdims=True)
        self._norm = self._m / np.maximum(norms, 1e-12)

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.cache.index_of(a), self.cache.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        return float(self._norm[ia] @ self._norm[ib])

    def words_nearest(self, word_or_vec, n: int = 10,
                      exclude: Sequence[str] = ()) -> List[str]:
        if isinstance(word_or_vec, str):
            i = self.cache.index_of(word_or_vec)
            if i < 0:
                return []
            v = self._norm[i]
            exclude = set(exclude) | {word_or_vec}
        else:
            v = np.asarray(word_or_vec, np.float32)
            nv = np.linalg.norm(v)
            v = v / max(nv, 1e-12)
            exclude = set(exclude)
        sims = self._norm @ v
        order = np.argsort(-sims)
        out = []
        for idx in order:
            w = self.cache.word_at(int(idx))
            if w in exclude:
                continue
            out.append(w)
            if len(out) >= n:
                break
        return out

    def words_nearest_sum(self, positive: Sequence[str],
                          negative: Sequence[str] = (),
                          n: int = 10) -> List[str]:
        """king - man + woman analogy arithmetic (reference
        ``wordsNearestSum``)."""
        v = np.zeros((self._m.shape[1],), np.float32)
        for w in positive:
            i = self.cache.index_of(w)
            if i >= 0:
                v += self._norm[i]
        for w in negative:
            i = self.cache.index_of(w)
            if i >= 0:
                v -= self._norm[i]
        return self.words_nearest(
            v, n, exclude=list(positive) + list(negative)
        )


class FlatModelUtils(BasicModelUtils):
    """Alias — the flat scan IS the basic implementation here
    (reference keeps them separate because BasicModelUtils adds
    adagrad-aware lookups)."""


class TreeModelUtils(BasicModelUtils):
    """VPTree-backed nearest words (reference
    ``TreeModelUtils.java`` — builds the tree lazily on first query)."""

    def __init__(self, model=None, seed: int = 12345):
        self._tree: Optional[VPTree] = None
        self._seed = seed
        super().__init__(model)

    def init(self, model) -> None:
        super().init(model)
        self._tree = None

    def _ensure_tree(self) -> None:
        if self._tree is None:
            self._tree = VPTree(
                self._norm, similarity_function="cosinesimilarity",
                invert=True, seed=self._seed,
            )

    def words_nearest(self, word_or_vec, n: int = 10,
                      exclude: Sequence[str] = ()) -> List[str]:
        self._ensure_tree()
        if isinstance(word_or_vec, str):
            i = self.cache.index_of(word_or_vec)
            if i < 0:
                return []
            v = self._norm[i]
            exclude = set(exclude) | {word_or_vec}
        else:
            v = np.asarray(word_or_vec, np.float32)
            v = v / max(np.linalg.norm(v), 1e-12)
            exclude = set(exclude)
        # over-fetch to survive the exclusions, then filter
        k = min(n + len(exclude) + 1, self._norm.shape[0])
        idxs, _ = self._tree.search(v, k)
        out = []
        for idx in idxs:
            w = self.cache.word_at(int(idx))
            if w in exclude:
                continue
            out.append(w)
            if len(out) >= n:
                break
        return out
