"""Bag-of-words / TF-IDF text vectorizers (reference
``bagofwords/vectorizer/BagOfWordsVectorizer.java``,
``TfidfVectorizer.java:1``, ``BaseTextVectorizer.java`` — fit a vocab
over labeled documents, then ``vectorize(text, label) -> DataSet``).

The fit pass builds the vocab + document frequencies host-side (the
reference's VocabConstructor pass); transform is a dense [1, V] row —
small enough that sparse storage buys nothing on the MXU path where
these rows feed classifier matmuls.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BaseTextVectorizer:
    """Shared fit machinery (reference ``BaseTextVectorizer.java``)."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory=None,
                 stop_words: Optional[Sequence[str]] = None,
                 labels: Optional[Sequence[str]] = None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = set(stop_words or ())
        self.labels: List[str] = list(labels or [])
        self.cache: Optional[VocabCache] = None
        self.doc_freq: Optional[np.ndarray] = None
        self.n_docs = 0

    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def fit(self, documents: Iterable[Tuple[str, str]]) -> None:
        """``documents``: (text, label) pairs (reference
        LabelAwareIterator)."""
        token_lists = []
        doc_labels = []
        for text, label in documents:
            token_lists.append(self._tokens(text))
            doc_labels.append(label)
        for lab in doc_labels:
            if lab not in self.labels:
                self.labels.append(lab)
        self.cache = VocabConstructor(
            min_word_frequency=self.min_word_frequency
        ).build_vocab_from_tokens(token_lists)
        self.n_docs = len(token_lists)
        df = np.zeros((len(self.cache),), np.int64)
        for toks in token_lists:
            for w in set(toks):
                i = self.cache.index_of(w)
                if i >= 0:
                    df[i] += 1
        self.doc_freq = df

    # -- transform -------------------------------------------------------

    def _counts(self, text: str) -> np.ndarray:
        row = np.zeros((len(self.cache),), np.float32)
        for w in self._tokens(text):
            i = self.cache.index_of(w)
            if i >= 0:
                row[i] += 1.0
        return row

    def _weights(self, counts: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, text: str) -> np.ndarray:
        """[V] weight row for one document."""
        if self.cache is None:
            raise RuntimeError("fit() first")
        return self._weights(self._counts(text))

    def vectorize(self, text: str, label: str) -> DataSet:
        """(reference ``vectorize(String, String) -> DataSet``)."""
        row = self.transform(text)[None, :]
        y = np.zeros((1, max(len(self.labels), 1)), np.float32)
        if label in self.labels:
            y[0, self.labels.index(label)] = 1.0
        return DataSet(features=row, labels=y)

    def vectorize_all(
        self, documents: Iterable[Tuple[str, str]]
    ) -> DataSet:
        rows, ys = [], []
        for text, label in documents:
            ds = self.vectorize(text, label)
            rows.append(ds.features[0])
            ys.append(ds.labels[0])
        return DataSet(features=np.stack(rows), labels=np.stack(ys))


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw term counts (reference ``BagOfWordsVectorizer.java``)."""

    def _weights(self, counts: np.ndarray) -> np.ndarray:
        return counts


class TfidfVectorizer(BaseTextVectorizer):
    """tf-idf weights (reference ``TfidfVectorizer.java``: tf = raw
    count in document, idf = log(nDocs / docFreq), matching the
    reference's MathUtils.tfidf/idf conventions with the standard
    guard against zero document frequency)."""

    def _weights(self, counts: np.ndarray) -> np.ndarray:
        idf = np.log(
            self.n_docs / np.maximum(self.doc_freq, 1)
        ).astype(np.float32)
        return counts * idf

    def tfidf_word(self, word: str, text: str) -> float:
        """Single-word score for one document (reference
        ``tfidfWord``)."""
        counts = self._counts(text)
        i = self.cache.index_of(word)
        if i < 0:
            return 0.0
        idf = math.log(self.n_docs / max(float(self.doc_freq[i]), 1.0))
        return float(counts[i] * idf)
