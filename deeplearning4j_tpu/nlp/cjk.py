"""CJK tokenizers (reference: `deeplearning4j-nlp-japanese`'s vendored
Kuromoji wrapper `JapaneseTokenizer`, `deeplearning4j-nlp-korean`'s
twitter-korean-text wrapper `KoreanTokenizer.java:35`).

Dependency-free analogs built on Unicode character classes instead of
vendored third-party analyzers:

- Japanese has no inter-word whitespace; Kuromoji segments with a
  morpheme lattice. The analog here segments on script-class
  boundaries (kanji / hiragana / katakana / latin / digits), which is
  the standard zero-dependency fallback. Documented divergence: runs
  of same-script characters are NOT split into individual morphemes.
- Korean IS whitespace-delimited (eojeol); twitter-korean-text
  additionally strips/splits particles. The analog splits on
  whitespace + punctuation and keeps hangul runs intact.

Both register in the TokenizerFactory registry
(`register_tokenizer_factory`), which is the reference's SPI seam
(`text/tokenization/tokenizerfactory/`)."""

from __future__ import annotations

import unicodedata
from typing import List

from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer,
    register_tokenizer_factory,
)


def _script_class(ch: str) -> str:
    """Coarse script class used for segmentation boundaries."""
    cp = ord(ch)
    if 0x3040 <= cp <= 0x309F:
        return "hiragana"
    if 0x30A0 <= cp <= 0x30FF or 0x31F0 <= cp <= 0x31FF:
        return "katakana"
    if (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0xF900 <= cp <= 0xFAFF
    ):
        return "kanji"
    if (
        0xAC00 <= cp <= 0xD7AF       # syllables
        or 0x1100 <= cp <= 0x11FF    # jamo
        or 0x3130 <= cp <= 0x318F    # compatibility jamo (e.g. ㅋㅋ)
        or 0xA960 <= cp <= 0xA97F    # jamo extended-A
    ):
        return "hangul"
    if ch.isspace():
        return "space"
    if ch.isdigit():
        return "digit"
    cat = unicodedata.category(ch)
    if cat.startswith("P") or cat.startswith("S"):
        return "punct"
    return "other"  # latin & everything else alphabetic


def segment_by_script(text: str, *, keep_punct: bool = False) -> List[str]:
    """Split ``text`` into runs of identical script class. Whitespace
    always separates; punctuation is dropped unless ``keep_punct``."""
    tokens: List[str] = []
    run: List[str] = []
    run_class = None
    for ch in text:
        c = _script_class(ch)
        if c != run_class:
            if run:
                tokens.append("".join(run))
            run = []
            run_class = c
        if c == "space":
            run = []
            run_class = None
            continue
        if c == "punct" and not keep_punct:
            run = []
            run_class = None
            continue
        run.append(ch)
    if run:
        tokens.append("".join(run))
    return tokens


class JapaneseTokenizerFactory:
    """Script-class segmentation for Japanese text (Kuromoji-wrapper
    analog, `deeplearning4j-nlp-japanese`). ``preprocessor`` follows
    the reference's TokenPreProcess seam."""

    def __init__(self, preprocessor=None, keep_punct: bool = False):
        self.preprocessor = preprocessor
        self.keep_punct = keep_punct

    def create(self, text: str) -> Tokenizer:
        # Tokenizer's preprocessor seam also drops emptied tokens
        # (e.g. a digit-only token a CommonPreprocessor maps to "")
        return Tokenizer(
            segment_by_script(text, keep_punct=self.keep_punct),
            self.preprocessor,
        )


class KoreanTokenizerFactory(JapaneseTokenizerFactory):
    """Eojeol (whitespace) tokenization with punctuation stripped
    (twitter-korean-text wrapper analog, ``KoreanTokenizer.java:35``).
    Korean is whitespace-delimited, which script-class segmentation
    already honors; mixed-script eojeols split on script boundaries so
    hangul runs separate from embedded latin/digits."""

    def __init__(self, preprocessor=None):
        super().__init__(preprocessor, keep_punct=False)


register_tokenizer_factory("japanese", JapaneseTokenizerFactory)
# explicit name for the script-run fallback; nlp.japanese re-registers
# "japanese" with the dictionary/Viterbi segmenter on package import
register_tokenizer_factory("japanese_script", JapaneseTokenizerFactory)
register_tokenizer_factory("korean", KoreanTokenizerFactory)
