"""Word-vector serialization (reference:
``models/embeddings/loader/WordVectorSerializer.java``, 2,603 LoC —
txt, Google word2vec binary, and zip formats).

Formats:
- txt: first line "V D", then one "word v1 v2 ..." per line
  (Google text format; reference ``writeWordVectors``/``loadTxt``).
- binary: header "V D\\n", then per word: name + 0x20 + D float32 LE
  (Google ``word2vec`` C binary; reference ``loadGoogleModel``).
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def _resolve(model) -> Tuple[VocabCache, np.ndarray]:
    """Accept a SequenceVectors/Word2Vec/Glove or (cache, matrix)."""
    if isinstance(model, tuple):
        return model
    cache = model.cache
    if hasattr(model, "lookup"):
        matrix = np.asarray(model.lookup.syn0)
    else:
        matrix = np.asarray(model.syn0)
    return cache, matrix


def write_txt(model, path) -> None:
    cache, m = _resolve(model)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{m.shape[0]} {m.shape[1]}\n")
        for i in range(m.shape[0]):
            vals = " ".join(repr(float(x)) for x in m[i])
            f.write(f"{cache.word_at(i)} {vals}\n")


def load_txt(path) -> Tuple[VocabCache, np.ndarray]:
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        v, d = int(header[0]), int(header[1])
        cache = VocabCache()
        m = np.zeros((v, d), np.float32)
        for i in range(v):
            # rsplit from the right: the word itself may contain
            # spaces (n-gram vocab entries)
            parts = f.readline().rstrip("\n").rsplit(" ", d)
            cache.add(VocabWord(parts[0]))
            m[i] = [float(x) for x in parts[1:d + 1]]
    return cache, m


def write_binary(model, path) -> None:
    """Google word2vec C binary format. Words containing spaces are
    written with '_' in their place (the word2vec phrases convention —
    the space is the field terminator in this format)."""
    cache, m = _resolve(model)
    with open(path, "wb") as f:
        f.write(f"{m.shape[0]} {m.shape[1]}\n".encode())
        for i in range(m.shape[0]):
            word = cache.word_at(i).replace(" ", "_")
            f.write(word.encode("utf-8") + b" ")
            f.write(m[i].astype("<f4").tobytes())
            f.write(b"\n")


def load_binary(path) -> Tuple[VocabCache, np.ndarray]:
    with open(path, "rb") as f:
        header = f.readline().split()
        v, d = int(header[0]), int(header[1])
        cache = VocabCache()
        m = np.zeros((v, d), np.float32)
        for i in range(v):
            word = bytearray()
            while True:
                ch = f.read(1)
                if ch in (b" ", b""):
                    break
                word.extend(ch)
            cache.add(VocabWord(word.decode("utf-8")))
            m[i] = np.frombuffer(f.read(4 * d), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                # older files omit the newline; step back
                f.seek(-1, 1)
    return cache, m


def write_word_vectors(model, path) -> None:
    """Dispatch on extension (.bin → binary, else txt) — reference
    ``writeWordVectors`` overloads."""
    if str(path).endswith(".bin"):
        write_binary(model, path)
    else:
        write_txt(model, path)


def read_word_vectors(path) -> Tuple[VocabCache, np.ndarray]:
    if str(path).endswith(".bin"):
        return load_binary(path)
    return load_txt(path)
