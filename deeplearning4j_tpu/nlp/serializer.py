"""Word-vector serialization (reference:
``models/embeddings/loader/WordVectorSerializer.java``, 2,603 LoC —
txt, Google word2vec binary, and zip formats).

Formats:
- txt: first line "V D", then one "word v1 v2 ..." per line
  (Google text format; reference ``writeWordVectors``/``loadTxt``).
- binary: header "V D\\n", then per word: name + 0x20 + D float32 LE
  (Google ``word2vec`` C binary; reference ``loadGoogleModel``).
- full model: zip of config.json + vocab.json + tables.npz preserving
  ALL training state — syn0 AND syn1/syn1neg + Huffman coding + word
  counts — so ``fit()`` resumes from disk (reference
  ``writeFullModel``/``loadFullModel``; the txt/binary interop formats
  keep only syn0 and cannot resume).
"""

from __future__ import annotations

import json
import zipfile
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def _resolve(model) -> Tuple[VocabCache, np.ndarray]:
    """Accept a SequenceVectors/Word2Vec/Glove or (cache, matrix)."""
    if isinstance(model, tuple):
        return model
    cache = model.cache
    if hasattr(model, "lookup"):
        matrix = np.asarray(model.lookup.syn0)
    else:
        matrix = np.asarray(model.syn0)
    return cache, matrix


def write_txt(model, path) -> None:
    cache, m = _resolve(model)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{m.shape[0]} {m.shape[1]}\n")
        for i in range(m.shape[0]):
            vals = " ".join(repr(float(x)) for x in m[i])
            f.write(f"{cache.word_at(i)} {vals}\n")


def _parse_txt(f) -> Tuple[VocabCache, np.ndarray]:
    header = f.readline().split()
    v, d = int(header[0]), int(header[1])
    cache = VocabCache()
    m = np.zeros((v, d), np.float32)
    for i in range(v):
        # rsplit from the right: the word itself may contain
        # spaces (n-gram vocab entries)
        parts = f.readline().rstrip("\n").rsplit(" ", d)
        cache.add(VocabWord(parts[0]))
        m[i] = [float(x) for x in parts[1:d + 1]]
    return cache, m


def load_txt(path) -> Tuple[VocabCache, np.ndarray]:
    with open(path, "r", encoding="utf-8") as f:
        return _parse_txt(f)


def write_binary(model, path) -> None:
    """Google word2vec C binary format. Words containing spaces are
    written with '_' in their place (the word2vec phrases convention —
    the space is the field terminator in this format)."""
    cache, m = _resolve(model)
    with open(path, "wb") as f:
        f.write(f"{m.shape[0]} {m.shape[1]}\n".encode())
        for i in range(m.shape[0]):
            word = cache.word_at(i).replace(" ", "_")
            f.write(word.encode("utf-8") + b" ")
            f.write(m[i].astype("<f4").tobytes())
            f.write(b"\n")


def load_binary(path) -> Tuple[VocabCache, np.ndarray]:
    with open(path, "rb") as f:
        header = f.readline().split()
        v, d = int(header[0]), int(header[1])
        cache = VocabCache()
        m = np.zeros((v, d), np.float32)
        for i in range(v):
            word = bytearray()
            while True:
                ch = f.read(1)
                if ch in (b" ", b""):
                    break
                word.extend(ch)
            cache.add(VocabWord(word.decode("utf-8")))
            m[i] = np.frombuffer(f.read(4 * d), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                # older files omit the newline; step back
                f.seek(-1, 1)
    return cache, m


_FULL_MODEL_KEYS = (
    "layer_size", "window", "learning_rate", "min_learning_rate",
    "negative", "sample", "epochs", "iterations", "batch_size",
    "seed", "algorithm",
)


def write_full_model(model, path) -> None:
    """Checkpoint a SequenceVectors/Word2Vec with its FULL training
    state (reference ``WordVectorSerializer.writeFullModel``): both
    weight tables, the Huffman coding, and per-word counts — enough to
    resume ``fit()`` with the alpha schedule and negative-sampling
    distribution intact."""
    import io

    cache = model.cache
    lk = model.lookup
    tables = {"syn0": np.asarray(lk.syn0)}
    if lk.syn1 is not None:
        tables["syn1"] = np.asarray(lk.syn1)
    if lk.syn1neg is not None:
        tables["syn1neg"] = np.asarray(lk.syn1neg)
    if model.use_hs:
        tables["huffman_codes"] = np.asarray(model._codes)
        tables["huffman_points"] = np.asarray(model._points)
        tables["huffman_code_lens"] = np.asarray(model._code_lens)
    conf = {
        "format": "deeplearning4j_tpu.full_word2vec.1",
        "class": type(model).__name__,
        "use_hierarchic_softmax": model.use_hs,
        **{k: getattr(model, k) for k in _FULL_MODEL_KEYS},
    }
    vocab = {
        "total_word_count": cache.total_word_count,
        "words": [[w.word, int(w.count)] for w in cache.words],
    }
    buf = io.BytesIO()
    np.savez(buf, **tables)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("config.json", json.dumps(conf))
        z.writestr("vocab.json", json.dumps(vocab))
        z.writestr("tables.npz", buf.getvalue())


def load_full_model(path, sequences: Optional[list] = None):
    """Restore a full word2vec checkpoint. Returns a ``Word2Vec``
    (or base ``SequenceVectors``) whose next ``fit()`` continues from
    the saved tables; pass ``sequences`` (id arrays) to resume
    training on a corpus (reference ``loadFullModel``)."""
    import io

    import jax.numpy as jnp

    from deeplearning4j_tpu.nlp.word2vec import SequenceVectors, Word2Vec

    with zipfile.ZipFile(path, "r") as z:
        conf = json.loads(z.read("config.json"))
        if not str(conf.get("format", "")).startswith(
            "deeplearning4j_tpu.full_word2vec."
        ):
            raise ValueError(
                f"{path} is not a full word2vec checkpoint"
            )
        vocab = json.loads(z.read("vocab.json"))
        tables = np.load(io.BytesIO(z.read("tables.npz")))
        tables = {k: tables[k] for k in tables.files}
    cache = VocabCache()
    for word, count in vocab["words"]:
        cache.add(VocabWord(word, count))
    cache.total_word_count = vocab["total_word_count"]
    kw = {k: conf[k] for k in _FULL_MODEL_KEYS}
    kw["use_hierarchic_softmax"] = conf["use_hierarchic_softmax"]
    if conf["class"] == "Word2Vec":
        model = Word2Vec(cache, sequences or [], **kw)
    else:
        model = SequenceVectors(cache, **kw)
        if sequences is not None:
            model._seqs = sequences
            model._sequences = lambda: iter(model._seqs)
    lk = model.lookup
    lk.syn0 = jnp.asarray(tables["syn0"])
    if "syn1" in tables:
        lk.syn1 = jnp.asarray(tables["syn1"])
    if "syn1neg" in tables:
        lk.syn1neg = jnp.asarray(tables["syn1neg"])
    if model.use_hs and "huffman_codes" in tables:
        model._codes = tables["huffman_codes"]
        model._points = tables["huffman_points"]
        model._code_lens = tables["huffman_code_lens"]
    lk.invalidate_norms()
    return model


def write_csv(model, path, sep: str = ",") -> None:
    """CSV interop (reference ``WordVectorSerializer`` CSV variant):
    one ``word,v1,...,vD`` row per word, no header. Words containing
    the separator are quoted per csv rules."""
    import csv

    cache, m = _resolve(model)
    with open(path, "w", encoding="utf-8", newline="") as f:
        w = csv.writer(f, delimiter=sep)
        for i in range(m.shape[0]):
            w.writerow([cache.word_at(i)]
                       + [repr(float(x)) for x in m[i]])


def load_csv(path, sep: str = ",") -> Tuple[VocabCache, np.ndarray]:
    """Headerless CSV has no declared dimensionality, so each row is
    validated against the first (the txt/bin loaders get this from
    their header)."""
    import csv

    cache = VocabCache()
    rows = []
    dim = None
    with open(path, "r", encoding="utf-8", newline="") as f:
        for lineno, parts in enumerate(csv.reader(f, delimiter=sep), 1):
            if not parts:
                continue
            vec = parts[1:]
            if dim is None:
                dim = len(vec)
                if dim == 0:
                    raise ValueError(
                        f"{path}:{lineno}: row {parts[0]!r} has no "
                        "vector components"
                    )
            elif len(vec) != dim:
                raise ValueError(
                    f"{path}:{lineno}: row {parts[0]!r} has "
                    f"{len(vec)} components, expected {dim}"
                )
            try:
                row = [float(x) for x in vec]
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno}: non-numeric component in row "
                    f"{parts[0]!r}: {e}"
                ) from None
            cache.add(VocabWord(parts[0]))
            rows.append(row)
    if not rows:
        return cache, np.zeros((0, 0), np.float32)
    return cache, np.asarray(rows, np.float32)


def write_zip(model, path) -> None:
    """Zip-compressed text vectors (reference zip variant:
    ``words.txt`` inside a zip — the compressed interchange format for
    large vocabularies)."""
    import io

    cache, m = _resolve(model)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        buf = io.StringIO()
        buf.write(f"{m.shape[0]} {m.shape[1]}\n")
        for i in range(m.shape[0]):
            vals = " ".join(repr(float(x)) for x in m[i])
            buf.write(f"{cache.word_at(i)} {vals}\n")
        z.writestr("words.txt", buf.getvalue())


def load_zip(path) -> Tuple[VocabCache, np.ndarray]:
    import io

    with zipfile.ZipFile(path, "r") as z:
        data = z.read("words.txt").decode("utf-8")
    return _parse_txt(io.StringIO(data))


def write_word_vectors(model, path) -> None:
    """Dispatch on extension (.bin → binary, .csv → csv, .zip → zip,
    else txt) — reference ``writeWordVectors`` overloads."""
    p = str(path)
    if p.endswith(".bin"):
        write_binary(model, path)
    elif p.endswith(".csv"):
        write_csv(model, path)
    elif p.endswith(".zip"):
        write_zip(model, path)
    else:
        write_txt(model, path)


def read_word_vectors(path) -> Tuple[VocabCache, np.ndarray]:
    p = str(path)
    if p.endswith(".bin"):
        return load_binary(path)
    if p.endswith(".csv"):
        return load_csv(path)
    if p.endswith(".zip"):
        return load_zip(path)
    return load_txt(path)
