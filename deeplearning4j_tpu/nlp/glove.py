"""GloVe embeddings (reference: ``models/glove/Glove.java`` +
``models/glove/AbstractCoOccurrences.java`` — co-occurrence counting
host-side, then weighted-least-squares with per-parameter AdaGrad).

TPU-first: co-occurrence triples (i, j, X_ij) are shuffled and packed
into fixed-shape batches; one jitted step computes
f(X)·(wᵢ·w̃ⱼ + bᵢ + b̃ⱼ − log X)² for the whole batch and applies
AdaGrad via gather/scatter — replacing the reference's per-pair
threaded updates.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


@functools.partial(jax.jit, donate_argnums=(0,))
def _glove_step(state, rows, cols, logx, fx, mask, lr):
    """One AdaGrad batch. state = (W, Wc, b, bc, hW, hWc, hb, hbc)."""
    W, Wc, b, bc, hW, hWc, hb, hbc = state

    def loss_fn(p):
        W_, Wc_, b_, bc_ = p
        wi = W_[rows]
        wj = Wc_[cols]
        diff = jnp.sum(wi * wj, axis=-1) + b_[rows] + bc_[cols] - logx
        return jnp.sum(mask * fx * diff * diff), diff

    (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        (W, Wc, b, bc)
    )
    gW, gWc, gb, gbc = grads
    hW = hW + gW * gW
    hWc = hWc + gWc * gWc
    hb = hb + gb * gb
    hbc = hbc + gbc * gbc
    eps = 1e-8
    W = W - lr * gW / jnp.sqrt(hW + eps)
    Wc = Wc - lr * gWc / jnp.sqrt(hWc + eps)
    b = b - lr * gb / jnp.sqrt(hb + eps)
    bc = bc - lr * gbc / jnp.sqrt(hbc + eps)
    return (W, Wc, b, bc, hW, hWc, hb, hbc), loss


class CoOccurrences:
    """Symmetric windowed co-occurrence counts with 1/distance
    weighting (reference ``AbstractCoOccurrences``)."""

    def __init__(self, cache: VocabCache, window: int = 5,
                 symmetric: bool = True):
        self.cache = cache
        self.window = window
        self.symmetric = symmetric
        self._counts: dict = defaultdict(float)

    def fit(self, id_sequences: Iterable[np.ndarray]) -> None:
        """Vectorized: for each offset d, pair ids[:-d] with ids[d:] in
        one slice, accumulate 1/d weights keyed by flat (i*V + j) via
        np.add.at-free bincount (unique+aggregate) — no per-token
        Python loop."""
        V = len(self.cache)
        w = self.window
        flush_at = 1 << 20  # bound peak memory to ~8MB of keys per flush
        keys_parts, vals_parts, pending = [], [], 0

        def flush():
            nonlocal keys_parts, vals_parts, pending
            if not keys_parts:
                return
            keys = np.concatenate(keys_parts)
            vals = np.concatenate(vals_parts)
            uniq, inv = np.unique(keys, return_inverse=True)
            sums = np.bincount(inv, weights=vals, minlength=len(uniq))
            for k, x in zip(uniq, sums):
                self._counts[(int(k) // V, int(k) % V)] += float(x)
            keys_parts, vals_parts, pending = [], [], 0

        for ids in id_sequences:
            ids = np.asarray(ids, np.int64)
            n = len(ids)
            for off in range(1, min(w, n - 1) + 1):
                a, b = ids[:-off], ids[off:]
                wt = np.full(len(a), 1.0 / off)
                keys_parts.append(a * V + b)
                vals_parts.append(wt)
                pending += len(a)
                if self.symmetric:
                    keys_parts.append(b * V + a)
                    vals_parts.append(wt)
                    pending += len(a)
                if pending >= flush_at:
                    flush()
        flush()

    def triples(self):
        n = len(self._counts)
        rows = np.empty(n, np.int32)
        cols = np.empty(n, np.int32)
        vals = np.empty(n, np.float32)
        for k, ((i, j), x) in enumerate(self._counts.items()):
            rows[k] = i
            cols[k] = j
            vals[k] = x
        return rows, cols, vals


class Glove:
    """GloVe trainer (reference ``Glove.java`` builder API)."""

    def __init__(self, cache: VocabCache, id_sequences: List[np.ndarray], *,
                 layer_size=100, window=5, learning_rate=0.05,
                 x_max=100.0, alpha=0.75, epochs=25, batch_size=1024,
                 seed=12345, symmetric=True):
        self.cache = cache
        self.layer_size = layer_size
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.co = CoOccurrences(cache, window=window, symmetric=symmetric)
        self.co.fit(id_sequences)
        v = len(cache)
        rng = np.random.RandomState(seed)
        init = lambda *s: jnp.asarray(
            (rng.rand(*s) - 0.5) / layer_size, jnp.float32
        )
        self._state = (
            init(v, layer_size), init(v, layer_size), init(v), init(v),
            jnp.zeros((v, layer_size), jnp.float32),
            jnp.zeros((v, layer_size), jnp.float32),
            jnp.zeros(v, jnp.float32), jnp.zeros(v, jnp.float32),
        )
        self.syn0: Optional[np.ndarray] = None
        self._normalized: Optional[np.ndarray] = None
        self.last_loss = float("nan")

    def _put(self, a):
        """Batch-array placement hook — ClusterGlove overrides this to
        shard over the mesh 'data' axis."""
        return jnp.asarray(a)

    def fit(self) -> "Glove":
        rows, cols, vals = self.co.triples()
        if len(rows) == 0:
            raise ValueError("Empty co-occurrence matrix")
        logx = np.log(vals).astype(np.float32)
        fx = np.minimum((vals / self.x_max) ** self.alpha, 1.0).astype(
            np.float32
        )
        B = self.batch_size
        rng = np.random.RandomState(self.seed)
        lr = jnp.float32(self.learning_rate)
        for _ in range(self.epochs):
            perm = rng.permutation(len(rows))
            epoch_losses = []
            for s in range(0, len(rows), B):
                sl = perm[s:s + B]
                mask = np.ones(B, np.float32)
                rb, cb = rows[sl], cols[sl]
                lb, fb = logx[sl], fx[sl]
                if len(sl) < B:
                    pad = B - len(sl)
                    mask[len(sl):] = 0.0
                    rb = np.pad(rb, (0, pad))
                    cb = np.pad(cb, (0, pad))
                    lb = np.pad(lb, (0, pad))
                    fb = np.pad(fb, (0, pad))
                self._state, loss = _glove_step(
                    self._state,
                    self._put(rb), self._put(cb),
                    self._put(lb), self._put(fb),
                    self._put(mask), lr,
                )
                epoch_losses.append(loss)  # device scalar; no sync
            self.last_loss = float(
                jnp.sum(jnp.stack(epoch_losses))
            ) / max(len(rows), 1)
        # final vectors: W + Wc (standard GloVe practice)
        self.syn0 = np.asarray(self._state[0]) + np.asarray(self._state[1])
        self._normalized = None
        return self

    # -- query (same surface as SequenceVectors) ----------------------------

    def _norm(self) -> np.ndarray:
        if self.syn0 is None:
            raise ValueError("Call fit() first")
        if self._normalized is None:
            n = np.linalg.norm(self.syn0, axis=1, keepdims=True)
            self._normalized = self.syn0 / np.maximum(n, 1e-12)
        return self._normalized

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.cache.index_of(word)
        return None if i < 0 else self.syn0[i]

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.cache.index_of(a), self.cache.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        m = self._norm()
        return float(m[ia] @ m[ib])

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        i = self.cache.index_of(word)
        if i < 0:
            return []
        m = self._norm()
        sims = m @ m[i]
        sims[i] = -np.inf
        return [
            self.cache.word_at(int(t)) for t in np.argsort(-sims)[:n]
        ]

    class Builder:
        def __init__(self):
            self._kw = {}
            self._min_word_frequency = 1
            self._iterator = None
            self._tokenizer = None

        def min_word_frequency(self, n):
            self._min_word_frequency = n; return self

        def layer_size(self, n): self._kw["layer_size"] = n; return self
        def window_size(self, n): self._kw["window"] = n; return self
        def learning_rate(self, x): self._kw["learning_rate"] = x; return self
        def x_max(self, x): self._kw["x_max"] = x; return self
        def alpha(self, x): self._kw["alpha"] = x; return self
        def epochs(self, n): self._kw["epochs"] = n; return self
        def batch_size(self, n): self._kw["batch_size"] = n; return self
        def seed(self, n): self._kw["seed"] = n; return self
        def symmetric(self, b): self._kw["symmetric"] = b; return self
        def iterate(self, it): self._iterator = it; return self
        def tokenizer_factory(self, tf): self._tokenizer = tf; return self

        def build(self) -> "Glove":
            if self._iterator is None:
                raise ValueError("iterate(sentence_iterator) is required")
            tf = self._tokenizer or DefaultTokenizerFactory()
            sentences = [tf.create(s).get_tokens() for s in self._iterator]
            cache = VocabConstructor(
                min_word_frequency=self._min_word_frequency
            ).build_vocab_from_tokens(sentences)
            ids = [
                np.asarray(cache.id_stream(t), np.int64) for t in sentences
            ]
            return Glove(cache, ids, **self._kw)
