"""Lattice-based Japanese morphological analysis (reference:
``deeplearning4j-nlp-japanese`` vendors the Kuromoji analyzer —
``com/atilika/kuromoji/TokenizerBase.java:1``; the search is a
dictionary lattice + Viterbi minimum-cost path where
``path_cost = prev_path_cost + connection_cost(prev.rightId,
node.leftId) + word_cost`` — ``viterbi/ViterbiSearcher.java:101`` —
and tokens expose part-of-speech / base-form attributes,
``TokenBase.java``).

Same scheme here, dependency-free and at mini scale:

- a checked-in lexicon where each surface maps to one or more
  ``(word_cost, pos_class, pos, detail, base_form)`` entries
  (ambiguous surfaces like も/か carry their class so the transition
  matrix can disambiguate in context);
- unknown spans covered by script-class runs (Kuromoji's unknown-word
  handler groups the same way), classed by script (katakana run ->
  noun-loanword, digits -> number, kanji -> unknown-noun);
- a **bigram connection-cost matrix over POS classes** — the compact
  analog of Kuromoji's learned (rightId, leftId) matrix. This is what
  resolves the classic ambiguities a unigram lattice gets wrong:
  particle-particle transitions are penalized, noun->particle and
  verb-stem->auxiliary are rewarded, so すもももももももものうち
  segments to the canonical すもも/も/もも/も/もも/の/うち;
- Viterbi over lattice *nodes* (cost depends on the previous node's
  class, so position-only DP is not enough).

The deliberate divergence from the reference is scale, not shape: the
lexicon is a few hundred entries and the matrix is ~15x15 hand-set
classes instead of IPADIC's learned 1316x1316 — a real deployment
loads a full dictionary through the same entry format.

Registered as ``tokenizer_factory("japanese")``; the zero-dependency
script-run segmenter stays available as ``"japanese_script"``.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

from deeplearning4j_tpu.nlp.cjk import _script_class, segment_by_script
from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer,
    register_tokenizer_factory,
)

# ---------------------------------------------------------------------------
# POS classes (connection ids). Kuromoji: left/right context ids from
# IPADIC; here one compact class per broad POS, used on both sides.
# ---------------------------------------------------------------------------

BOS = 0    # virtual begin-of-sentence
EOS = 1    # virtual end-of-sentence
N = 2      # noun
PRON = 3   # pronoun / demonstrative
PRT = 4    # case/binding particle (は が を に で と の へ から まで も)
PRT_F = 5  # sentence-final particle (ね よ か)
V = 6      # verb, terminal/past/te form (行く 行った して)
VSTEM = 7  # verb continuative stem (行き 食べ) — wants an auxiliary
AUX = 8    # auxiliary / copula / polite endings (です ます ない)
ADJ = 9    # i-adjective
NUM = 10   # number run
SYM = 11   # symbol / punctuation
UNK = 12   # unknown span (non-katakana)
ADV = 13   # adverb (とても もう ゆっくり) — r5, scaled lexicon

_CLASS_NAMES = {
    BOS: "BOS", EOS: "EOS", N: "noun", PRON: "pronoun",
    PRT: "particle", PRT_F: "particle", V: "verb", VSTEM: "verb",
    AUX: "auxiliary", ADJ: "adjective", NUM: "number", SYM: "symbol",
    UNK: "unknown", ADV: "adverb",
}

# loader names -> class ids (the TSV lexicon and user dictionaries
# name classes; VSTEM/PRT_F disambiguate via the detail column)
_NAME_TO_CLASS = {
    "noun": N, "pronoun": PRON, "particle": PRT,
    "final-particle": PRT_F, "verb": V, "verb-stem": VSTEM,
    "auxiliary": AUX, "adjective": ADJ, "adverb": ADV,
    "number": NUM, "symbol": SYM,
}

# Bigram connection costs (left_class, right_class) -> cost, the
# compact analog of Kuromoji's ConnectionCosts matrix
# (``viterbi/ViterbiSearcher.java:101`` adds costs.get(rightId,
# leftId) on every edge). Unlisted pairs cost _CONN_DEFAULT. Negative
# = rewarded transition. Hand-set to encode the grammar facts IPADIC
# learned from corpora: particles follow nominals, auxiliaries follow
# verb stems, particle chains and particle-initial sentences are
# implausible.
_CONN_DEFAULT = 200
_CONN: Dict[Tuple[int, int], int] = {
    (BOS, N): 0, (BOS, PRON): 0, (BOS, NUM): 0, (BOS, V): 50,
    (BOS, VSTEM): 50, (BOS, ADJ): 50, (BOS, UNK): 100, (BOS, SYM): 100,
    (BOS, PRT): 800, (BOS, PRT_F): 800, (BOS, AUX): 800,

    (N, PRT): -150, (N, PRT_F): 0, (N, AUX): -100, (N, EOS): 0,
    (N, N): 150, (N, V): 50, (N, VSTEM): 50,
    (PRON, PRT): -150, (PRON, AUX): -50, (PRON, EOS): 50,

    (PRT, N): -50, (PRT, PRON): 0, (PRT, V): -50, (PRT, VSTEM): -50,
    (PRT, ADJ): -50, (PRT, NUM): 0, (PRT, UNK): 0,
    (PRT, PRT): 700, (PRT, PRT_F): 500, (PRT, AUX): 400,
    (PRT, EOS): 500,
    (PRT_F, EOS): -100, (PRT_F, PRT_F): 100,

    (V, EOS): -100, (V, PRT): 150, (V, PRT_F): -50, (V, N): 100,
    (V, AUX): 100,
    (VSTEM, AUX): -300, (VSTEM, EOS): 800, (VSTEM, PRT): 300,
    (VSTEM, V): 400, (VSTEM, N): 400,

    (AUX, EOS): -150, (AUX, PRT_F): -50, (AUX, AUX): 0,
    (AUX, PRT): 300, (AUX, N): 300,

    (ADJ, N): -50, (ADJ, EOS): -50, (ADJ, AUX): 0, (ADJ, PRT): 100,
    (NUM, N): -100, (NUM, PRT): -50, (NUM, EOS): 0,
    (UNK, PRT): -50, (UNK, AUX): 0, (UNK, EOS): 100,

    # adverbs: sentence-initial or mid-clause, preceding predicates
    (BOS, ADV): 20, (ADV, V): -100, (ADV, VSTEM): -100,
    (ADV, ADJ): -100, (ADV, ADV): 150, (ADV, N): 150,
    (ADV, EOS): 300, (PRT, ADV): 0, (ADV, PRT): 250,
}


def connection_cost(left_class: int, right_class: int) -> int:
    return _CONN.get((left_class, right_class), _CONN_DEFAULT)


# ---------------------------------------------------------------------------
# Lexicon: surface -> [(word_cost, class, pos, detail, base_form)].
# Ambiguous surfaces carry multiple entries; the connection matrix
# picks in context. A real deployment swaps this for a full
# IPADIC-style lexicon through the same format.
# ---------------------------------------------------------------------------

Entry = Tuple[int, int, str, str, Optional[str]]


def _e(cost: int, cls: int, detail: str = "",
       base: Optional[str] = None) -> Entry:
    return (cost, cls, _CLASS_NAMES[cls], detail, base)


LEXICON: Dict[str, List[Entry]] = {
    # case/binding particles
    "は": [_e(100, PRT, "binding")], "が": [_e(100, PRT, "case")],
    "を": [_e(100, PRT, "case")], "に": [_e(100, PRT, "case")],
    "で": [_e(110, PRT, "case")], "と": [_e(110, PRT, "case")],
    "も": [_e(100, PRT, "binding")], "の": [_e(100, PRT, "genitive")],
    "へ": [_e(120, PRT, "case")], "や": [_e(130, PRT, "parallel")],
    "から": [_e(120, PRT, "case")], "まで": [_e(120, PRT, "case")],
    "より": [_e(130, PRT, "case")],
    # sentence-final particles (か doubles as question marker)
    "ね": [_e(140, PRT_F, "final")], "よ": [_e(140, PRT_F, "final")],
    "か": [_e(130, PRT_F, "final"), _e(160, PRT, "parallel")],
    # copula / auxiliaries / polite endings
    "です": [_e(150, AUX, "copula", "です")],
    "でした": [_e(160, AUX, "copula-past", "です")],
    "ます": [_e(150, AUX, "polite", "ます")],
    "ました": [_e(160, AUX, "polite-past", "ます")],
    "ません": [_e(160, AUX, "polite-negative", "ます")],
    "だ": [_e(160, AUX, "copula", "だ")],
    "である": [_e(170, AUX, "copula", "だ")],
    "ない": [_e(170, AUX, "negative", "ない")],
    "たい": [_e(180, AUX, "desiderative", "たい")],
    "れる": [_e(180, AUX, "passive", "れる")],
    "られる": [_e(190, AUX, "passive", "られる")],
    "ください": [_e(180, AUX, "request", "くださる")],
    # verbs — terminal/past/te forms
    "する": [_e(170, V, "suru", "する")],
    "します": [_e(160, V, "suru-polite", "する")],
    "しました": [_e(170, V, "suru-polite-past", "する")],
    "した": [_e(170, V, "suru-past", "する")],
    "して": [_e(170, V, "suru-te", "する")],
    "います": [_e(170, V, "subsidiary", "いる")],
    "いる": [_e(170, V, "subsidiary", "いる")],
    "ある": [_e(170, V, "existence", "ある")],
    "なる": [_e(180, V, "", "なる")],
    "行く": [_e(260, V, "", "行く")], "行った": [_e(270, V, "past", "行く")],
    "来る": [_e(260, V, "", "来る")], "来た": [_e(270, V, "past", "来る")],
    "くる": [_e(260, V, "", "くる")],
    "見る": [_e(260, V, "", "見る")], "見た": [_e(270, V, "past", "見る")],
    "食べる": [_e(270, V, "", "食べる")],
    "読む": [_e(270, V, "", "読む")], "書く": [_e(270, V, "", "書く")],
    "話す": [_e(270, V, "", "話す")], "思う": [_e(270, V, "", "思う")],
    "使う": [_e(270, V, "", "使う")], "待つ": [_e(270, V, "", "待つ")],
    "まつ": [_e(280, V, "", "まつ")],
    # verb continuative stems (expect auxiliaries)
    "行き": [_e(260, VSTEM, "stem", "行く")],
    "食べ": [_e(270, VSTEM, "stem", "食べる")],
    "読み": [_e(270, VSTEM, "stem", "読む")],
    "書き": [_e(270, VSTEM, "stem", "書く")],
    "思い": [_e(270, VSTEM, "stem", "思う")],
    "使い": [_e(270, VSTEM, "stem", "使う")],
    # pronouns / demonstratives
    "私": [_e(200, PRON)], "僕": [_e(210, PRON)], "彼": [_e(210, PRON)],
    "彼女": [_e(220, PRON)], "これ": [_e(200, PRON)],
    "それ": [_e(200, PRON)], "あれ": [_e(210, PRON)],
    "ここ": [_e(210, PRON)], "そこ": [_e(210, PRON)],
    "どこ": [_e(210, PRON)],
    # i-adjectives
    "良い": [_e(270, ADJ, "", "良い")], "いい": [_e(260, ADJ, "", "いい")],
    "大きい": [_e(280, ADJ, "", "大きい")],
    "小さい": [_e(280, ADJ, "", "小さい")],
    "新しい": [_e(280, ADJ, "", "新しい")],
    "高い": [_e(280, ADJ, "", "高い")], "速い": [_e(280, ADJ, "", "速い")],
    # common nouns
    "こと": [_e(200, N)], "もの": [_e(260, N)], "とき": [_e(210, N)],
    "ところ": [_e(220, N)], "人": [_e(220, N)], "日": [_e(230, N)],
    "年": [_e(230, N)], "月": [_e(230, N)], "時間": [_e(240, N)],
    "今日": [_e(230, N)], "明日": [_e(240, N)], "昨日": [_e(240, N)],
    "学生": [_e(250, N)], "先生": [_e(250, N)], "学校": [_e(250, N)],
    "大学": [_e(250, N)], "東京": [_e(250, N, "proper")],
    "日本": [_e(240, N, "proper")], "日本語": [_e(250, N, "proper")],
    "言語": [_e(260, N)], "単語": [_e(260, N)], "文章": [_e(260, N)],
    "意味": [_e(260, N)], "世界": [_e(260, N)], "会社": [_e(260, N)],
    "仕事": [_e(260, N)], "勉強": [_e(260, N, "verbal")],
    "電車": [_e(270, N)], "車": [_e(260, N)], "くるま": [_e(280, N)],
    "家": [_e(250, N)], "水": [_e(260, N)], "本": [_e(250, N)],
    "犬": [_e(260, N)], "猫": [_e(260, N)], "うち": [_e(230, N)],
    "すもも": [_e(300, N)], "もも": [_e(280, N)], "桃": [_e(270, N)],
    "李": [_e(290, N)],
}


class JapaneseDictionary:
    """Compiled lexicon with a per-first-character prefix index — the
    compact analog of the trie Kuromoji compiles IPADIC into
    (``com/atilika/kuromoji/trie/PatriciaTrie.java:1``,
    ``dict/TokenInfoDictionary``): at each lattice position only the
    lengths up to the longest dictionary word starting with that
    character are probed, so lookup cost scales with per-character
    fan-out instead of the global longest surface.

    Sources, merged in order (later entries append, same format):
    the hand-set core ``LEXICON``, the generated TSV shipped in
    ``nlp/data/ja_lexicon.tsv`` (scripts/gen_ja_lexicon.py — base
    vocabulary expanded through godan/ichidan/i-adjective
    conjugation), and user dictionaries via :meth:`add_word` /
    :meth:`load_tsv` (Kuromoji's user-dictionary seam)."""

    def __init__(self, entries: Optional[Dict[str, List[Entry]]] = None):
        self._entries: Dict[str, List[Entry]] = {}
        self._max_by_first: Dict[str, int] = {}
        if entries:
            for surface, es in entries.items():
                for e in es:
                    self._add(surface, e)

    def _add(self, surface: str, entry: Entry) -> None:
        if not surface:
            raise ValueError("empty surface")
        lst = self._entries.setdefault(surface, [])
        if entry not in lst:
            lst.append(entry)
        c = surface[0]
        if len(surface) > self._max_by_first.get(c, 0):
            self._max_by_first[c] = len(surface)

    def add_word(self, surface: str, pos: str = "noun",
                 cost: int = 250, detail: str = "user",
                 base: Optional[str] = None) -> None:
        """User-dictionary seam: register one surface with a named
        POS class (kuromoji UserDictionary analog)."""
        cls = _NAME_TO_CLASS.get(pos)
        if cls is None:
            raise ValueError(
                f"unknown POS class {pos!r}; one of "
                f"{sorted(_NAME_TO_CLASS)}"
            )
        self._add(surface, (cost, cls, _CLASS_NAMES[cls], detail,
                            base))

    def load_tsv(self, path) -> int:
        """Load ``surface<TAB>cost<TAB>class<TAB>detail<TAB>base``
        rows (the generated-lexicon / user-dictionary format);
        returns the number of entries added."""
        n = 0
        with open(path, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t")
                if len(parts) != 5:
                    raise ValueError(
                        f"{path}:{lineno}: expected 5 tab-separated "
                        f"fields, got {len(parts)}"
                    )
                surface, cost, cls_name, detail, base = parts
                cls = _NAME_TO_CLASS.get(cls_name)
                if cls is None:
                    raise ValueError(
                        f"{path}:{lineno}: unknown class {cls_name!r}"
                    )
                self._add(surface, (int(cost), cls,
                                    _CLASS_NAMES[cls], detail,
                                    base or None))
                n += 1
        return n

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, surface: str) -> bool:
        return surface in self._entries

    def max_surface_len(self, first_char: str) -> int:
        return self._max_by_first.get(first_char, 0)

    def lookup(self, surface: str):
        return self._entries.get(surface, ())

    def prefixes(self, text: str, i: int):
        """Yield (surface, entries) for every dictionary surface
        starting at ``text[i]`` — the lattice construction probe."""
        mx = min(self._max_by_first.get(text[i], 0), len(text) - i)
        for ln in range(1, mx + 1):
            w = text[i:i + ln]
            es = self._entries.get(w)
            if es:
                yield w, es


_DEFAULT_DICT: Optional[JapaneseDictionary] = None


def default_dictionary() -> JapaneseDictionary:
    """The process-wide dictionary: core LEXICON + the shipped
    generated lexicon (loaded once, lazily)."""
    global _DEFAULT_DICT
    if _DEFAULT_DICT is None:
        d = JapaneseDictionary(LEXICON)
        import os

        tsv = os.path.join(os.path.dirname(__file__), "data",
                           "ja_lexicon.tsv")
        if os.path.exists(tsv):
            d.load_tsv(tsv)
        _DEFAULT_DICT = d
    return _DEFAULT_DICT


# Unknown-span costs by script class (Kuromoji's unknown-word handler
# assigns per-category costs from unk.def; same idea, coarser).
# Katakana runs are almost always loanword nouns -> cheap; hiragana is
# function-word territory -> long unknown runs are implausible.
_UNK_BASE = 700
_UNK_PER_CHAR = {
    "katakana": 200, "other": 200, "digit": 150,
    "kanji": 350, "hiragana": 500, "hangul": 250,
}
_UNK_PER_CHAR_DEFAULT = 350


class Token(NamedTuple):
    """Analyzed token (reference ``TokenBase.java``: surface, POS
    levels, base form, known/unknown)."""

    surface: str
    part_of_speech: str          # coarse label: noun/particle/verb/...
    pos_detail: str              # sub-class ("case", "stem", ...)
    base_form: str               # dictionary form (= surface if n/a)
    known: bool                  # True if from the lexicon

    @property
    def pos(self) -> str:
        return self.part_of_speech


class _Node(NamedTuple):
    start: int
    end: int
    surface: str
    word_cost: int
    cls: int
    pos: str
    detail: str
    base: Optional[str]
    known: bool


def _script_runs(text: str) -> List[Tuple[int, str]]:
    """Per-position (run_end, script_class), computed once in O(n)
    (Kuromoji's unknown-word grouping). Positions inside a run share
    its end, so lattice construction never rescans."""
    n = len(text)
    out: List[Tuple[int, str]] = [None] * n  # type: ignore[list-item]
    i = 0
    while i < n:
        c = _script_class(text[i])
        j = i + 1
        while j < n and _script_class(text[j]) == c:
            j += 1
        for k in range(i, j):
            out[k] = (j, c)
        i = j
    return out


def _unknown_node(i: int, end: int, script: str) -> _Node:
    """Unknown-span node. ``surface`` stays empty until the node wins
    a place on the Viterbi path (avoids O(n^2) substring copies on
    long single-script runs)."""
    per = _UNK_PER_CHAR.get(script, _UNK_PER_CHAR_DEFAULT)
    cost = _UNK_BASE + per * (end - i)
    if script in ("katakana", "other"):  # loanwords / latin words
        cls, pos, detail = N, "noun", f"unknown-{script}"
    elif script == "digit":
        cls, pos, detail = NUM, "number", "unknown-digit"
    elif script == "punct":
        cls, pos, detail = SYM, "symbol", "punct"
    else:
        cls, pos, detail = UNK, "unknown", f"unknown-{script}"
    return _Node(i, end, "", cost, cls, pos, detail, None, False)


def tokenize(text: str,
             dictionary: Optional[JapaneseDictionary] = None
             ) -> List[Token]:
    """Morphological analysis: Viterbi minimum-cost path over the
    dictionary lattice with bigram connection costs. Whitespace splits
    the lattice; punctuation tokens are dropped (the script-run
    segmenter's convention). ``dictionary`` defaults to the core +
    generated lexicon; pass your own (e.g. with user entries) to
    extend it."""
    d = dictionary if dictionary is not None else default_dictionary()
    out: List[Token] = []
    for chunk in text.split():
        out.extend(_tokenize_chunk(chunk, d))
    return [t for t in out if t.part_of_speech != "symbol"]


def segment(text: str) -> List[str]:
    """Surfaces of :func:`tokenize` (back-compat API)."""
    return [t.surface for t in tokenize(text)]


def _lattice_nodes(text: str,
                   d: JapaneseDictionary) -> List[List[_Node]]:
    """starts[i] = lattice nodes beginning at position i: all
    dictionary matches (probed through the prefix index), plus the
    unknown same-script run AND its single first character (so a
    dictionary word just past i+1 is reachable without consuming the
    whole run)."""
    n = len(text)
    runs = _script_runs(text)
    starts: List[List[_Node]] = [[] for _ in range(n)]
    for i in range(n):
        for w, entries in d.prefixes(text, i):
            for (cost, cls, pos, detail, base) in entries:
                starts[i].append(
                    _Node(i, i + len(w), w, cost, cls, pos, detail,
                          base, True)
                )
        run_end, script = runs[i]
        starts[i].append(_unknown_node(i, run_end, script))
        if run_end - i > 1:
            starts[i].append(_unknown_node(i, i + 1, script))
    return starts


def _tokenize_chunk(text: str, d: JapaneseDictionary) -> List[Token]:
    n = len(text)
    if n == 0:
        return []
    starts = _lattice_nodes(text, d)
    # Viterbi over nodes (cost depends on the previous node's class,
    # so position-only DP is not enough): `arena` is the flat list of
    # settled (node, best_cost, backpointer-index) entries and
    # arena_at[i] indexes the entries whose node ends at i.
    bos = _Node(0, 0, "", 0, BOS, "BOS", "", None, True)
    INF = float("inf")
    arena: List[Tuple[_Node, float, Optional[int]]] = [(bos, 0.0, None)]
    arena_at: List[List[int]] = [[] for _ in range(n + 1)]
    arena_at[0].append(0)
    for i in range(n):
        if not arena_at[i]:
            continue
        for node in starts[i]:
            best_cost, best_back = INF, None
            for ai in arena_at[i]:
                left, lcost, _ = arena[ai]
                c = (lcost + connection_cost(left.cls, node.cls)
                     + node.word_cost)
                if c < best_cost:
                    best_cost, best_back = c, ai
            if best_back is None:
                continue
            arena.append((node, best_cost, best_back))
            arena_at[node.end].append(len(arena) - 1)
    # EOS: pick the end-node with the best cost + connection to EOS
    best_cost, best_ai = INF, None
    for ai in arena_at[n]:
        node, cost, _ = arena[ai]
        c = cost + connection_cost(node.cls, EOS)
        if c < best_cost:
            best_cost, best_ai = c, ai
    if best_ai is None:  # only possible on empty/degenerate input
        return [
            Token(s, "unknown", "", s, False)
            for s in segment_by_script(text)
        ]
    path: List[_Node] = []
    ai: Optional[int] = best_ai
    while ai is not None:
        node, _, back = arena[ai]
        if node.cls != BOS:
            path.append(node)
        ai = back
    path.reverse()
    out = []
    for nd in path:
        surface = nd.surface or text[nd.start:nd.end]
        out.append(
            Token(surface, nd.pos, nd.detail, nd.base or surface,
                  nd.known)
        )
    return out


class JapaneseDictTokenizerFactory:
    """Kuromoji-analog TokenizerFactory: dictionary lattice + Viterbi
    with bigram connection costs; unknown spans grouped by script
    class. ``preprocessor`` follows the reference's TokenPreProcess
    seam. ``create`` yields surfaces (the Tokenizer SPI);
    ``tokenize`` yields POS-tagged :class:`Token`s (the reference's
    JapaneseTokenizer returns Kuromoji Tokens the same way)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(segment(text), self.preprocessor)

    def tokenize(self, text: str) -> List[Token]:
        return tokenize(text)


# dictionary lattice becomes the default "japanese" tokenizer; the
# zero-dependency script-run fallback stays registered under an
# explicit name
register_tokenizer_factory("japanese", JapaneseDictTokenizerFactory)
