"""Dictionary-based Japanese segmentation (reference:
``deeplearning4j-nlp-japanese`` vendors the Kuromoji morphological
analyzer — ``com/atilika/kuromoji/TokenizerBase.java:1``, a
dictionary lattice + Viterbi minimum-cost path over connection costs).

This is the same algorithmic scheme at mini scale, dependency-free:
a checked-in lexicon (common particles, auxiliaries, verb forms, and
frequent content words) is matched into a lattice over every text
position, unknown spans are covered by script-class runs (the
Kuromoji unknown-word handler does the same grouping), and a Viterbi
pass picks the minimum-cost segmentation. Costs are unigram
(length-discounted dictionary costs vs a per-character unknown
penalty) rather than Kuromoji's learned connection matrix — the
honest divergence, documented here and in the README.

Registered as ``tokenizer_factory("japanese")``; the zero-dependency
script-run segmenter stays available as ``"japanese_script"``.
"""

from __future__ import annotations

from typing import Dict, List

from deeplearning4j_tpu.nlp.cjk import _script_class, segment_by_script
from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer,
    register_tokenizer_factory,
)

# Mini-lexicon: surface -> cost (lower = preferred). Particles and
# auxiliaries are cheap (they are near-certain when they match);
# content words cost more than function words but much less than
# unknown spans. A real deployment swaps this dict for a full
# IPADIC-style lexicon through the same factory.
LEXICON: Dict[str, int] = {
    # particles
    "は": 100, "が": 100, "を": 100, "に": 100, "で": 110, "と": 110,
    # も costs more than half of もも so the lattice prefers the noun
    # over a particle chain (the unigram stand-in for Kuromoji's
    # connection costs, which penalize particle-particle transitions)
    "も": 150, "の": 100, "へ": 120, "や": 130, "から": 120,
    "まで": 120, "より": 130, "ね": 140, "よ": 140, "か": 130,
    # copula / auxiliaries / common verb endings
    "です": 150, "でした": 160, "ます": 150, "ました": 160,
    "ません": 160, "だ": 160, "である": 170, "する": 170,
    "します": 160, "しました": 170,
    "した": 170, "して": 170, "います": 170, "いる": 170,
    "ある": 170, "なる": 180, "れる": 180, "られる": 190,
    "ない": 170, "たい": 180, "ください": 180,
    # pronouns / demonstratives
    "私": 200, "僕": 210, "彼": 210, "彼女": 220, "これ": 200,
    "それ": 200, "あれ": 210, "ここ": 210, "そこ": 210, "どこ": 210,
    # common nouns
    "こと": 200, "もの": 260, "とき": 210, "ところ": 220, "人": 220,
    "日": 230, "年": 230, "月": 230, "時間": 240, "今日": 230,
    "明日": 240, "昨日": 240, "学生": 250, "先生": 250, "学校": 250,
    "大学": 250, "東京": 250, "日本": 240, "日本語": 250, "言語": 260,
    "単語": 260, "文章": 260, "意味": 260, "世界": 260, "会社": 260,
    "仕事": 260, "電車": 270, "車": 260, "家": 250, "水": 260,
    "本": 250, "犬": 260, "猫": 260, "うち": 230, "すもも": 300,
    "もも": 280, "桃": 270, "李": 290,
    # common verbs/adjectives (stems + frequent conjugations)
    "行き": 260, "行く": 260, "行った": 270, "来る": 260, "来た": 270,
    "見る": 260, "見た": 270, "食べ": 270, "食べる": 270,
    "読む": 270, "読み": 270, "書く": 270, "書き": 270, "話す": 270,
    "思い": 270, "思う": 270, "使う": 270, "使い": 270,
    "良い": 270, "いい": 260, "大きい": 280, "小さい": 280,
    "新しい": 280, "高い": 280,
}

_MAX_LEN = max(len(w) for w in LEXICON)
_UNK_BASE = 700       # flat penalty for opening an unknown span
_UNK_PER_CHAR = 350   # per-character unknown cost: two dictionary
#                       words always beat one unknown covering both


def _unknown_run_len(text: str, i: int) -> int:
    """Length of the same-script run starting at i (Kuromoji's
    unknown-word grouping)."""
    c = _script_class(text[i])
    j = i + 1
    while j < len(text) and _script_class(text[j]) == c:
        j += 1
    return j - i


def segment(text: str) -> List[str]:
    """Minimum-cost segmentation of ``text`` (Viterbi over the match
    lattice). Whitespace splits the lattice; punctuation tokens are
    dropped (matching the script-run segmenter's convention)."""
    out: List[str] = []
    for chunk in text.split():
        out.extend(_segment_chunk(chunk))
    return [
        t for t in out
        if t and not all(_script_class(c) == "punct" for c in t)
    ]


def _segment_chunk(text: str) -> List[str]:
    n = len(text)
    if n == 0:
        return []
    INF = float("inf")
    best = [INF] * (n + 1)
    back = [0] * (n + 1)
    best[0] = 0.0
    for i in range(n):
        if best[i] is INF:
            continue
        # dictionary edges
        for ln in range(1, min(_MAX_LEN, n - i) + 1):
            w = text[i:i + ln]
            cost = LEXICON.get(w)
            if cost is None:
                continue
            c = best[i] + cost
            if c < best[i + ln]:
                best[i + ln] = c
                back[i + ln] = i
        # unknown edges: the full same-script run AND its single first
        # character (so a dictionary word just past position i+1 is
        # reachable without consuming the whole run)
        run = _unknown_run_len(text, i)
        for ln in {run, 1}:
            c = best[i] + _UNK_BASE + _UNK_PER_CHAR * ln
            if c < best[i + ln]:
                best[i + ln] = c
                back[i + ln] = i
    if best[n] is INF:  # unreachable only if text is empty; guard
        return segment_by_script(text)
    cuts = []
    j = n
    while j > 0:
        cuts.append(j)
        j = back[j]
    cuts.append(0)
    cuts.reverse()
    return [text[a:b] for a, b in zip(cuts, cuts[1:])]


class JapaneseDictTokenizerFactory:
    """Kuromoji-analog TokenizerFactory: lattice + Viterbi over the
    checked-in mini-lexicon, unknown spans grouped by script class.
    ``preprocessor`` follows the reference's TokenPreProcess seam."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(segment(text), self.preprocessor)


# dictionary segmentation becomes the default "japanese" tokenizer;
# the zero-dependency script-run fallback stays registered under an
# explicit name
register_tokenizer_factory("japanese", JapaneseDictTokenizerFactory)
