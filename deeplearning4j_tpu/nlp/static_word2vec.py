"""Read-only memory-mapped word vectors (reference
``models/word2vec/StaticWord2Vec.java`` — serve vectors from a closed
model without loading/duplicating the full matrix per consumer).

Backing store: an .npz/.npy matrix memory-mapped via numpy, plus the
vocab loaded from the sibling vocab file; or any (cache, matrix) pair
saved by :mod:`deeplearning4j_tpu.nlp.serializer`. Lookups never
mutate; an LRU keeps hot rows (the reference keeps a per-device cache).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def save_static(model_or_pair, directory: str) -> None:
    """Write <dir>/vectors.npy (float32 [V, D]) + <dir>/vocab.txt
    (word<TAB>count per line, index order)."""
    from deeplearning4j_tpu.nlp.serializer import _resolve

    cache, m = _resolve(model_or_pair)
    os.makedirs(directory, exist_ok=True)
    np.save(os.path.join(directory, "vectors.npy"),
            np.asarray(m, np.float32))
    with open(os.path.join(directory, "vocab.txt"), "w",
              encoding="utf-8") as f:
        for i in range(len(cache)):
            w = cache.word_for(cache.word_at(i))
            f.write(f"{w.word}\t{w.count}\n")


class StaticWord2Vec:
    """Read-only vector store over an mmapped matrix (reference
    ``StaticWord2Vec.java``)."""

    def __init__(self, directory: str, cache_size: int = 1024):
        vec_path = os.path.join(directory, "vectors.npy")
        vocab_path = os.path.join(directory, "vocab.txt")
        if not (os.path.exists(vec_path) and os.path.exists(vocab_path)):
            raise FileNotFoundError(
                f"expected vectors.npy + vocab.txt under {directory!r}"
            )
        # mmap: rows page in on demand, shared across processes
        self.syn0 = np.load(vec_path, mmap_mode="r")
        self.cache = VocabCache()
        with open(vocab_path, encoding="utf-8") as f:
            for line in f:
                if not line.strip():
                    continue
                word, _, count = line.rstrip("\n").partition("\t")
                self.cache.add(VocabWord(word, int(count or 1)))
        if len(self.cache) != self.syn0.shape[0]:
            raise ValueError(
                f"vocab size {len(self.cache)} != matrix rows "
                f"{self.syn0.shape[0]}"
            )
        self.layer_size = int(self.syn0.shape[1])
        self._lru: OrderedDict = OrderedDict()
        self._lru_size = cache_size

    # -- reference WordVectors surface -----------------------------------

    def has_word(self, word: str) -> bool:
        return self.cache.index_of(word) >= 0

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.cache.index_of(word)
        if i < 0:
            return None
        if i in self._lru:
            self._lru.move_to_end(i)
            return self._lru[i]
        v = np.array(self.syn0[i])  # copy out of the mmap
        # read-only: callers mutating the returned row in place must
        # not corrupt the cache shared by later lookups
        v.flags.writeable = False
        self._lru[i] = v
        if len(self._lru) > self._lru_size:
            self._lru.popitem(last=False)
        return v

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        na = np.linalg.norm(va)
        nb = np.linalg.norm(vb)
        if na == 0 or nb == 0:
            return 0.0
        return float(va @ vb / (na * nb))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.get_word_vector(word)
        if v is None:
            return []
        m = np.asarray(self.syn0)
        norms = np.linalg.norm(m, axis=1)
        sims = (m @ v) / np.maximum(norms * np.linalg.norm(v), 1e-12)
        sims[self.cache.index_of(word)] = -np.inf
        return [
            self.cache.word_at(int(i)) for i in np.argsort(-sims)[:n]
        ]
