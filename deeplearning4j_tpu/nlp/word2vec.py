"""Word2Vec / SequenceVectors on batched XLA ops (reference:
``models/sequencevectors/SequenceVectors.java:161`` fit,
``models/word2vec/Word2Vec.java:31``, learning algorithms
``models/embeddings/learning/impl/elements/SkipGram.java:31`` /
``CBOW.java``, lookup table
``models/embeddings/inmemory/InMemoryLookupTable.java:55``).

TPU-first redesign of the hogwild trainer: the reference races N
threads over shared syn0/syn1 with per-pair axpy updates through the
native ``AggregateSkipGram`` op. Here the host packs fixed-shape
batches of (center, context, negatives | huffman path) int32 arrays
and ONE jitted XLA program does gather → dot → sigmoid → scatter-add
for the whole batch — the TPU-shaped equivalent of the fused native
aggregate. Updates within a batch are AVERAGED (synchronous
large-batch SGD; ``learning_rate`` is the batch-level step, default
0.5) rather than racing per pair; parity is statistical (SURVEY.md §7
hard part 3).
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (
    Huffman,
    VocabCache,
    VocabConstructor,
    build_unigram_table,
    subsample_mask,
)

# ---------------------------------------------------------------------------
# Jitted update steps. Static over (B, K|L, D); shapes are pinned by
# the host batcher so each variant compiles once.
# ---------------------------------------------------------------------------


def _dense_rows() -> bool:
    """Historical knob, kept for signature/compile-cache stability: it
    used to route TPU lookups through a bf16 one-hot matmul (MXU-
    friendly gradient), but that materialized a ``[B, V]`` one-hot and
    rounded rows through bf16 — ``_rows`` is a plain gather on every
    platform now, bitwise-identical across this flag. The value still
    threads into the jitted steps as a static argument (so flipping
    ``DL4J_TPU_W2V_DENSE`` still re-keys the compile cache exactly as
    before), and sparse-gradient row updates live in
    ``embeddings/sparse.py``. Env override: DL4J_TPU_W2V_DENSE=1/0."""
    import os

    from deeplearning4j_tpu.ops.dispatch import effective_platform

    env = os.environ.get("DL4J_TPU_W2V_DENSE", "auto").lower()
    if env in ("1", "true", "on"):
        return True
    if env in ("0", "false", "off"):
        return False
    return effective_platform() == "tpu"


def _rows(table, ids, dense):
    """table[ids] — always a gather, on every platform.

    The ``dense=True`` branch used to lower this as
    ``one_hot(ids, V, bf16) @ table``: that materializes a ``[B, V]``
    one-hot (cost scales with VOCAB, not batch — the exact failure
    mode the sharded embeddings subsystem exists to avoid) and rounds
    the looked-up rows through bf16, so the two paths diverged by
    ~1e-4. ``jnp.take`` keeps the lookup O(B·D) and bitwise-identical
    whichever way ``dense`` is flipped; the MXU-gradient question is
    the sparse update's job now (``embeddings/sparse.py``).

    ``dense`` is REQUIRED and must be threaded in as a STATIC jit
    argument by the callers — it no longer changes the math (tests
    assert bitwise-equal loss across it), but it stays in every step
    signature so compile-cache keys and the ``DL4J_TPU_W2V_DENSE``
    override surface are unchanged."""
    del dense
    return jnp.take(table, ids, axis=0)


def _ns_step_raw(syn0, syn1neg, centers, contexts, negs, mask, alpha,
                 dense):
    """Negative-sampling step (SkipGram: centers=input word ids,
    contexts=predicted word ids; CBOW passes precomputed context means
    through ``_ns_step_cbow`` instead)."""
    def loss_fn(tables):
        s0, s1 = tables
        v = _rows(s0, centers, dense)        # [B, D]
        u_pos = _rows(s1, contexts, dense)   # [B, D]
        u_neg = _rows(s1, negs, dense)       # [B, K, D]
        pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, axis=-1))
        # a drawn negative equal to the true context is masked out (the
        # reference resamples on collision; masking is the static-shape
        # equivalent)
        nvalid = (negs != contexts[:, None]).astype(v.dtype)
        neg = jnp.sum(
            nvalid
            * jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", v, u_neg)),
            axis=-1,
        )
        return -jnp.sum(mask * (pos + neg)) / jnp.maximum(jnp.sum(mask), 1.0)

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1neg))
    return syn0 - alpha * g0, syn1neg - alpha * g1, loss


def _hs_step_raw(syn0, syn1, centers, codes, points, path_mask, mask,
                 alpha, dense):
    """Hierarchical-softmax step: codes/points are the context word's
    padded Huffman path ([B, L]); loss per node is
    -log σ((1-2·code)·(v_center · syn1[point]))."""
    def loss_fn(tables):
        s0, s1 = tables
        v = _rows(s0, centers, dense)        # [B, D]
        u = _rows(s1, points, dense)         # [B, L, D]
        x = jnp.einsum("bd,bld->bl", v, u)
        sign = 1.0 - 2.0 * codes
        ll = jax.nn.log_sigmoid(sign * x) * path_mask
        return -jnp.sum(mask * jnp.sum(ll, axis=-1)) / jnp.maximum(jnp.sum(mask), 1.0)

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1))
    return syn0 - alpha * g0, syn1 - alpha * g1, loss


# ``dense`` is a STATIC argument so the env-var/platform choice
# participates in the compilation cache key (flipping it recompiles
# instead of silently reusing the other path's executable).
_ns_step = functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("dense",)
)(_ns_step_raw)
_hs_step = functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("dense",)
)(_hs_step_raw)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                   static_argnames=("dense",))
def _sg_scan_steps(syn0, syn1, syn1neg, centers_k, contexts_k, codes_k,
                   points_k, pmask_k, negs_k, mask_k, alphas_k,
                   dense):
    """k skip-gram batches fused into ONE dispatch via lax.scan (same
    rationale as MultiLayerNetwork._build_multi_step: per-batch
    host->device transfers+dispatches bound throughput). hs/ns legs
    participate according to which table carries are non-None."""

    def body(tables, per):
        s0, s1, s1n = tables
        c, o, cd, pt, pm, ng, m, a = per
        loss = 0.0
        if s1 is not None:
            s0, s1, l1 = _hs_step_raw(s0, s1, c, cd, pt, pm, m, a,
                                      dense)
            loss = loss + l1
        if s1n is not None:
            s0, s1n, l2 = _ns_step_raw(s0, s1n, c, o, ng, m, a, dense)
            loss = loss + l2
        return (s0, s1, s1n), loss

    (syn0, syn1, syn1neg), losses = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (centers_k, contexts_k, codes_k, points_k, pmask_k, negs_k,
         mask_k, alphas_k),
    )
    return syn0, syn1, syn1neg, losses


_NEG_POOL_MAX = 1 << 18  # presampled negatives; rolled+tiled per epoch


@functools.partial(
    jax.jit, static_argnames=("N", "V", "P", "W", "K", "B"),
)
def _unpack_corpus(packed, *, N, V, P, W, K, B):
    """Split the single packed u16 upload back into corpus arrays
    (layout: ids[N] | pos|slen<<8 [N] | kp_q[V] | pool[P]). One
    buffer = ONE host->device transfer: through the dev tunnel each
    separate jnp.asarray pays a ~100 ms round trip, which dominated
    the cold fit when the corpus shipped as 6 arrays."""
    ids = packed[:N].astype(jnp.int32)
    ps = packed[N:2 * N].astype(jnp.int32)
    pos = ps & 0xFF
    slen = ps >> 8
    kp = packed[2 * N:2 * N + V].astype(jnp.float32) / 65535.0
    pool = packed[2 * N + V:2 * N + V + P]
    # per-position keep prob: one [N] gather, ONCE per corpus — fine
    # outside the hot epoch loop (a one-hot matmul here would build
    # an [N, V] f32 intermediate: 1.7 GB at bench scale, HBM death
    # at real vocabularies)
    kp_pos = jnp.take(kp, ids, axis=0)
    return ids, pos, slen, kp_pos, pool


@functools.partial(
    jax.jit, donate_argnums=(0, 1),
    static_argnames=("E", "W", "K", "B", "dense"),
)
def _sg_device_epochs(syn0, syn1neg, ids, pos, slen, kp_pos, neg_pool,
                      base_key, sched, *, E, W, K, B, dense):
    """ONE dispatch = E full skip-gram/NS epochs, generated and
    trained on device (VERDICT r4 #2: the cold path was bounded by
    host pair-generation + host->device transfer of ~90 bytes/word;
    here the corpus ids live in HBM and each epoch's subsampling,
    reduced windows, negatives and updates are all device work — the
    TPU-shaped equivalent of the reference's producer thread
    (``SequenceVectors.java:935`` AsyncSequencer), which exists to
    hide exactly this host prep). An outer ``lax.scan`` over E epochs
    keeps the WHOLE multi-epoch fit in one dispatch — measured on the
    dev tunnel each dispatch costs ~20 ms of latency against ~21 ms
    of device work per epoch at bench scale, so per-epoch dispatching
    halves throughput. Per-epoch keys fold in ON device and the
    linear alpha schedule derives from the 4-scalar ``sched``
    (lr0, lr_min, total_items, step0), so a fit's recurring host
    traffic is that one tiny array.

    Formulation: per-CENTER padded contexts. Each corpus position is a
    center with up to 2W context slots (validity mask = reduced
    window + sentence bounds + subsampling), and negatives are drawn
    per center, shared across its pairs. The loss is the exact pair
    sum Σ_pairs [log σ(v_c·u_o) + Σ_k log σ(-v_c·u_nk)] with the
    negative term factored per center (weighted by its surviving pair
    count, collision-masked per pair) — word2vec.c semantics up to
    negative-sample sharing, which trades per-pair draws for a ~3x
    FLOP cut in the dominant one-hot lookups (statistical parity,
    module docstring). Alphas come in precomputed per batch.

    Divergences from the host generator (documented): subsampling
    masks pairs in place rather than compacting the corpus first (so
    windows do not stretch across removed frequent words), and
    negatives come from a host-presampled unigram^0.75 pool rotated by
    a random per-epoch offset rather than fresh per-epoch table draws
    — the marginal distribution is identical (the pool is itself
    table-sampled), only cross-epoch independence is relaxed.

    The generation phase is deliberately GATHER-FREE: contexts and
    keep-flags are built by 2W static shifts of the corpus array,
    per-position keep probabilities and the negative pool come in
    precomputed — TPUs execute large scalar gathers row-serially, and
    a gather-based first cut of this generator cost more than the
    training matmuls it feeds.
    """
    N = ids.shape[0]
    n_batches = N // B
    ids32 = ids.astype(jnp.int32)
    offsets = [o for o in range(-W, W + 1) if o != 0]
    offs = jnp.asarray(offsets, jnp.int32)
    p = pos[:, None] + offs[None, :]
    inb = (p >= 0) & (p < slen[:, None])
    pad_ids = jnp.pad(ids32, (W, W))
    # context ids via static shifts, not gathers (epoch-independent)
    ctx = jnp.stack(
        [pad_ids[W + o:W + o + N] for o in offsets], axis=1
    )                                                   # [N, 2W]
    centers_b = ids32[: n_batches * B].reshape(n_batches, B)
    ctx_b = ctx[: n_batches * B].reshape(n_batches, B, -1)

    def body(tables, per):
        s0, s1n = tables
        c, cx, cm, ng, a = per

        def loss_fn(ts):
            t0, t1 = ts
            v = _rows(t0, c, dense)                     # [B, D]
            u_c = _rows(t1, cx, dense)                  # [B, 2W, D]
            u_n = _rows(t1, ng, dense)                  # [B, K, D]
            pos_ll = jax.nn.log_sigmoid(
                jnp.einsum("bd,bwd->bw", v, u_c)
            )
            # per-pair collision mask (reference resamples a negative
            # equal to the true context; masking is the static-shape
            # equivalent): weight of negative k = count of this
            # center's valid pairs whose context != negs[k]
            w_k = jnp.einsum(
                "bw,bkw->bk", cm,
                (ng[:, :, None] != cx[:, None, :]).astype(cm.dtype),
            )
            neg_ll = jax.nn.log_sigmoid(
                -jnp.einsum("bd,bkd->bk", v, u_n)
            )
            npairs = jnp.maximum(jnp.sum(cm), 1.0)
            return -(jnp.sum(cm * pos_ll)
                     + jnp.sum(w_k * neg_ll)) / npairs

        loss, (g0, g1) = jax.value_and_grad(loss_fn)((s0, s1n))
        return (s0 - a * g0, s1n - a * g1), loss

    lr0, lr_min, total, step0 = (sched[0], sched[1], sched[2],
                                 sched[3])

    def epoch(tables, e):
        key = jax.random.fold_in(base_key, e)
        steps = (step0 + e.astype(jnp.float32) * n_batches
                 + jnp.arange(n_batches, dtype=jnp.float32))
        frac = jnp.minimum(steps * B / total, 1.0)
        alphas_e = jnp.maximum(lr0 * (1.0 - frac), lr_min)
        k1, k2, k3 = jax.random.split(key, 3)
        keep = jax.random.uniform(k1, (N,)) < kp_pos
        b = jax.random.randint(k2, (N,), 1, W + 1)
        pad_keep = jnp.pad(keep, (W, W))
        keep_ctx = jnp.stack(
            [pad_keep[W + o:W + o + N] for o in offsets], axis=1
        )
        cmask = (
            inb
            & (jnp.abs(offs)[None, :] <= b[:, None])
            & keep[:, None] & keep_ctx
        ).astype(syn0.dtype)
        shift = jax.random.randint(k3, (), 0, neg_pool.size)
        flat = jnp.roll(neg_pool.reshape(-1), shift)
        reps = -(-(N * K) // flat.size)
        if reps > 1:
            flat = jnp.tile(flat, reps)
        negs = flat[: N * K].reshape(N, K).astype(jnp.int32)
        per = (
            centers_b,
            ctx_b,
            cmask[: n_batches * B].reshape(n_batches, B, -1),
            negs[: n_batches * B].reshape(n_batches, B, -1),
            alphas_e,
        )
        tables, losses = jax.lax.scan(body, tables, per)
        return tables, losses

    (syn0, syn1neg), losses = jax.lax.scan(
        epoch, (syn0, syn1neg), jnp.arange(E, dtype=jnp.int32)
    )
    return syn0, syn1neg, losses


def _cbow_hidden(s0, ctx_ids, ctx_mask, dense):
    ctx = _rows(s0, ctx_ids, dense)          # [B, W, D]
    denom = jnp.maximum(jnp.sum(ctx_mask, axis=-1, keepdims=True), 1.0)
    return jnp.sum(ctx * ctx_mask[..., None], axis=1) / denom  # [B, D]


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("dense",))
def _cbow_ns_step(syn0, syn1neg, ctx_ids, ctx_mask, targets, negs, mask,
                  alpha, dense):
    """CBOW + negative sampling: mean of context vectors predicts the
    center word (reference ``CBOW.java`` iterateSample)."""
    def loss_fn(tables):
        s0, s1 = tables
        h = _cbow_hidden(s0, ctx_ids, ctx_mask, dense)
        u_pos = _rows(s1, targets, dense)
        u_neg = _rows(s1, negs, dense)
        pos = jax.nn.log_sigmoid(jnp.sum(h * u_pos, axis=-1))
        nvalid = (negs != targets[:, None]).astype(h.dtype)
        neg = jnp.sum(
            nvalid
            * jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", h, u_neg)),
            axis=-1,
        )
        return -jnp.sum(mask * (pos + neg)) / jnp.maximum(jnp.sum(mask), 1.0)

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1neg))
    return syn0 - alpha * g0, syn1neg - alpha * g1, loss


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("dense",))
def _cbow_hs_step(syn0, syn1, ctx_ids, ctx_mask, codes, points, path_mask,
                  mask, alpha, dense):
    """CBOW + hierarchical softmax: context mean against the TARGET
    word's Huffman path."""
    def loss_fn(tables):
        s0, s1 = tables
        h = _cbow_hidden(s0, ctx_ids, ctx_mask, dense)
        u = _rows(s1, points, dense)         # [B, L, D]
        x = jnp.einsum("bd,bld->bl", h, u)
        sign = 1.0 - 2.0 * codes
        ll = jax.nn.log_sigmoid(sign * x) * path_mask
        return -jnp.sum(mask * jnp.sum(ll, axis=-1)) / jnp.maximum(jnp.sum(mask), 1.0)

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1))
    return syn0 - alpha * g0, syn1 - alpha * g1, loss


# ---------------------------------------------------------------------------
# Lookup table
# ---------------------------------------------------------------------------


class InMemoryLookupTable:
    """syn0/syn1/syn1neg embedding matrices (reference
    ``InMemoryLookupTable.java:55``); syn0 rows are the word vectors."""

    def __init__(self, cache: VocabCache, layer_size: int, seed: int = 12345,
                 use_hs: bool = False, negative: int = 5):
        self.cache = cache
        self.layer_size = layer_size
        self.use_hs = use_hs
        self.negative = negative
        v = len(cache)
        rng = np.random.RandomState(seed)
        # reference resetWeights: syn0 ~ U(-0.5, 0.5)/layerSize
        self.syn0 = jnp.asarray(
            (rng.rand(v, layer_size) - 0.5) / layer_size, jnp.float32
        )
        self.syn1 = (
            jnp.zeros((v, layer_size), jnp.float32) if use_hs else None
        )
        self.syn1neg = (
            jnp.zeros((v, layer_size), jnp.float32) if negative > 0 else None
        )
        self._normalized: Optional[np.ndarray] = None

    def vector(self, word: str) -> Optional[np.ndarray]:
        i = self.cache.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def invalidate_norms(self):
        self._normalized = None

    def normalized(self) -> np.ndarray:
        if self._normalized is None:
            m = np.asarray(self.syn0)
            norms = np.linalg.norm(m, axis=1, keepdims=True)
            self._normalized = m / np.maximum(norms, 1e-12)
        return self._normalized


# ---------------------------------------------------------------------------
# SequenceVectors: generic trainer over id sequences
# ---------------------------------------------------------------------------


class SequenceVectors:
    """Generic embedding trainer over integer id sequences (reference
    ``SequenceVectors<T>`` — DeepWalk and ParagraphVectors reuse it).

    Subclasses/owners supply: a built ``VocabCache`` and an iterable of
    id sequences per epoch (``_sequences()``).
    """

    def __init__(self, cache: VocabCache, *, layer_size=100, window=5,
                 learning_rate=0.5, min_learning_rate=1e-4, negative=5,
                 use_hierarchic_softmax=False, sample=1e-3, epochs=1,
                 iterations=1, batch_size=1024, seed=12345,
                 algorithm="SkipGram"):
        if negative <= 0 and not use_hierarchic_softmax:
            raise ValueError(
                "Need negative sampling (negative>0) or hierarchical "
                "softmax (use_hierarchic_softmax=True)"
            )
        self.cache = cache
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.sample = sample
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = algorithm
        self.scan_chunk = 16  # skip-gram batches fused per dispatch
        # Device-resident epoch replay: the prepared (ids, negatives,
        # masks, alphas) chunk arrays for an epoch are cached in HBM
        # keyed by (epoch seed, step offset, batch/scan geometry, and
        # every hyperparameter baked into the arrays), so repeated
        # fits (and epochs>1 re-runs with matching keys) skip ALL
        # host-side pair generation + transfer — the NLP analog of the
        # engines' multi-epoch device cache. Pure caching: the cached
        # arrays are bit-identical to regeneration (same seeds); a
        # subclass that mutates its corpus between fits under the same
        # seed must call clear_epoch_cache(). Bounded by
        # ``epoch_cache_budget_bytes`` (epochs past the budget stream
        # as before); 0 disables like cache_epoch_data=False.
        self.cache_epoch_data = True
        self.epoch_cache_budget_bytes = 256 * 2 ** 20
        self._epoch_cache: dict = {}
        self._epoch_cache_bytes = 0
        # On-device epoch generation (skip-gram/NS only): "auto" =
        # enabled on TPU, where the cold path is otherwise bounded by
        # host pair-gen + transfer; True/False force. Env override:
        # DL4J_TPU_W2V_DEVICE_GEN=1/0.
        self.device_epoch_gen = "auto"
        self._dev_base_key = None
        self._dev_corpus = None  # (key, (ids, pos, slen, kp_pos, pool, n))
        # device-gen continuation counters: repeated fit() calls must
        # draw FRESH epoch keys (the first fit's stream replayed
        # verbatim before) and continue the lr schedule where the
        # last fit stopped instead of restarting it
        self._dev_fit_no = 0
        self._dev_steps_done = 0
        self.lookup = self._make_lookup()
        self._rng = np.random.RandomState(seed)
        if use_hierarchic_softmax:
            huff = Huffman(cache.words)
            huff.build()
            self._codes, self._points, self._code_lens = huff.padded_arrays()
        if negative > 0:
            self._table = build_unigram_table(cache)
        self._counts = np.array([w.count for w in cache.words], np.int64)

    def _make_lookup(self) -> InMemoryLookupTable:
        """Lookup-table factory hook: the mesh-sharded subclass
        (``embeddings/word2vec.py``) substitutes row-sharded tables
        here, so the dense ``[V, D]`` device arrays never allocate for
        vocabularies that don't fit one device."""
        return InMemoryLookupTable(
            self.cache, self.layer_size, seed=self.seed,
            use_hs=self.use_hs, negative=self.negative,
        )

    # -- corpus plumbing ----------------------------------------------------

    def _sequences(self) -> Iterable[np.ndarray]:
        raise NotImplementedError

    def _flatten_corpus(self, rng):
        """Concatenate every sequence into corpus-wide arrays for
        vectorized window generation: (all_ids, pos-in-sentence,
        own-sentence-length, reduced-window draw b ~ U{1..window}) —
        after frequent-word subsampling. Returns None for an
        empty/too-short corpus. Shared by the SkipGram and CBOW pair
        generators (the per-sentence Python loop this replaces
        dominated fit() wall-clock)."""
        total = self.cache.total_word_count
        seqs = [np.asarray(ids, np.int32) for ids in self._sequences()]
        seqs = [s for s in seqs if len(s) > 0]
        if not seqs:
            return None
        all_ids = np.concatenate(seqs)
        lens = np.array([len(s) for s in seqs], np.int32)
        sent = np.repeat(np.arange(len(lens), dtype=np.int32), lens)
        if self.sample > 0:
            keep = subsample_mask(
                all_ids, self._counts, total, self.sample, rng
            )
            all_ids = all_ids[keep]
            sent = sent[keep]
            lens = np.bincount(sent, minlength=len(lens)).astype(np.int32)
        n = len(all_ids)
        if n < 2:
            return None
        starts = np.repeat(
            np.cumsum(lens, dtype=np.int64).astype(np.int32) - lens, lens
        )
        pos = np.arange(n, dtype=np.int32) - starts
        slen = np.repeat(lens, lens)
        b = rng.randint(1, self.window + 1, n)
        return all_ids, pos, slen, b

    def _gen_pairs(self, epoch_seed: int):
        """(centers, contexts) int32 arrays for one epoch: reduced
        window sampling + frequent-word subsampling (reference
        SkipGram.learnSequence).

        Vectorized over the WHOLE corpus, not per sentence: all
        sequences are concatenated with a sentence-id array, so pair
        generation is ~2*window numpy slices total instead of per
        sentence — the host-side analog of batching for the MXU (the
        per-sentence loop dominated fit() wall-clock before)."""
        rng = np.random.RandomState(epoch_seed)
        flat = self._flatten_corpus(rng)
        if flat is None:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        all_ids, pos, slen, b = flat
        centers: List[np.ndarray] = []
        contexts: List[np.ndarray] = []
        for off in range(1, self.window + 1):
            idx = np.nonzero(b >= off)[0]
            left = idx[pos[idx] >= off]
            centers.append(all_ids[left])
            contexts.append(all_ids[left - off])
            right = idx[pos[idx] < slen[idx] - off]
            centers.append(all_ids[right])
            contexts.append(all_ids[right + off])
        c = np.concatenate(centers).astype(np.int32)
        o = np.concatenate(contexts).astype(np.int32)
        perm = rng.permutation(len(c))
        return c[perm], o[perm]

    def _gen_cbow(self, epoch_seed: int):
        """(targets[N], ctx_ids[N, 2W], ctx_mask[N, 2W]) for one epoch
        (true windowed CBOW: all context words within the reduced
        window feed one averaged prediction)."""
        rng = np.random.RandomState(epoch_seed)
        W = self.window
        offsets = [o for o in range(-W, W + 1) if o != 0]
        flat = self._flatten_corpus(rng)
        if flat is None:
            z = np.zeros((0, 2 * W), np.int32)
            return np.zeros(0, np.int32), z, z.astype(np.float32)
        all_ids, pos, slen, b = flat
        n = len(all_ids)
        padded = np.pad(all_ids, (W, W))
        cols, masks = [], []
        for off in offsets:
            cols.append(padded[W + off:W + off + n])
            masks.append(
                (pos + off >= 0) & (pos + off < slen)
                & (np.abs(off) <= b)
            )
        ctx = np.stack(cols, 1).astype(np.int32)
        cm = np.stack(masks, 1)
        keep_rows = cm.any(axis=1)
        t = all_ids[keep_rows].astype(np.int32)
        c = ctx[keep_rows]
        m = cm[keep_rows].astype(np.float32)
        perm = rng.permutation(len(t))
        return t[perm], c[perm], m[perm]

    # -- training -----------------------------------------------------------

    def clear_epoch_cache(self) -> None:
        """Drop the device-resident epoch replay cache AND the
        device-generation corpus arrays (required after mutating the
        corpus without changing the seed)."""
        self._epoch_cache.clear()
        self._epoch_cache_bytes = 0
        self._dev_corpus = None

    def _epoch_cache_key(self, ep_seed: int, step: int):
        """Everything that shapes the prepared chunk arrays: epoch
        seed + step offset (negatives, alpha offsets), geometry, the
        hyperparameters baked into alphas/negatives/hs-paths, and the
        pair-generation knobs (window/sample/algorithm shape
        ``_gen_pairs`` output via ``_flatten_corpus``)."""
        return (
            ep_seed, step, self.batch_size, self.scan_chunk,
            self.learning_rate, self.min_learning_rate, self.epochs,
            self.negative, self.use_hs,
            self.window, self.sample, self.algorithm,
        )

    @staticmethod
    def _chunks_nbytes(chunks) -> int:
        total = 0
        for tup in chunks:
            for a in tup[:-1]:
                if a is not None:
                    total += int(np.prod(a.shape)) * a.dtype.itemsize
        return total

    def _use_device_gen(self) -> bool:
        import os

        from deeplearning4j_tpu.ops.dispatch import effective_platform

        if not (self.algorithm == "SkipGram" and self.negative > 0
                and not self.use_hs and self.iterations == 1
                and self._scan_path_ok()):
            return False
        env = os.environ.get("DL4J_TPU_W2V_DEVICE_GEN", "").lower()
        if env in ("1", "true", "on"):
            return True
        if env in ("0", "false", "off"):
            return False
        flag = self.device_epoch_gen
        if flag == "auto":
            return effective_platform() == "tpu"
        return bool(flag)

    def _flat_corpus_static(self):
        """One-time (ids, pos, slen) over the UNsubsampled corpus for
        the device-generation path — subsampling is drawn on device
        per epoch, so these arrays are epoch-independent."""
        seqs = [np.asarray(ids, np.int32) for ids in self._sequences()]
        seqs = [s for s in seqs if len(s) > 0]
        if not seqs:
            return None
        all_ids = np.concatenate(seqs)
        lens = np.array([len(s) for s in seqs], np.int32)
        starts = np.repeat(
            np.cumsum(lens, dtype=np.int64).astype(np.int32) - lens, lens
        )
        pos = np.arange(len(all_ids), dtype=np.int32) - starts
        slen = np.repeat(lens, lens)
        return all_ids, pos, slen

    def _keep_probs(self) -> np.ndarray:
        """Per-word P(keep) of frequent-word subsampling (reference
        SkipGram sample branch), as a [V] table for device draws."""
        v = len(self._counts)
        if self.sample <= 0:
            return np.ones(v, np.float32)
        total = max(self.cache.total_word_count, 1)
        freq = self._counts / total
        kp = (np.sqrt(freq / self.sample) + 1) * (
            self.sample / np.maximum(freq, 1e-12)
        )
        return np.minimum(kp, 1.0).astype(np.float32)

    def _fit_device_gen(self) -> None:
        """Epoch loop for the on-device generation path: one
        ``_sg_device_epoch`` dispatch per epoch; the only recurring
        host work is the [n_batches] alpha schedule."""
        B = self.batch_size
        # staleness key: everything baked into the cached device arrays
        # (kp_pos bakes sample; the pool bakes negative+seed; padding
        # bakes batch_size) — same discipline as _epoch_cache_key
        dev_key = (B, self.negative, self.sample, self.seed)
        if self._dev_corpus is not None and self._dev_corpus[0] != dev_key:
            self._dev_corpus = None
        if self._dev_corpus is None:
            flat = self._flat_corpus_static()
            if flat is None:
                return
            all_ids, pos, slen = flat
            n = len(all_ids)
            pad = (-n) % B
            if pad:
                all_ids = np.pad(all_ids, (0, pad))
                pos = np.pad(pos, (0, pad))
                slen = np.pad(slen, (0, pad))  # slen 0 -> no pairs
            V = len(self._counts)
            pool_rng = np.random.RandomState(self.seed ^ 0x5EED)
            P = int(min(len(all_ids) * self.negative, _NEG_POOL_MAX))
            pool = self._table[
                pool_rng.randint(0, len(self._table), P)
            ]
            if V < 2 ** 16 and int(slen.max(initial=0)) < 256:
                # ONE u16 buffer = ONE transfer: ids | pos|slen<<8 |
                # kp quantized to u16 fixed point | negative pool.
                # Each separate jnp.asarray pays a full host->device
                # round trip (~100 ms on the dev tunnel) — the cold
                # fit was 6 round trips of latency, not bandwidth.
                kp_q = np.round(
                    self._keep_probs() * 65535.0
                ).astype(np.uint16)
                packed = np.concatenate([
                    all_ids.astype(np.uint16),
                    (pos.astype(np.uint16)
                     | (slen.astype(np.uint16) << 8)),
                    kp_q,
                    pool.astype(np.uint16),
                ])
                self._dev_upload_bytes = packed.nbytes
                arrs = _unpack_corpus(
                    jnp.asarray(packed), N=len(all_ids), V=V, P=P,
                    W=self.window, K=self.negative, B=B,
                )
            else:
                # large-vocab / long-sentence fallback: plain arrays
                idt = np.uint16 if V < 2 ** 16 else np.int32
                kp_pos = self._keep_probs()[all_ids].astype(np.float32)
                arrs = (
                    jnp.asarray(all_ids.astype(idt)),
                    jnp.asarray(pos), jnp.asarray(slen),
                    jnp.asarray(kp_pos), jnp.asarray(pool.astype(idt)),
                )
                self._dev_upload_bytes = sum(
                    int(np.prod(a.shape)) * a.dtype.itemsize
                    for a in arrs
                )
            self._dev_corpus = (dev_key, (*arrs, n))
        ids_d, pos_d, slen_d, kp_d, pool_d, n_words = self._dev_corpus[1]
        n_batches = ids_d.shape[0] // B
        E = self.epochs
        lr0, lr_min = self.learning_rate, self.min_learning_rate
        lk = self.lookup
        if self._dev_base_key is None:
            self._dev_base_key = jax.random.PRNGKey(self.seed)
        # Repeated fit() calls continue training, not replay it: the
        # fit counter folds into the base key so call #2 draws fresh
        # epoch keys (before this, the identical sampling stream
        # re-ran every call), and the lr schedule resumes from the
        # steps already taken. The first call folds nothing and sees
        # the original totals, so its trajectory stays bitwise
        # identical to prior releases.
        base_key = self._dev_base_key
        if self._dev_fit_no:
            base_key = jax.random.fold_in(base_key, self._dev_fit_no)
        total = max((self._dev_steps_done + n_batches * E) * B, 1)
        # ALL epochs in one dispatch; the schedule rides in as 4
        # scalars and per-epoch keys fold in on device, so a fit is
        # one tiny transfer + one dispatch (per-epoch dispatching
        # paid ~20 ms of tunnel latency against ~21 ms of device
        # work; so did per-epoch host-side fold_in round trips)
        sched = jnp.asarray(
            [lr0, lr_min, float(total), float(self._dev_steps_done)],
            jnp.float32,
        )
        lk.syn0, lk.syn1neg, _ = _sg_device_epochs(
            lk.syn0, lk.syn1neg, ids_d, pos_d, slen_d, kp_d,
            pool_d, base_key, sched,
            E=E, W=self.window, K=self.negative, B=B,
            dense=_dense_rows(),
        )
        self._dev_fit_no += 1
        self._dev_steps_done += n_batches * E
        lk.invalidate_norms()

    def fit(self) -> None:
        if self._use_device_gen():
            return self._fit_device_gen()
        B = self.batch_size
        lr0, lr_min = self.learning_rate, self.min_learning_rate
        total_items = None
        step = 0
        cbow = self.algorithm == "CBOW"
        for epoch in range(self.epochs):
            scan_ok = (
                not cbow and self.scan_chunk > 1
                and self.iterations == 1
                and self._scan_path_ok()
            )
            ep_seed = self.seed + 31 * epoch
            caching = (
                self.cache_epoch_data
                and self.epoch_cache_budget_bytes > 0
            )
            if scan_ok:
                key = self._epoch_cache_key(ep_seed, step)
                entry = self._epoch_cache.get(key) if caching else None
                if entry is not None:
                    n_items, chunks = entry
                    if total_items is None:
                        total_items = max(n_items * self.epochs, 1)
                    step = self._run_scan_chunks(chunks, step)
                    continue
            if cbow:
                t, c, m = self._gen_cbow(ep_seed)
                n_items = len(t)
            else:
                c, o = self._gen_pairs(ep_seed)
                n_items = len(c)
            if total_items is None:
                total_items = max(n_items * self.epochs, 1)
            if scan_ok:
                chunks = self._prepare_scan_chunks(
                    c, o, step, total_items, lr0, lr_min
                )
                if caching:
                    nbytes = self._chunks_nbytes(chunks)
                    if (self._epoch_cache_bytes + nbytes
                            <= self.epoch_cache_budget_bytes):
                        self._epoch_cache[key] = (n_items, chunks)
                        self._epoch_cache_bytes += nbytes
                step = self._run_scan_chunks(chunks, step)
                continue
            for s in range(0, n_items, B):
                mask = np.ones(B, np.float32)
                if cbow:
                    tb, cb, mb = t[s:s + B], c[s:s + B], m[s:s + B]
                    if len(tb) < B:
                        pad = B - len(tb)
                        mask[len(tb):] = 0.0
                        tb = np.pad(tb, (0, pad))
                        cb = np.pad(cb, ((0, pad), (0, 0)))
                        mb = np.pad(mb, ((0, pad), (0, 0)))
                else:
                    cb, ob = c[s:s + B], o[s:s + B]
                    if len(cb) < B:
                        pad = B - len(cb)
                        mask[len(cb):] = 0.0
                        cb = np.pad(cb, (0, pad))
                        ob = np.pad(ob, (0, pad))
                frac = min((step * B) / total_items, 1.0)
                alpha = max(lr0 * (1 - frac), lr_min)
                for _ in range(self.iterations):
                    if cbow:
                        self._apply_cbow_batch(tb, cb, mb, mask, alpha, step)
                    else:
                        self._apply_batch(cb, ob, mask, alpha, step)
                step += 1
        self.lookup.invalidate_norms()

    def _scan_path_ok(self) -> bool:
        """The scan epoch bypasses the per-batch ``_apply_batch`` hook;
        a subclass overriding it would silently lose its override, so
        scanning requires either the base hook or an explicit
        ``scan_path_compatible = True`` (set by subclasses that hook
        placement via ``_put_stacked`` instead)."""
        return (
            type(self)._apply_batch is SequenceVectors._apply_batch
            or getattr(self, "scan_path_compatible", False)
        )

    def _prepare_scan_chunks(self, centers, contexts, step, total_items,
                             lr0, lr_min) -> list:
        """Build the device-resident chunk arrays for one scan-fused
        skip-gram epoch: ``scan_chunk`` batches per XLA call, identical
        math/negative-sampling/alphas to the per-batch path (same
        per-batch step seeds). Returns a list of per-dispatch tuples
        consumed by :meth:`_run_scan_chunks` (and cached for epoch
        replay — ``_sg_scan_steps`` donates only the tables, never
        these batch arrays, so they are reusable)."""
        B = self.batch_size
        K = self.scan_chunk
        n = len(centers)
        # word ids transfer at native width (uint16 for vocabs under
        # 64k — half the host->device bytes); the on-device gather
        # accepts either and values are identical
        idt = np.uint16 if len(self._counts) < 2 ** 16 else np.int32
        chunks = []
        for s0 in range(0, n, B * K):
            cs = centers[s0:s0 + B * K]
            os_ = contexts[s0:s0 + B * K]
            k = (len(cs) + B - 1) // B
            pad = k * B - len(cs)
            mask = np.ones(k * B, np.float32)
            if pad:
                mask[len(cs):] = 0.0
                cs = np.pad(cs, (0, pad))
                os_ = np.pad(os_, (0, pad))
            ck = cs.reshape(k, B).astype(idt, copy=False)
            ok = os_.reshape(k, B).astype(idt, copy=False)
            mk = mask.reshape(k, B)
            alphas = np.empty(k, np.float32)
            negs = (
                np.empty((k, B, self.negative), idt)
                if self.negative > 0 else None
            )
            for i in range(k):
                frac = min(((step + i) * B) / total_items, 1.0)
                alphas[i] = max(lr0 * (1 - frac), lr_min)
                if negs is not None:
                    negs[i] = self._sample_negatives(B, step + i)
            if self.use_hs:
                codes, points, pmask = self._path_arrays(ok.ravel())
                ckd = jnp.asarray(codes).reshape(k, B, -1)
                ptd = jnp.asarray(points).reshape(k, B, -1)
                pmd = jnp.asarray(pmask).reshape(k, B, -1)
            else:
                ckd = ptd = pmd = None
            chunks.append((
                self._put_stacked(ck), self._put_stacked(ok),
                ckd, ptd, pmd,
                self._put_stacked(negs) if negs is not None else None,
                self._put_stacked(mk), jnp.asarray(alphas), k,
            ))
            step += k
        return chunks

    def _run_scan_chunks(self, chunks, step) -> int:
        """Run a prepared epoch: one fused-scan dispatch per chunk,
        zero host work (the device-resident replay path)."""
        lk = self.lookup
        for (ck, ok, ckd, ptd, pmd, negs, mk, alphas, k) in chunks:
            lk.syn0, lk.syn1, lk.syn1neg, _ = _sg_scan_steps(
                lk.syn0, lk.syn1, lk.syn1neg, ck, ok, ckd, ptd, pmd,
                negs, mk, alphas, dense=_dense_rows(),
            )
            step += k
        return step

    def _put_stacked(self, a):
        """Placement hook for [k, B, ...] stacked batch arrays (the
        mesh-sharded subclass shards the B axis)."""
        return jnp.asarray(a)

    def _path_arrays(self, word_ids: np.ndarray):
        codes = jnp.asarray(self._codes[word_ids])
        points = jnp.asarray(self._points[word_ids])
        lens = self._code_lens[word_ids]
        pmask = jnp.asarray(
            (np.arange(self._codes.shape[1])[None, :] < lens[:, None])
            .astype(np.float32)
        )
        return codes, points, pmask

    def _apply_batch(self, centers, contexts, mask, alpha, step):
        lk = self.lookup
        alpha = jnp.float32(alpha)
        mask = jnp.asarray(mask)
        cb = jnp.asarray(centers)
        ob = jnp.asarray(contexts)
        if self.use_hs:
            codes, points, pmask = self._path_arrays(contexts)
            lk.syn0, lk.syn1, _ = _hs_step(
                lk.syn0, lk.syn1, cb, codes, points, pmask, mask, alpha,
                dense=_dense_rows(),
            )
        if self.negative > 0:
            negs = self._sample_negatives(len(centers), step)
            lk.syn0, lk.syn1neg, _ = _ns_step(
                lk.syn0, lk.syn1neg, cb, ob, jnp.asarray(negs), mask, alpha,
                dense=_dense_rows(),
            )

    def _apply_cbow_batch(self, targets, ctx_ids, ctx_mask, mask, alpha,
                          step):
        lk = self.lookup
        alpha = jnp.float32(alpha)
        mask = jnp.asarray(mask)
        tb = jnp.asarray(targets)
        cb = jnp.asarray(ctx_ids)
        cm = jnp.asarray(ctx_mask)
        if self.use_hs:
            codes, points, pmask = self._path_arrays(targets)
            lk.syn0, lk.syn1, _ = _cbow_hs_step(
                lk.syn0, lk.syn1, cb, cm, codes, points, pmask, mask, alpha,
                dense=_dense_rows(),
            )
        if self.negative > 0:
            negs = jnp.asarray(self._sample_negatives(len(targets), step))
            lk.syn0, lk.syn1neg, _ = _cbow_ns_step(
                lk.syn0, lk.syn1neg, cb, cm, tb, negs, mask, alpha,
                dense=_dense_rows(),
            )

    def _sample_negatives(self, b: int, step: int) -> np.ndarray:
        rng = np.random.RandomState((self.seed + step) % (2**31))
        idx = rng.randint(0, len(self._table), (b, self.negative))
        return self._table[idx]

    # -- query API (reference BasicModelUtils / wordVectors) ----------------

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup.vector(word)

    def has_word(self, word: str) -> bool:
        return word in self.cache

    def similarity(self, a: str, b: str) -> float:
        """Cosine similarity (reference
        ``BasicModelUtils.similarity``)."""
        ia, ib = self.cache.index_of(a), self.cache.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        m = self.lookup.normalized()
        return float(m[ia] @ m[ib])

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        """Top-n by cosine (reference ``wordsNearest``) — one matmul
        over the normalized table."""
        i = self.cache.index_of(word)
        if i < 0:
            return []
        m = self.lookup.normalized()
        sims = m @ m[i]
        sims[i] = -np.inf
        top = np.argsort(-sims)[:n]
        return [self.cache.word_at(int(t)) for t in top]

    def words_nearest_vec(self, vec: np.ndarray, n: int = 10) -> List[str]:
        m = self.lookup.normalized()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = m @ v
        top = np.argsort(-sims)[:n]
        return [self.cache.word_at(int(t)) for t in top]


# ---------------------------------------------------------------------------
# Word2Vec
# ---------------------------------------------------------------------------


class Word2Vec(SequenceVectors):
    """Word2Vec over a sentence corpus (reference
    ``models/word2vec/Word2Vec.java`` builder API)."""

    def __init__(self, cache, sentences_ids, **kw):
        super().__init__(cache, **kw)
        self._sentence_ids = sentences_ids

    def _sequences(self):
        return iter(self._sentence_ids)

    class Builder:
        def __init__(self):
            self._min_word_frequency = 1
            self._layer_size = 100
            self._window = 5
            self._lr = 0.5
            self._min_lr = 1e-4
            self._negative = 5
            self._hs = False
            self._sample = 1e-3
            self._epochs = 1
            self._iterations = 1
            self._batch_size = 1024
            self._seed = 12345
            self._algorithm = "SkipGram"
            self._iterator = None
            self._tokenizer = None

        def min_word_frequency(self, n): self._min_word_frequency = n; return self
        def layer_size(self, n): self._layer_size = n; return self
        def window_size(self, n): self._window = n; return self
        def learning_rate(self, x): self._lr = x; return self
        def min_learning_rate(self, x): self._min_lr = x; return self
        def negative_sample(self, n): self._negative = int(n); return self
        def use_hierarchic_softmax(self, b): self._hs = b; return self
        def sampling(self, x): self._sample = x; return self
        def epochs(self, n): self._epochs = n; return self
        def iterations(self, n): self._iterations = n; return self
        def batch_size(self, n): self._batch_size = n; return self
        def seed(self, n): self._seed = n; return self
        def elements_learning_algorithm(self, a): self._algorithm = a; return self
        def iterate(self, it): self._iterator = it; return self
        def tokenizer_factory(self, tf): self._tokenizer = tf; return self

        def build(self) -> "Word2Vec":
            if self._iterator is None:
                raise ValueError("iterate(sentence_iterator) is required")
            tf = self._tokenizer or DefaultTokenizerFactory()
            sentences = [
                tf.create(s).get_tokens() for s in self._iterator
            ]
            cache = VocabConstructor(
                min_word_frequency=self._min_word_frequency
            ).build_vocab_from_tokens(sentences)
            ids = [
                np.asarray(cache.id_stream(toks), np.int64)
                for toks in sentences
            ]
            return Word2Vec(
                cache, ids,
                layer_size=self._layer_size, window=self._window,
                learning_rate=self._lr, min_learning_rate=self._min_lr,
                negative=self._negative, use_hierarchic_softmax=self._hs,
                sample=self._sample, epochs=self._epochs,
                iterations=self._iterations, batch_size=self._batch_size,
                seed=self._seed, algorithm=self._algorithm,
            )
