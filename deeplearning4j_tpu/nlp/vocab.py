"""Vocabulary construction + Huffman coding + negative-sampling table
(reference: ``models/word2vec/wordstore/VocabConstructor.java``,
``models/word2vec/Huffman.java:34``, unigram table construction in
``InMemoryLookupTable.java``).

Host-side; produces the fixed-shape integer arrays (huffman
codes/points padded to max code length, unigram sampling table) that
the jitted training steps consume.
"""

from __future__ import annotations

import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabWord:
    """A vocab entry (reference ``VocabWord``): word, frequency,
    huffman code/points filled by ``Huffman.build``."""

    __slots__ = ("word", "count", "index", "code", "points")

    def __init__(self, word: str, count: int = 1, index: int = -1):
        self.word = word
        self.count = count
        self.index = index
        self.code: List[int] = []
        self.points: List[int] = []

    def __repr__(self):
        return f"VocabWord({self.word!r}, n={self.count}, i={self.index})"


class VocabCache:
    """In-memory vocab (reference ``AbstractCache`` /
    ``InMemoryLookupCache``)."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_word_count = 0

    def add(self, vw: VocabWord) -> None:
        vw.index = len(self.words)
        self.words.append(vw)
        self._by_word[vw.word] = vw

    def __contains__(self, word: str) -> bool:
        return word in self._by_word

    def __len__(self) -> int:
        return len(self.words)

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return -1 if vw is None else vw.index

    def word_at(self, index: int) -> str:
        return self.words[index].word

    def id_stream(self, tokens: Iterable[str]) -> List[int]:
        """Token strings -> known-word indices (unknowns dropped, as
        the reference does)."""
        out = []
        for t in tokens:
            vw = self._by_word.get(t)
            if vw is not None:
                out.append(vw.index)
        return out


class VocabConstructor:
    """Count words over a corpus, filter by min frequency, assign
    indices by descending count (reference ``VocabConstructor`` —
    parallel count collapsed to a single pass; Counter is plenty at
    host side)."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory=None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory

    def build_vocab(self, sentences: Iterable[str]) -> VocabCache:
        def tokens_of(sentence):
            if self.tokenizer_factory is not None:
                return self.tokenizer_factory.create(sentence).get_tokens()
            return sentence.split()

        return self.build_vocab_from_tokens(
            tokens_of(s) for s in sentences
        )

    def build_vocab_from_tokens(
        self, token_lists: Iterable[List[str]]
    ) -> VocabCache:
        """Build from pre-tokenized sentences — preserves tokens that
        contain spaces (n-grams)."""
        counts: Counter = Counter()
        for tokens in token_lists:
            counts.update(tokens)
        cache = VocabCache()
        # descending count, then lexical for determinism
        for word, n in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if n < self.min_word_frequency:
                continue
            cache.add(VocabWord(word, n))
        cache.total_word_count = sum(w.count for w in cache.words)
        return cache


class Huffman:
    """Huffman tree over vocab counts; fills each VocabWord's
    ``code`` (0/1 path) and ``points`` (inner-node indices root→leaf)
    (reference ``Huffman.java:34`` — same two-pass heap construction,
    vectorized here with numpy for the count arrays).
    """

    MAX_CODE_LENGTH = 40

    def __init__(self, words: List[VocabWord]):
        self.words = words

    def build(self) -> None:
        n = len(self.words)
        if n == 0:
            return
        if n == 1:
            self.words[0].code = [0]
            self.words[0].points = [0]
            return
        # heap of (count, tiebreak, node_id); nodes 0..n-1 are leaves,
        # n..2n-2 inner
        heap = [(w.count, i, i) for i, w in enumerate(self.words)]
        heapq.heapify(heap)
        parent = np.zeros(2 * n - 1, np.int64)
        binary = np.zeros(2 * n - 1, np.int8)
        next_id = n
        tiebreak = n
        while len(heap) > 1:
            c1, _, n1 = heapq.heappop(heap)
            c2, _, n2 = heapq.heappop(heap)
            parent[n1] = next_id
            parent[n2] = next_id
            binary[n2] = 1
            heapq.heappush(heap, (c1 + c2, tiebreak, next_id))
            next_id += 1
            tiebreak += 1
        root = 2 * n - 2
        for i, w in enumerate(self.words):
            code: List[int] = []
            points: List[int] = []
            node = i
            while node != root:
                code.append(int(binary[node]))
                points.append(int(parent[node]) - n)
                node = int(parent[node])
            code.reverse()
            points.reverse()
            if len(code) > self.MAX_CODE_LENGTH:
                raise ValueError(
                    f"Huffman code length {len(code)} exceeds "
                    f"{self.MAX_CODE_LENGTH}"
                )
            w.code = code
            w.points = points

    def padded_arrays(self):
        """(codes[V, L], points[V, L], lengths[V]) padded fixed-shape
        arrays for the jitted HS step."""
        L = max((len(w.code) for w in self.words), default=1)
        V = len(self.words)
        codes = np.zeros((V, L), np.float32)
        points = np.zeros((V, L), np.int32)
        lengths = np.zeros(V, np.int32)
        for i, w in enumerate(self.words):
            l = len(w.code)
            codes[i, :l] = w.code
            points[i, :l] = w.points
            lengths[i] = l
        return codes, points, lengths


def build_unigram_table(cache: VocabCache, table_size: int = 100_000,
                        power: float = 0.75,
                        limit: Optional[int] = None) -> np.ndarray:
    """Negative-sampling table: word index repeated proportionally to
    count^0.75 (reference ``InMemoryLookupTable.makeTable``).
    ``limit``: only the first N vocab rows participate (used by
    ParagraphVectors to keep label rows out of negative sampling)."""
    words = cache.words if limit is None else cache.words[:limit]
    counts = np.array([w.count for w in words], np.float64)
    probs = counts ** power
    probs /= probs.sum()
    # cumulative assignment, one vectorized pass
    boundaries = np.floor(np.cumsum(probs) * table_size).astype(np.int64)
    table = np.zeros(table_size, np.int32)
    start = 0
    for idx, end in enumerate(boundaries):
        if end > start:
            table[start:end] = idx
            start = end
    if start < table_size:
        table[start:] = len(words) - 1
    return table


def subsample_mask(ids: np.ndarray, counts: np.ndarray, total: int,
                   sample: float, rng: np.random.RandomState) -> np.ndarray:
    """Frequent-word subsampling keep-mask (reference SkipGram's
    ``sample`` branch: P(keep) = (sqrt(f/sample)+1)*sample/f)."""
    if sample <= 0:
        return np.ones(len(ids), bool)
    freq = counts[ids] / max(total, 1)
    keep_prob = (np.sqrt(freq / sample) + 1) * (sample / np.maximum(freq, 1e-12))
    return rng.rand(len(ids)) < np.minimum(keep_prob, 1.0)
