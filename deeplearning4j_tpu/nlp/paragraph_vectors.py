"""ParagraphVectors — document embeddings, PV-DBOW and PV-DM
(reference: ``models/paragraphvectors/ParagraphVectors.java`` with
sequence learning algorithms ``DBOW.java`` / ``DM.java``).

Labels (document ids) get embedding rows in the SAME syn0 table,
appended after the word vocab (the reference interleaves label
VocabWords into the vocab). DBOW: the label vector predicts each word
of the document (skip-gram with the label as center). DM: the label
vector joins the context-window average that predicts each word
(CBOW with one extra context slot).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    LabelAwareIterator,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.word2vec import SequenceVectors


@jax.jit
def _infer_step(v, syn1neg, words, negs, alpha):
    """One inference gradient step on a fresh doc vector, tables
    frozen (module-level so it compiles once per doc-length shape —
    syn1neg rides as a traced arg instead of a baked constant)."""

    def loss(v_):
        u_pos = syn1neg[words]                  # [n, D]
        pos = jax.nn.log_sigmoid(u_pos @ v_)
        u_neg = syn1neg[negs]                   # [n, K, D]
        nvalid = (negs != words[:, None]).astype(v_.dtype)
        neg = jnp.sum(
            nvalid * jax.nn.log_sigmoid(-(u_neg @ v_)), axis=-1
        )
        return -jnp.mean(pos + neg)

    return v - alpha * jax.grad(loss)(v)


class ParagraphVectors(SequenceVectors):
    def __init__(self, cache: VocabCache, doc_ids: List[np.ndarray],
                 doc_labels: List[List[str]], label_index: Dict[str, int],
                 algorithm: str = "DBOW", **kw):
        kw.setdefault("sample", 0.0)
        super().__init__(cache, algorithm="SkipGram", **kw)
        self._pv_algorithm = algorithm
        self._doc_ids = doc_ids
        self._doc_labels = doc_labels
        self._label_index = label_index  # label -> row in syn0
        self._n_words = min(label_index.values(), default=len(cache))
        if self.negative > 0:
            # labels must not be drawn as negatives for word pairs
            from deeplearning4j_tpu.nlp.vocab import build_unigram_table

            self._table = build_unigram_table(cache, limit=self._n_words)

    # SequenceVectors hooks -------------------------------------------------

    def _sequences(self):
        return iter(self._doc_ids)

    def _gen_pairs(self, epoch_seed: int):
        """DBOW pairs: (label_row, word) for every word of each doc
        (reference DBOW.learnSequence: iterateSample(label, word))."""
        rng = np.random.RandomState(epoch_seed)
        centers, contexts = [], []
        for ids, labels in zip(self._doc_ids, self._doc_labels):
            if len(ids) == 0:
                continue
            for lab in labels:
                row = self._label_index[lab]
                centers.append(np.full(len(ids), row, np.int32))
                contexts.append(np.asarray(ids, np.int32))
        if not centers:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        c = np.concatenate(centers)
        o = np.concatenate(contexts)
        perm = rng.permutation(len(c))
        return c[perm], o[perm]

    def _gen_cbow(self, epoch_seed: int):
        """DM items: window context + label row predict the center
        word (reference DM.java)."""
        rng = np.random.RandomState(epoch_seed)
        W = self.window
        offsets = [o for o in range(-W, W + 1) if o != 0]
        t_list, c_list, m_list = [], [], []
        for ids, labels in zip(self._doc_ids, self._doc_labels):
            ids = np.asarray(ids, np.int64)
            n = len(ids)
            if n < 2 or not labels:
                continue
            b = rng.randint(1, W + 1, n)
            padded = np.pad(ids, (W, W))
            pos = np.arange(n)
            base_cols, base_masks = [], []
            for off in offsets:
                base_cols.append(padded[W + off:W + off + n])
                base_masks.append(
                    (pos + off >= 0) & (pos + off < n) & (np.abs(off) <= b)
                )
            # one training example per label: each label row joins the
            # context window (reference DM trains every sequence label)
            for lab in labels:
                row = self._label_index[lab]
                cols = base_cols + [np.full(n, row, np.int64)]
                masks = base_masks + [np.ones(n, bool)]
                ctx = np.stack(cols, 1).astype(np.int32)
                cm = np.stack(masks, 1)
                t_list.append(ids.astype(np.int32))
                c_list.append(ctx)
                m_list.append(cm.astype(np.float32))
        if not t_list:
            z = np.zeros((0, 2 * W + 1), np.int32)
            return np.zeros(0, np.int32), z, z.astype(np.float32)
        t = np.concatenate(t_list)
        c = np.concatenate(c_list)
        m = np.concatenate(m_list)
        perm = rng.permutation(len(t))
        return t[perm], c[perm], m[perm]

    def fit(self) -> None:
        # route DBOW through pair training, DM through cbow training
        self.algorithm = "CBOW" if self._pv_algorithm == "DM" else "SkipGram"
        super().fit()

    # query -----------------------------------------------------------------

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        """Word-level query: label rows are excluded."""
        i = self.cache.index_of(word)
        if i < 0:
            return []
        m = self.lookup.normalized()[:self._n_words]
        sims = m @ m[i]
        sims[i] = -np.inf
        return [self.cache.word_at(int(t)) for t in np.argsort(-sims)[:n]]

    def words_nearest_vec(self, vec: np.ndarray, n: int = 10) -> List[str]:
        m = self.lookup.normalized()[:self._n_words]
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = m @ v
        return [self.cache.word_at(int(t)) for t in np.argsort(-sims)[:n]]

    def get_vector(self, label: str) -> Optional[np.ndarray]:
        row = self._label_index.get(label)
        return None if row is None else np.asarray(self.lookup.syn0[row])

    def infer_vector(self, tokens, epochs: int = 10,
                     learning_rate: float = 0.05,
                     seed: int = 0) -> np.ndarray:
        """Embed an UNSEEN document (reference
        ``ParagraphVectors.inferVector``): gradient-descend a fresh
        doc vector against the frozen word/output tables under the
        DBOW objective — one jitted step per epoch over all of the
        doc's words at once. Requires negative sampling (the training
        default); HS-only models raise — the batched-XLA design
        documents NS as the inference objective."""
        if self.lookup.syn1neg is None:
            raise ValueError(
                "infer_vector needs a negative-sampling model "
                "(negative > 0); this model was trained with "
                "hierarchical softmax only"
            )
        if isinstance(tokens, str):
            tokens = tokens.split()
        ids = np.asarray(
            [
                self.cache.index_of(t) for t in tokens
                if t in self.cache
                and self.cache.index_of(t) < self._n_words
            ],
            np.int32,
        )
        rng = np.random.RandomState(seed)
        v = jnp.asarray(
            (rng.rand(self.layer_size) - 0.5) / self.layer_size,
            jnp.float32,
        )
        if len(ids) == 0:
            return np.asarray(v)
        words = jnp.asarray(ids)
        for e in range(epochs):
            negs = jnp.asarray(self._table[
                rng.randint(0, len(self._table),
                            (len(ids), self.negative))
            ])
            alpha = jnp.float32(
                max(learning_rate * (1 - e / max(epochs, 1)),
                    self.min_learning_rate)
            )
            v = _infer_step(v, self.lookup.syn1neg, words, negs, alpha)
        return np.asarray(v)

    def similarity_to_label(self, a: str, b: str) -> float:
        ra, rb = self._label_index.get(a), self._label_index.get(b)
        if ra is None or rb is None:
            return float("nan")
        m = self.lookup.normalized()
        return float(m[ra] @ m[rb])

    def nearest_labels(self, label: str, n: int = 5) -> List[str]:
        row = self._label_index.get(label)
        if row is None:
            return []
        m = self.lookup.normalized()
        sims = m @ m[row]
        inv = {v: k for k, v in self._label_index.items()}
        order = [
            i for i in np.argsort(-sims)
            if int(i) in inv and int(i) != row
        ]
        return [inv[int(i)] for i in order[:n]]

    class Builder:
        def __init__(self):
            self._kw = {}
            self._min_word_frequency = 1
            self._iterator: Optional[LabelAwareIterator] = None
            self._tokenizer = None
            self._algorithm = "DBOW"

        def min_word_frequency(self, n):
            self._min_word_frequency = n; return self

        def layer_size(self, n): self._kw["layer_size"] = n; return self
        def window_size(self, n): self._kw["window"] = n; return self
        def learning_rate(self, x): self._kw["learning_rate"] = x; return self
        def min_learning_rate(self, x):
            self._kw["min_learning_rate"] = x; return self
        def negative_sample(self, n): self._kw["negative"] = int(n); return self
        def epochs(self, n): self._kw["epochs"] = n; return self
        def batch_size(self, n): self._kw["batch_size"] = n; return self
        def seed(self, n): self._kw["seed"] = n; return self
        def sequence_learning_algorithm(self, a):
            self._algorithm = a; return self
        def iterate(self, it: LabelAwareIterator): self._iterator = it; return self
        def tokenizer_factory(self, tf): self._tokenizer = tf; return self

        def build(self) -> "ParagraphVectors":
            if self._iterator is None:
                raise ValueError("iterate(LabelAwareIterator) is required")
            tf = self._tokenizer or DefaultTokenizerFactory()
            docs = list(self._iterator)
            token_docs = [tf.create(d.content).get_tokens() for d in docs]
            cache = VocabConstructor(
                min_word_frequency=self._min_word_frequency
            ).build_vocab_from_tokens(token_docs)
            # append label rows to the vocab (reference: labels become
            # special VocabWords)
            label_index: Dict[str, int] = {}
            for d in docs:
                for lab in d.labels:
                    if lab not in label_index:
                        vw = VocabWord(f"\x00label:{lab}", 1)
                        cache.add(vw)
                        label_index[lab] = vw.index
            doc_ids = [
                np.asarray(cache.id_stream(t), np.int64) for t in token_docs
            ]
            doc_labels = [d.labels for d in docs]
            return ParagraphVectors(
                cache, doc_ids, doc_labels, label_index,
                algorithm=self._algorithm, **self._kw,
            )
