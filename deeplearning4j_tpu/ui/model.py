"""Stats records + storage SPI (reference
``deeplearning4j-ui-model``: ``StatsListener.java:46`` record content,
``api/storage/StatsStorage``/``Persistable``/``StorageMetaData``/
``StatsStorageRouter`` SPI in ``deeplearning4j-core``).

The reference encodes records with generated SBE codecs
(``ui/stats/sbe/UpdateEncoder.java``); here records are plain dicts
with a stable JSON wire encoding (binary-stable enough for files and
HTTP) — SURVEY.md §2.3 maps SBE → plain JSON/msgpack on purpose."""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class StatsInitializationReport:
    """Once-per-session static info (reference
    ``SbeStatsInitializationReport``): software/hardware/model info."""

    session_id: str
    worker_id: str
    timestamp: float
    software: Dict[str, str] = field(default_factory=dict)
    hardware: Dict[str, str] = field(default_factory=dict)
    model: Dict[str, str] = field(default_factory=dict)

    record_type = "init"

    def encode(self) -> bytes:
        d = asdict(self)
        d["record_type"] = self.record_type
        return json.dumps(d).encode()


@dataclass
class StatsReport:
    """Per-iteration update (reference ``SbeStatsReport`` content per
    ``StatsListener.iterationDone:259``): score, timing, memory,
    per-param histograms/mean-magnitudes/learning rates."""

    session_id: str
    worker_id: str
    timestamp: float
    iteration: int
    score: float
    duration_ms: float = 0.0
    memory: Dict[str, float] = field(default_factory=dict)
    learning_rates: Dict[str, float] = field(default_factory=dict)
    param_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    update_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    param_histograms: Dict[str, dict] = field(default_factory=dict)
    activation_mean_magnitudes: Dict[str, float] = field(
        default_factory=dict)
    minibatches_per_second: float = float("nan")
    examples_per_second: float = float("nan")

    record_type = "update"

    def encode(self) -> bytes:
        d = asdict(self)
        d["record_type"] = self.record_type
        return json.dumps(d).encode()


def decode_record(data: bytes):
    d = json.loads(data.decode())
    rt = d.pop("record_type", "update")
    cls = StatsInitializationReport if rt == "init" else StatsReport
    return cls(**d)


class StatsStorage:
    """Storage SPI (reference ``api/storage/StatsStorage.java``):
    session → worker → records, with attachable listeners that fire on
    new records (the Play UI modules subscribe this way)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._static: Dict[str, Dict[str, StatsInitializationReport]] = {}
        self._updates: Dict[str, Dict[str, List[StatsReport]]] = {}
        self._listeners: List[Callable] = []

    # -- router side ----------------------------------------------------

    def put_static_info(self, rec: StatsInitializationReport) -> None:
        with self._lock:
            self._static.setdefault(rec.session_id, {})[rec.worker_id] = rec
        self._notify("static", rec)

    def put_update(self, rec: StatsReport) -> None:
        with self._lock:
            self._updates.setdefault(rec.session_id, {}).setdefault(
                rec.worker_id, []
            ).append(rec)
        self._notify("update", rec)

    # -- query side -----------------------------------------------------

    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def list_workers(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted(
                set(self._static.get(session_id, {}))
                | set(self._updates.get(session_id, {}))
            )

    def get_static_info(self, session_id: str,
                        worker_id: str) -> Optional[
                            StatsInitializationReport]:
        with self._lock:
            return self._static.get(session_id, {}).get(worker_id)

    def get_all_updates(self, session_id: str,
                        worker_id: str) -> List[StatsReport]:
        with self._lock:
            return list(
                self._updates.get(session_id, {}).get(worker_id, [])
            )

    def get_latest_update(self, session_id: str,
                          worker_id: str) -> Optional[StatsReport]:
        ups = self.get_all_updates(session_id, worker_id)
        return ups[-1] if ups else None

    # -- events ---------------------------------------------------------

    def register_stats_storage_listener(self, fn: Callable) -> None:
        self._listeners.append(fn)

    def _notify(self, kind: str, rec) -> None:
        for fn in list(self._listeners):
            try:
                fn(kind, rec)
            except Exception:  # listener bugs must not kill training
                pass

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """Reference ``InMemoryStatsStorage`` — the base class is already
    in-memory."""


class FileStatsStorage(StatsStorage):
    """Append-only JSONL file persistence (reference
    ``FileStatsStorage`` / ``MapDBStatsStorage`` file mode). Existing
    records are loaded on open; new records appended."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._file_lock = threading.Lock()
        try:
            with open(path, "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = decode_record(line)
                    if isinstance(rec, StatsInitializationReport):
                        super().put_static_info(rec)
                    else:
                        super().put_update(rec)
        except FileNotFoundError:
            pass

    def _append(self, rec) -> None:
        with self._file_lock:
            with open(self._path, "ab") as f:
                f.write(rec.encode() + b"\n")

    def put_static_info(self, rec: StatsInitializationReport) -> None:
        self._append(rec)
        super().put_static_info(rec)

    def put_update(self, rec: StatsReport) -> None:
        self._append(rec)
        super().put_update(rec)


def now_ms() -> float:
    return time.time() * 1000.0
