"""StatsListener (reference ``ui/stats/StatsListener.java:46``,
``iterationDone:259``): per-iteration score, param/update stats,
memory (``:310``), learning rates — routed to a StatsStorage.

TPU note: param stats require device→host syncs, so collection is
gated by ``frequency`` (collect every Nth iteration) and histograms by
``collect_histograms``, mirroring the reference's
``StatsUpdateConfiguration`` knobs."""

from __future__ import annotations

import os
import resource
import time
import uuid
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observability.metrics import (
    MetricsRegistry,
    array_histograms,
    default_registry,
    mean_magnitudes,
)
from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.model import (
    StatsInitializationReport,
    StatsReport,
    StatsStorage,
    now_ms,
)


def _graph_structure_json(model) -> str:
    """Nodes + edges for the model-graph page (reference
    ``FlowListenerModule``/``TrainModule`` model tab): a chain for
    MultiLayerNetwork, vertex_inputs for ComputationGraph."""
    import json

    try:
        conf = model.conf
        if hasattr(conf, "vertex_inputs"):  # ComputationGraph
            nodes = (
                [{"name": n, "type": "input"} for n in conf.inputs]
                + [
                    {
                        "name": n,
                        "type": type(
                            getattr(v, "layer_conf", None) or v
                        ).__name__,
                    }
                    for n, v in conf.vertices.items()
                ]
            )
            edges = [
                {"from": src, "to": name}
                for name, srcs in conf.vertex_inputs.items()
                for src in srcs
            ]
        else:  # MultiLayerNetwork chain
            names = list(getattr(model, "layer_names", []))
            nodes = [{"name": "input", "type": "input"}] + [
                {"name": n, "type": type(l).__name__}
                for n, l in zip(names, conf.layers)
            ]
            chain = ["input"] + names
            edges = [
                {"from": a, "to": b} for a, b in zip(chain, chain[1:])
            ]
        return json.dumps({"nodes": nodes, "edges": edges})
    except Exception:
        return "{}"


# canonical implementations live in observability/metrics.py (one
# copy of "summarize this param tree" for every consumer); the old
# private names stay importable
_mean_magnitudes = mean_magnitudes
_histograms = array_histograms


class StatsListener(IterationListener):
    """Collects and routes training statistics (reference
    ``StatsListener.java``)."""

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 collect_histograms: bool = False,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker-0",
                 registry: Optional[MetricsRegistry] = None,
                 collect_param_stats: bool = True,
                 defer_score_read: bool = True):
        """``collect_param_stats=False`` drops the per-sample param
        reads (mean magnitudes, update deltas, histograms) — those
        ``np.asarray`` calls block until the sampled step completes,
        which serializes the async fit loop's dispatch; without them
        (and with ``defer_score_read``, which publishes the sampled
        score one sampled callback LATE, when its step has already
        retired) the listener forces no per-step device sync at
        all."""
        self.storage = storage
        self.frequency = max(int(frequency), 1)
        self.collect_histograms = collect_histograms
        self.collect_param_stats = collect_param_stats
        self.defer_score_read = defer_score_read
        self._pending_report = None  # (StatsReport, score_ref)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        # shared metrics substrate: the same signals the StatsReport
        # records also land in the registry the UI server exports at
        # /metrics?format=prometheus
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self._score_gauge = self.registry.gauge(
            "training_score", help="latest sampled minibatch score"
        )
        self._iter_gauge = self.registry.gauge(
            "training_iteration", help="latest sampled iteration"
        )
        self._rss_gauge = self.registry.gauge(
            "training_host_rss_mb", help="host max RSS (MB)"
        )
        self._init_sent = False
        self._last_time: Optional[float] = None
        self._prev_params: Optional[dict] = None

    def _send_init(self, model) -> None:
        import jax

        import deeplearning4j_tpu

        n_params = sum(
            int(np.asarray(a).size)
            for lp in model.params.values() for a in lp.values()
        )
        rec = StatsInitializationReport(
            session_id=self.session_id, worker_id=self.worker_id,
            timestamp=now_ms(),
            software={
                "framework": "deeplearning4j_tpu",
                "version": getattr(deeplearning4j_tpu, "__version__", "0"),
                "backend": jax.default_backend(),
            },
            hardware={
                "device_count": str(jax.device_count()),
                "devices": ",".join(
                    d.device_kind for d in jax.devices()
                ),
            },
            model={
                "class": type(model).__name__,
                "layers": ",".join(getattr(model, "layer_names", [])),
                "n_params": str(n_params),
                "graph_json": _graph_structure_json(model),
            },
        )
        self.storage.put_static_info(rec)
        self._init_sent = True

    def iteration_done(self, model, iteration: int) -> None:
        if not self._init_sent:
            self._send_init(model)
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        duration_ms = (
            (now - self._last_time) * 1000.0 / self.frequency
            if self._last_time is not None else 0.0
        )
        self._last_time = now
        rec = self._build_report(model, iteration, duration_ms)
        score_ref = getattr(model, "_last_score", None)
        if self.defer_score_read:
            # publish the PREVIOUS sampled report now (its score ref
            # completed long ago — reading it is a copy, not a
            # dispatch stall), park this one until the next sample
            # or flush()/on_epoch_end
            pending = self._pending_report
            self._pending_report = (rec, score_ref)
            if pending is not None:
                self._publish(*pending)
        else:
            self._publish(rec, score_ref)

    def chunk_done(self, model, it0: int, k: int, metrics) -> None:
        """Megastep cadence: at most ONE report per fused K-step
        chunk (when a sampling boundary falls inside it). The chunk's
        scores arrive already host-side from the driver's single
        readback, so the report publishes immediately — no deferred
        score read, no extra device sync; the blocking param-stat
        reads stay gated by ``collect_param_stats`` as per-step."""
        if not self._init_sent:
            self._send_init(model)
        if (it0 + k) // self.frequency == it0 // self.frequency:
            return  # no sampling boundary inside this chunk
        now = time.perf_counter()
        duration_ms = (
            (now - self._last_time) * 1000.0 / k
            if self._last_time is not None else 0.0
        )
        self._last_time = now
        rec = self._build_report(model, it0 + k, duration_ms)
        scores = metrics.get("scores") if hasattr(metrics, "get") \
            else None
        score = (
            float(scores[-1])
            if scores is not None and len(scores) else float("nan")
        )
        self._publish(rec, score)

    def _build_report(self, model, iteration: int,
                      duration_ms: float) -> "StatsReport":
        lrs = {}
        for i, layer in enumerate(getattr(model.conf, "layers", [])):
            lrs[getattr(layer, "name", "") or str(i)] = float(
                getattr(layer, "learning_rate", 0.0)
            )
        params = model.params
        update_mags = {}
        param_mags = {}
        histograms = {}
        if self.collect_param_stats:
            # these np.asarray reads block until the sampled step
            # completes — the price of param introspection (they must
            # run before the next dispatch donates these buffers)
            if self._prev_params is not None:
                for lname, lp in params.items():
                    for pname, arr in lp.items():
                        prev = self._prev_params[lname][pname]
                        update_mags[f"{lname}_{pname}"] = float(
                            np.mean(np.abs(np.asarray(arr) - prev))
                        )
            self._prev_params = {
                ln: {pn: np.asarray(a) for pn, a in lp.items()}
                for ln, lp in params.items()
            }
            param_mags = _mean_magnitudes(params)
            if self.collect_histograms:
                histograms = _histograms(params)
        maxrss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        self._iter_gauge.set(iteration)
        self._rss_gauge.set(maxrss_kb / 1024.0)
        rec = StatsReport(
            session_id=self.session_id, worker_id=self.worker_id,
            timestamp=now_ms(), iteration=iteration,
            score=float("nan"),  # filled at publish time
            duration_ms=duration_ms,
            memory={
                "host_rss_mb": maxrss_kb / 1024.0,
                "pid": float(os.getpid()),
            },
            learning_rates=lrs,
            param_mean_magnitudes=param_mags,
            update_mean_magnitudes=update_mags,
            param_histograms=histograms,
        )
        return rec

    def _publish(self, rec, score_ref) -> None:
        try:
            score = float(score_ref)
        except Exception:
            score = float("nan")
        rec.score = score
        self._score_gauge.set(score)
        self.storage.put_update(rec)

    def flush(self) -> None:
        """Publish the pending deferred report (epoch end / end of
        fit)."""
        pending, self._pending_report = self._pending_report, None
        if pending is not None:
            self._publish(*pending)

    def on_epoch_end(self, model) -> None:
        self.flush()


class J7StatsListener(StatsListener):
    """Compatibility alias (reference ``J7StatsListener`` — a Java-7
    safe variant; no behavioral difference here)."""
