"""Observability / training UI (reference
``deeplearning4j-ui-parent`` — SURVEY.md §2.9): StatsListener →
StatsStorage → browser UI, with a remote HTTP router."""

from deeplearning4j_tpu.ui.model import (
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsInitializationReport,
    StatsReport,
    StatsStorage,
    decode_record,
)
from deeplearning4j_tpu.ui.server import (
    RemoteUIStatsStorageRouter,
    UIServer,
)
from deeplearning4j_tpu.ui.stats_listener import (
    J7StatsListener,
    StatsListener,
)
from deeplearning4j_tpu.ui.conv_listener import (
    ConvolutionalIterationListener,
)

__all__ = [
    "FileStatsStorage", "InMemoryStatsStorage",
    "StatsInitializationReport", "StatsReport", "StatsStorage",
    "decode_record", "RemoteUIStatsStorageRouter", "UIServer",
    "J7StatsListener", "StatsListener",
    "ConvolutionalIterationListener",
]
