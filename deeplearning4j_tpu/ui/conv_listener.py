"""Convolutional activation visualization (reference
``ConvolutionalIterationListener`` + ``ConvolutionalListenerModule`` —
renders each conv layer's activation maps for one example as an image
grid in the training UI).

The listener re-runs the forward pass on the first example of the
net's last minibatch every ``frequency`` iterations, tiles each conv
layer's [C, H, W] activations into one grayscale grid, PNG-encodes it
(PIL) and hands it to the UIServer, which serves it at
``/train/activations``.
"""

from __future__ import annotations

import base64
import io
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener


def _tile_grid(act: np.ndarray, pad: int = 1) -> np.ndarray:
    """[C, H, W] -> one [gh*H', gw*W'] uint8 grid, channels tiled
    near-square, each map min-max normalized."""
    c, h, w = act.shape
    gw = int(np.ceil(np.sqrt(c)))
    gh = int(np.ceil(c / gw))
    out = np.zeros((gh * (h + pad) + pad, gw * (w + pad) + pad),
                   np.uint8)
    for i in range(c):
        a = act[i]
        lo, hi = float(a.min()), float(a.max())
        norm = (a - lo) / (hi - lo) if hi > lo else np.zeros_like(a)
        r, col = divmod(i, gw)
        y = pad + r * (h + pad)
        x = pad + col * (w + pad)
        out[y:y + h, x:x + w] = (norm * 255).astype(np.uint8)
    return out


def _png_b64(grid: np.ndarray) -> str:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(grid, mode="L").save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode()


class ConvolutionalIterationListener(IterationListener):
    """Every ``frequency`` iterations, publish conv activation grids
    for one example to the UI server (reference
    ``ConvolutionalIterationListener.java``)."""

    supports_batched_iterations = True  # grids are per-snapshot anyway
    needs_last_features = True  # nets snapshot the batch for us

    def __init__(self, ui_server=None, frequency: int = 10):
        self.ui_server = ui_server
        self.frequency = max(int(frequency), 1)
        self.last_grids: Dict[str, str] = {}

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        x = getattr(model, "_last_features", None)
        if x is None:
            return
        x1 = np.asarray(x)[:1]
        try:
            acts = model.feed_forward(x1)
        except Exception:
            return
        grids: Dict[str, str] = {}
        names = getattr(model, "layer_names", [])
        for name, act in zip(names, acts):
            a = np.asarray(act)
            if a.ndim == 4:  # [1, C, H, W] conv activation
                grids[str(name)] = _png_b64(_tile_grid(a[0]))
        if grids:
            self.last_grids = grids
            if self.ui_server is not None:
                self.ui_server.set_activations(grids)
