"""UI component DSL (reference ``deeplearning4j-ui-components``: Java
bean components — ChartLine, ChartScatter, ChartHistogram,
ComponentTable, ComponentText, ComponentDiv — serialized to JSON and
rendered by TypeScript in the browser).

Here the beans are dataclasses with the same JSON round-trip contract
plus a dependency-free ``render_html()`` that emits self-contained
SVG/HTML — the renderer half of the reference's TypeScript, inline.
"""

from __future__ import annotations

import html
import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Type

_REGISTRY: Dict[str, Type] = {}


def _register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


def component_from_json(s: str):
    d = json.loads(s) if isinstance(s, str) else s
    return _from_dict(d)


def _from_dict(d: dict):
    kind = d.pop("component_type")
    cls = _REGISTRY[kind]
    if cls is ComponentDiv:
        d["children"] = [_from_dict(c) for c in d.get("children", [])]
    return cls(**d)


class _Component:
    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def to_dict(self) -> dict:
        d = asdict(self)
        d["component_type"] = type(self).__name__
        return d

    def render_html(self) -> str:
        raise NotImplementedError


def _svg_axes(width, height, pad, xmin, xmax, ymin, ymax, title):
    parts = []
    if title:
        parts.append(
            f'<text x="{width // 2}" y="14" text-anchor="middle" '
            f'font-size="12">{html.escape(title)}</text>'
        )
    parts.append(
        f'<text x="4" y="{height - 4}" font-size="9">'
        f"{xmin:.3g}..{xmax:.3g}</text>"
    )
    parts.append(
        f'<text x="4" y="{pad + 8}" font-size="9">{ymax:.3g}</text>'
    )
    parts.append(
        f'<text x="4" y="{height - pad}" font-size="9">{ymin:.3g}</text>'
    )
    return parts


@_register
@dataclass
class ChartLine(_Component):
    """Multi-series line chart (reference ``ChartLine.java``)."""

    title: str = ""
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    width: int = 640
    height: int = 300

    COLORS = ("#06c", "#c33", "#2a2", "#a3c", "#f80", "#088")

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        self.series_names.append(name)
        self.x.append([float(v) for v in x])
        self.y.append([float(v) for v in y])
        return self

    def render_html(self) -> str:
        pad = 24
        allx = [v for s in self.x for v in s] or [0.0, 1.0]
        ally = [v for s in self.y for v in s] or [0.0, 1.0]
        xmin, xmax = min(allx), max(allx)
        ymin, ymax = min(ally), max(ally)
        xr = (xmax - xmin) or 1.0
        yr = (ymax - ymin) or 1.0
        parts = [
            f'<svg width="{self.width}" height="{self.height}" '
            f'xmlns="http://www.w3.org/2000/svg">'
        ]
        parts += _svg_axes(self.width, self.height, pad, xmin, xmax,
                           ymin, ymax, self.title)
        for si, (xs, ys) in enumerate(zip(self.x, self.y)):
            pts = " ".join(
                f"{pad + (x - xmin) / xr * (self.width - 2 * pad):.1f},"
                f"{self.height - pad - (y - ymin) / yr * (self.height - 2 * pad):.1f}"
                for x, y in zip(xs, ys)
            )
            color = self.COLORS[si % len(self.COLORS)]
            parts.append(
                f'<polyline fill="none" stroke="{color}" '
                f'stroke-width="1.5" points="{pts}"/>'
            )
        parts.append("</svg>")
        return "".join(parts)


@_register
@dataclass
class ChartScatter(_Component):
    """Scatter chart (reference ``ChartScatter.java``)."""

    title: str = ""
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    width: int = 640
    height: int = 300

    def add_series(self, name, x, y) -> "ChartScatter":
        self.series_names.append(name)
        self.x.append([float(v) for v in x])
        self.y.append([float(v) for v in y])
        return self

    def render_html(self) -> str:
        pad = 24
        allx = [v for s in self.x for v in s] or [0.0, 1.0]
        ally = [v for s in self.y for v in s] or [0.0, 1.0]
        xmin, xmax = min(allx), max(allx)
        ymin, ymax = min(ally), max(ally)
        xr = (xmax - xmin) or 1.0
        yr = (ymax - ymin) or 1.0
        parts = [
            f'<svg width="{self.width}" height="{self.height}" '
            f'xmlns="http://www.w3.org/2000/svg">'
        ]
        parts += _svg_axes(self.width, self.height, pad, xmin, xmax,
                           ymin, ymax, self.title)
        for si, (xs, ys) in enumerate(zip(self.x, self.y)):
            color = ChartLine.COLORS[si % len(ChartLine.COLORS)]
            for x, y in zip(xs, ys):
                cx = pad + (x - xmin) / xr * (self.width - 2 * pad)
                cy = (
                    self.height - pad
                    - (y - ymin) / yr * (self.height - 2 * pad)
                )
                parts.append(
                    f'<circle cx="{cx:.1f}" cy="{cy:.1f}" r="2.5" '
                    f'fill="{color}"/>'
                )
        parts.append("</svg>")
        return "".join(parts)


@_register
@dataclass
class ChartHistogram(_Component):
    """Histogram chart (reference ``ChartHistogram.java``): bins as
    (lower, upper, value) triples."""

    title: str = ""
    lower: List[float] = field(default_factory=list)
    upper: List[float] = field(default_factory=list)
    values: List[float] = field(default_factory=list)
    width: int = 640
    height: int = 300

    def add_bin(self, lower: float, upper: float,
                value: float) -> "ChartHistogram":
        self.lower.append(float(lower))
        self.upper.append(float(upper))
        self.values.append(float(value))
        return self

    def render_html(self) -> str:
        pad = 24
        if not self.values:
            return (
                f'<svg width="{self.width}" height="{self.height}"/>'
            )
        xmin, xmax = min(self.lower), max(self.upper)
        vmax = max(self.values) or 1.0
        xr = (xmax - xmin) or 1.0
        parts = [
            f'<svg width="{self.width}" height="{self.height}" '
            f'xmlns="http://www.w3.org/2000/svg">'
        ]
        parts += _svg_axes(self.width, self.height, pad, xmin, xmax,
                           0.0, vmax, self.title)
        for lo, up, v in zip(self.lower, self.upper, self.values):
            x0 = pad + (lo - xmin) / xr * (self.width - 2 * pad)
            x1 = pad + (up - xmin) / xr * (self.width - 2 * pad)
            h = (v / vmax) * (self.height - 2 * pad)
            parts.append(
                f'<rect x="{x0:.1f}" y="{self.height - pad - h:.1f}" '
                f'width="{max(x1 - x0 - 1, 1):.1f}" height="{h:.1f}" '
                f'fill="#06c"/>'
            )
        parts.append("</svg>")
        return "".join(parts)


@_register
@dataclass
class ComponentTable(_Component):
    """Table (reference ``ComponentTable.java``)."""

    header: List[str] = field(default_factory=list)
    content: List[List[str]] = field(default_factory=list)

    def render_html(self) -> str:
        rows = []
        if self.header:
            rows.append(
                "<tr>" + "".join(
                    f"<th>{html.escape(str(h))}</th>" for h in self.header
                ) + "</tr>"
            )
        for row in self.content:
            rows.append(
                "<tr>" + "".join(
                    f"<td>{html.escape(str(c))}</td>" for c in row
                ) + "</tr>"
            )
        return (
            '<table border="1" style="border-collapse:collapse">'
            + "".join(rows) + "</table>"
        )


@_register
@dataclass
class ComponentText(_Component):
    """Styled text (reference ``ComponentText.java``)."""

    text: str = ""
    font_size: int = 12
    color: str = "#222"

    def render_html(self) -> str:
        return (
            f'<span style="font-size:{self.font_size}px;'
            f'color:{html.escape(self.color)}">'
            f"{html.escape(self.text)}</span>"
        )


@_register
@dataclass
class ComponentDiv(_Component):
    """Container (reference ``ComponentDiv.java``)."""

    children: List = field(default_factory=list)
    style: str = ""

    def to_dict(self) -> dict:
        return {
            "component_type": "ComponentDiv",
            "style": self.style,
            "children": [c.to_dict() for c in self.children],
        }

    def render_html(self) -> str:
        inner = "".join(c.render_html() for c in self.children)
        style = (
            f' style="{html.escape(self.style)}"' if self.style else ""
        )
        return f"<div{style}>{inner}</div>"


def render_page(component, title: str = "dl4j-tpu components") -> str:
    """Standalone HTML page around one component tree (reference:
    the component-renderer HTML scaffold)."""
    return (
        "<!DOCTYPE html><html><head><title>"
        + html.escape(title)
        + '</title></head><body style="font-family:sans-serif">'
        + component.render_html()
        + "</body></html>"
    )
