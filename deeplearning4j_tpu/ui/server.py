"""Browser training UI (reference ``deeplearning4j-play``:
``PlayUIServer.java:48`` — port 9000, overridable; TrainModule
overview page; ``RemoteReceiverModule`` accepting remote-posted stats;
``RemoteUIStatsStorageRouter`` posting them over HTTP).

The Play framework is replaced by a stdlib ``http.server`` thread:
JSON endpoints + one self-contained overview page (inline SVG chart,
no external assets)."""

from __future__ import annotations

import json
import os
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.model import (
    StatsStorage,
    decode_record,
    StatsInitializationReport,
)

DEFAULT_PORT = 9000
PORT_ENV_VAR = "DL4J_UI_PORT"  # analog of org.deeplearning4j.ui.port
HOST_ENV_VAR = "DL4J_UI_HOST"  # set 0.0.0.0 to expose beyond loopback
MAX_POST_BYTES = 16 * 1024 * 1024  # /remoteReceive body cap

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu Training UI</title>
<style>
 body { font-family: sans-serif; margin: 2em; color: #222; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; }
 .card { border: 1px solid #ccc; border-radius: 6px; padding: 1em;
         margin-bottom: 1em; max-width: 860px; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #ddd; padding: 4px 10px; font-size: 0.9em; }
 svg { background: #fafafa; border: 1px solid #eee; }
</style></head>
<body>
<h1>deeplearning4j_tpu &mdash; Training Overview</h1>
<div class="card"><h2>Score vs. Iteration</h2>
 <svg id="chart" width="820" height="260"></svg></div>
<div class="card"><h2>Model</h2><table id="model"></table></div>
<div class="card"><h2>System</h2><table id="system"></table></div>
<script>
async function refresh() {
  const sessions = await (await fetch('train/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const d = await (await fetch('train/overview?sid=' + sid)).json();
  const svg = document.getElementById('chart');
  const xs = d.iterations, ys = d.scores;
  svg.innerHTML = '';
  if (xs.length > 1) {
    const W = 820, H = 260, P = 34;
    const xmin = Math.min(...xs), xmax = Math.max(...xs);
    const yminRaw = Math.min(...ys), ymaxRaw = Math.max(...ys);
    const ymin = yminRaw, ymax = ymaxRaw === yminRaw ? yminRaw+1 : ymaxRaw;
    const pts = xs.map((x, i) =>
      ((P + (x - xmin) / (xmax - xmin || 1) * (W - 2*P)) + ',' +
       (H - P - (ys[i] - ymin) / (ymax - ymin) * (H - 2*P)))).join(' ');
    svg.innerHTML =
      '<polyline fill="none" stroke="#06c" stroke-width="1.5" points="'
      + pts + '"/>' +
      '<text x="4" y="14" font-size="11">' + ymaxRaw.toFixed(4) +
      '</text><text x="4" y="' + (H - 8) + '" font-size="11">' +
      yminRaw.toFixed(4) + '</text>';
  }
  const fill = (id, obj) => {
    const table = document.getElementById(id);
    table.textContent = '';
    for (const [k, v] of Object.entries(obj || {})) {
      const tr = document.createElement('tr');
      const th = document.createElement('th');
      th.textContent = k;                  // textContent: no HTML
      const td = document.createElement('td');
      td.textContent = String(v);          // injection from records
      tr.append(th, td); table.append(tr);
    }
  };
  fill('model', d.model); fill('system', d.system);
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


def _sanitize(obj):
    """NaN/Inf are not legal JSON and break the browser's JSON.parse —
    map them to null (a diverged score must not blank the UI)."""
    if isinstance(obj, float) and (obj != obj or obj in
                                   (float("inf"), float("-inf"))):
        return None
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _make_handler(server: "UIServer"):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code: int = 200):
            body = json.dumps(_sanitize(obj)).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path in ("/", "/train", "/train/overview.html"):
                body = _PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if url.path == "/train/sessions":
                self._json(server.session_ids())
                return
            if url.path == "/train/overview":
                q = parse_qs(url.query)
                sid = q.get("sid", [None])[0]
                self._json(server.overview(sid))
                return
            self._json({"error": "not found"}, 404)

        def do_POST(self):
            # RemoteReceiverModule analog: accept posted stats records
            if urlparse(self.path).path != "/remoteReceive":
                self._json({"error": "not found"}, 404)
                return
            if not server.remote_enabled:
                self._json({"error": "remote receiver disabled"}, 403)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                self._json({"error": "bad Content-Length"}, 400)
                return
            if length < 0 or length > MAX_POST_BYTES:
                # negative would make rfile.read unbounded
                self._json({"error": "payload too large"}, 413)
                return
            data = self.rfile.read(length)
            try:
                rec = decode_record(data)
            except Exception as e:
                self._json({"error": f"bad record: {e}"}, 400)
                return
            storage = server.primary_storage()
            if isinstance(rec, StatsInitializationReport):
                storage.put_static_info(rec)
            else:
                storage.put_update(rec)
            self._json({"status": "ok"})

    return Handler


class UIServer:
    """Singleton UI server (reference ``UIServer.getInstance()`` /
    ``PlayUIServer``)."""

    _instance: Optional["UIServer"] = None
    _lock = threading.Lock()

    def __init__(self, port: Optional[int] = None,
                 host: Optional[str] = None):
        self.port = port if port is not None else int(
            os.environ.get(PORT_ENV_VAR, DEFAULT_PORT)
        )
        # default loopback-only: the remote receiver accepts
        # unauthenticated POSTs, so exposure must be an explicit choice
        self.host = host if host is not None else os.environ.get(
            HOST_ENV_VAR, "127.0.0.1"
        )
        self._storages: List[StatsStorage] = []
        self.remote_enabled = False
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), _make_handler(self)
        )
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-ui",
        )
        self._thread.start()

    @classmethod
    def get_instance(cls, port: Optional[int] = None) -> "UIServer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(port)
            return cls._instance

    # -- reference API ---------------------------------------------------

    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._storages:
            self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._storages:
            self._storages.remove(storage)

    def enable_remote_listener(self) -> None:
        self.remote_enabled = True
        if not self._storages:
            self._storages.append(StatsStorage())

    def primary_storage(self) -> StatsStorage:
        if not self._storages:
            self._storages.append(StatsStorage())
        return self._storages[0]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        with UIServer._lock:
            if UIServer._instance is self:
                UIServer._instance = None

    # -- data for the page ----------------------------------------------

    def session_ids(self) -> List[str]:
        out = []
        for s in self._storages:
            out += s.list_session_ids()
        return sorted(set(out))

    def overview(self, session_id: Optional[str]) -> dict:
        # honor the requested session across ALL storages before
        # falling back to any storage's latest
        ordered = self._storages
        if session_id is not None:
            exact = [s for s in self._storages
                     if session_id in s.list_session_ids()]
            if exact:
                ordered = exact
        for storage in ordered:
            sids = storage.list_session_ids()
            if not sids:
                continue
            sid = session_id if session_id in sids else sids[-1]
            workers = storage.list_workers(sid)
            if not workers:
                continue
            wid = workers[0]
            updates = storage.get_all_updates(sid, wid)
            static = storage.get_static_info(sid, wid)
            latest = updates[-1] if updates else None
            return {
                "session": sid,
                "iterations": [u.iteration for u in updates],
                "scores": [u.score for u in updates],
                "model": dict(static.model) if static else {},
                "system": {
                    **(dict(static.software) if static else {}),
                    **(dict(static.hardware) if static else {}),
                    **({"host_rss_mb":
                        round(latest.memory.get("host_rss_mb", 0), 1)}
                       if latest else {}),
                },
            }
        return {"session": None, "iterations": [], "scores": [],
                "model": {}, "system": {}}


class RemoteUIStatsStorageRouter:
    """HTTP POST router to a remote UI (reference
    ``RemoteUIStatsStorageRouter.java`` → ``RemoteReceiverModule``).
    Like the reference, transport failures never propagate into the
    training loop: failed posts are counted and, after
    ``max_consecutive_failures``, further sends are dropped with one
    warning (``retry_on_failure`` re-enables on the next success)."""

    def __init__(self, url: str, timeout: float = 5.0,
                 max_consecutive_failures: int = 10,
                 raise_on_error: bool = False):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.timeout = timeout
        self.max_consecutive_failures = max_consecutive_failures
        self.raise_on_error = raise_on_error
        self._failures = 0
        self._disabled_logged = False

    def _post(self, rec) -> None:
        if self._failures >= self.max_consecutive_failures:
            if not self._disabled_logged:
                import logging

                logging.getLogger(__name__).warning(
                    "Remote stats routing disabled after %d consecutive "
                    "failures (target %s)", self._failures, self.url,
                )
                self._disabled_logged = True
            return
        req = urllib.request.Request(
            self.url, data=rec.encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout
            ) as resp:
                resp.read()
            self._failures = 0
        except Exception:
            self._failures += 1
            if self.raise_on_error:
                raise

    def put_static_info(self, rec) -> None:
        self._post(rec)

    def put_update(self, rec) -> None:
        self._post(rec)
