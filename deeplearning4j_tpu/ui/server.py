"""Browser training UI (reference ``deeplearning4j-play``:
``PlayUIServer.java:48`` — port 9000, overridable; ``TrainModule.java:1``
overview/model/system pages; ``HistogramModule`` per-layer param/update
charts; ``TsneModule`` embedding scatter; ``RemoteReceiverModule``
accepting remote-posted stats; ``RemoteUIStatsStorageRouter`` posting
them over HTTP).

The Play framework is replaced by a stdlib ``http.server`` thread:
JSON endpoints + one self-contained page (inline SVG charts, no
external assets) with Overview / Histograms / Model / System / t-SNE
sections fed by the data StatsListener already records."""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.observability.export import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    parse_format_query,
    prometheus_text,
    registry_snapshot,
)
from deeplearning4j_tpu.observability.metrics import default_registry
from deeplearning4j_tpu.serving.envelope import (
    HttpBodyError,
    error_envelope,
    read_request_body,
)
from deeplearning4j_tpu.ui.model import (
    StatsStorage,
    decode_record,
    StatsInitializationReport,
)

logger = logging.getLogger(__name__)

DEFAULT_PORT = 9000
PORT_ENV_VAR = "DL4J_UI_PORT"  # analog of org.deeplearning4j.ui.port
HOST_ENV_VAR = "DL4J_UI_HOST"  # set 0.0.0.0 to expose beyond loopback
MAX_POST_BYTES = 16 * 1024 * 1024  # /remoteReceive body cap

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu Training UI</title>
<style>
 body { font-family: sans-serif; margin: 2em; color: #222; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; }
 .card { border: 1px solid #ccc; border-radius: 6px; padding: 1em;
         margin-bottom: 1em; max-width: 860px; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #ddd; padding: 4px 10px; font-size: 0.9em; }
 svg { background: #fafafa; border: 1px solid #eee; }
 nav a { margin-right: 1em; cursor: pointer; color: #06c;
         text-decoration: underline; }
 select { margin-bottom: 0.6em; }
</style></head>
<body>
<h1>deeplearning4j_tpu &mdash; Training UI</h1>
<nav>
 <a data-tab="overview">Overview</a><a data-tab="histograms">Histograms</a>
 <a data-tab="model">Model</a><a data-tab="graph">Graph</a>
 <a data-tab="system">System</a><a data-tab="activations">Activations</a>
 <a data-tab="tsne">t-SNE</a>
</nav>
<div id="tab-overview">
 <div class="card"><h2>Score vs. Iteration</h2>
  <svg id="chart" width="820" height="260"></svg></div>
</div>
<div id="tab-histograms" style="display:none">
 <div class="card"><h2>Parameter Histogram</h2>
  <select id="hkey"></select>
  <svg id="hist" width="820" height="220"></svg></div>
 <div class="card"><h2>Mean Magnitudes vs. Iteration</h2>
  <svg id="mm" width="820" height="220"></svg>
  <div id="mmlegend" style="font-size:0.85em"></div></div>
</div>
<div id="tab-model" style="display:none">
 <div class="card"><h2>Model</h2><table id="model"></table></div>
 <div class="card"><h2>Layers</h2><table id="layers"></table></div>
</div>
<div id="tab-system" style="display:none">
 <div class="card"><h2>System</h2><table id="system"></table></div>
 <div class="card"><h2>Host RSS (MB) vs. Iteration</h2>
  <svg id="rss" width="820" height="200"></svg></div>
</div>
<div id="tab-graph" style="display:none">
 <div class="card"><h2>Model Graph</h2>
  <svg id="modelgraph" width="820" height="480"></svg></div>
</div>
<div id="tab-activations" style="display:none">
 <div class="card"><h2>Conv Activations (latest snapshot)</h2>
  <div id="actgrids"></div></div>
</div>
<div id="tab-tsne" style="display:none">
 <div class="card"><h2>t-SNE Embedding</h2>
  <svg id="tsneplot" width="820" height="540"></svg>
  <p>POST JSON {"vectors": [[...]], "labels": [...]} to /tsne/post
     to (re)compute.</p></div>
</div>
<script>
const $ = (id) => document.getElementById(id);
document.querySelectorAll('nav a').forEach(a => a.onclick = () => {
  for (const t of ['overview','histograms','model','graph','system',
                   'activations','tsne'])
    $('tab-'+t).style.display = (t === a.dataset.tab) ? '' : 'none';
});
function drawGraph(svg, g) {
  svg.innerHTML = '';
  if (!g.nodes || !g.nodes.length) return;
  // layer nodes into columns by longest-path depth
  const depth = {}, incoming = {};
  g.nodes.forEach(n => { depth[n.name] = 0; incoming[n.name] = []; });
  g.edges.forEach(e => incoming[e.to] && incoming[e.to].push(e.from));
  for (let pass = 0; pass < g.nodes.length; pass++) {
    let changed = false;
    g.edges.forEach(e => {
      if (depth[e.from] !== undefined &&
          depth[e.to] < depth[e.from] + 1) {
        depth[e.to] = depth[e.from] + 1; changed = true;
      }
    });
    if (!changed) break;
  }
  const cols = {};
  g.nodes.forEach(n => {
    (cols[depth[n.name]] = cols[depth[n.name]] || []).push(n);
  });
  const W = +svg.getAttribute('width'), H = +svg.getAttribute('height');
  const nCols = Object.keys(cols).length;
  const pos = {};
  Object.entries(cols).forEach(([d, nodes]) => {
    nodes.forEach((n, i) => {
      pos[n.name] = {
        x: 30 + (W - 160) * (+d) / Math.max(nCols - 1, 1),
        y: 30 + (H - 70) * i / Math.max(nodes.length - 1, 1) *
           (nodes.length > 1 ? 1 : 0) + (nodes.length === 1 ? H/2-35 : 0),
      };
    });
  });
  const NS = 'http://www.w3.org/2000/svg';
  g.edges.forEach(e => {
    const a = pos[e.from], b = pos[e.to];
    if (!a || !b) return;
    const l = document.createElementNS(NS, 'line');
    l.setAttribute('x1', a.x + 110); l.setAttribute('y1', a.y + 15);
    l.setAttribute('x2', b.x); l.setAttribute('y2', b.y + 15);
    l.setAttribute('stroke', '#999');
    svg.append(l);
  });
  g.nodes.forEach(n => {
    const p = pos[n.name];
    const r = document.createElementNS(NS, 'rect');
    r.setAttribute('x', p.x); r.setAttribute('y', p.y);
    r.setAttribute('width', 110); r.setAttribute('height', 30);
    r.setAttribute('rx', 4);
    r.setAttribute('fill', n.type === 'input' ? '#def' : '#fff');
    r.setAttribute('stroke', '#06c');
    const t = document.createElementNS(NS, 'text');
    t.setAttribute('x', p.x + 55); t.setAttribute('y', p.y + 13);
    t.setAttribute('text-anchor', 'middle');
    t.setAttribute('font-size', 10);
    t.textContent = n.name;
    const t2 = document.createElementNS(NS, 'text');
    t2.setAttribute('x', p.x + 55); t2.setAttribute('y', p.y + 25);
    t2.setAttribute('text-anchor', 'middle');
    t2.setAttribute('font-size', 8); t2.setAttribute('fill', '#666');
    t2.textContent = n.type;
    svg.append(r, t, t2);
  });
}
function line(svg, xs, series, colors) {
  // series: [[y...], ...] multi-line chart with shared scale
  svg.innerHTML = '';
  const W = +svg.getAttribute('width'), H = +svg.getAttribute('height');
  const P = 34;
  const all = series.flat().filter(v => v !== null && isFinite(v));
  if (xs.length < 2 || !all.length) return;
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...all), ymaxR = Math.max(...all);
  const ymax = ymaxR === ymin ? ymin + 1 : ymaxR;
  series.forEach((ys, si) => {
    const pts = xs.map((x, i) => [x, ys[i]])
      .filter(([x, y]) => y !== null && isFinite(y))  // skip null gaps
      .map(([x, y]) =>
      ((P + (x - xmin) / (xmax - xmin || 1) * (W - 2*P)) + ',' +
       (H - P - (y - ymin) / (ymax - ymin) * (H - 2*P)))).join(' ');
    const pl = document.createElementNS('http://www.w3.org/2000/svg',
                                        'polyline');
    pl.setAttribute('fill', 'none');
    pl.setAttribute('stroke', colors[si % colors.length]);
    pl.setAttribute('stroke-width', '1.5');
    pl.setAttribute('points', pts);
    svg.append(pl);
  });
  const t1 = document.createElementNS('http://www.w3.org/2000/svg','text');
  t1.setAttribute('x', 4); t1.setAttribute('y', 14);
  t1.setAttribute('font-size', 11); t1.textContent = ymaxR.toFixed(4);
  const t2 = document.createElementNS('http://www.w3.org/2000/svg','text');
  t2.setAttribute('x', 4); t2.setAttribute('y', H - 8);
  t2.setAttribute('font-size', 11); t2.textContent = ymin.toFixed(4);
  svg.append(t1, t2);
}
function bars(svg, h) {
  svg.innerHTML = '';
  if (!h || !h.counts || !h.counts.length) return;
  const W = +svg.getAttribute('width'), H = +svg.getAttribute('height');
  const P = 24, n = h.counts.length, cmax = Math.max(...h.counts) || 1;
  const bw = (W - 2*P) / n;
  h.counts.forEach((c, i) => {
    const r = document.createElementNS('http://www.w3.org/2000/svg','rect');
    const bh = (H - 2*P) * c / cmax;
    r.setAttribute('x', P + i*bw + 1); r.setAttribute('width', bw - 2);
    r.setAttribute('y', H - P - bh); r.setAttribute('height', bh);
    r.setAttribute('fill', '#06c');
    svg.append(r);
  });
  const t = document.createElementNS('http://www.w3.org/2000/svg','text');
  t.setAttribute('x', 4); t.setAttribute('y', H - 6);
  t.setAttribute('font-size', 11);
  t.textContent = h.min.toFixed(3) + ' .. ' + h.max.toFixed(3);
  svg.append(t);
}
const fill = (id, obj) => {
  const table = $(id);
  table.textContent = '';
  for (const [k, v] of Object.entries(obj || {})) {
    const tr = document.createElement('tr');
    const th = document.createElement('th');
    th.textContent = k;                  // textContent: no HTML
    const td = document.createElement('td');
    td.textContent = String(v);          // injection from records
    tr.append(th, td); table.append(tr);
  }
};
const COLORS = ['#06c','#c33','#2a2','#a3c','#f80','#088','#880'];
let histKey = null;
$('hkey').onchange = () => { histKey = $('hkey').value; };
async function refresh() {
  const sessions = await (await fetch('train/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const d = await (await fetch('train/overview?sid=' + sid)).json();
  line($('chart'), d.iterations, [d.scores], COLORS);
  fill('model', d.model); fill('system', d.system);

  const h = await (await fetch('train/histograms?sid=' + sid)).json();
  const keys = Object.keys(h.latest_histograms || {});
  const sel = $('hkey');
  if (sel.options.length !== keys.length) {
    sel.textContent = '';
    keys.forEach(k => {
      const o = document.createElement('option');
      o.value = k; o.textContent = k; sel.append(o);
    });
  }
  if (!histKey || !keys.includes(histKey)) histKey = keys[0];
  if (histKey) { sel.value = histKey; bars($('hist'),
                                          h.latest_histograms[histKey]); }
  const mmKeys = Object.keys(h.param_mean_magnitudes || {});
  line($('mm'), h.iterations,
       mmKeys.map(k => h.param_mean_magnitudes[k]), COLORS);
  $('mmlegend').textContent = mmKeys.map(
    (k, i) => k + ' (' + COLORS[i % COLORS.length] + ')').join('   ');

  const m = await (await fetch('train/model?sid=' + sid)).json();
  const lt = $('layers');
  lt.textContent = '';
  (m.layers || []).forEach(row => {
    const tr = document.createElement('tr');
    row.forEach(v => {
      const td = document.createElement('td');
      td.textContent = String(v); tr.append(td);
    });
    lt.append(tr);
  });

  const s = await (await fetch('train/system?sid=' + sid)).json();
  line($('rss'), s.iterations, [s.rss_mb], COLORS);

  const gr = await (await fetch('train/graph?sid=' + sid)).json();
  drawGraph($('modelgraph'), gr);

  const act = await (await fetch('train/activations')).json();
  const ag = $('actgrids');
  ag.textContent = '';
  for (const [layer, b64] of Object.entries(act.grids || {})) {
    const cap = document.createElement('div');
    cap.textContent = 'layer ' + layer;
    const img = document.createElement('img');
    img.src = 'data:image/png;base64,' + b64;
    img.style.imageRendering = 'pixelated';
    img.style.width = '640px';
    ag.append(cap, img);
  }

  const t = await (await fetch('train/tsne')).json();
  const svg = $('tsneplot');
  svg.innerHTML = '';
  if (t.coords && t.coords.length) {
    const W = 820, H = 540, P = 20;
    const xs = t.coords.map(c => c[0]), ys = t.coords.map(c => c[1]);
    const xmin = Math.min(...xs), xmax = Math.max(...xs) || xmin + 1;
    const ymin = Math.min(...ys), ymax = Math.max(...ys) || ymin + 1;
    t.coords.forEach((c, i) => {
      const g = document.createElementNS('http://www.w3.org/2000/svg',
                                         'circle');
      g.setAttribute('cx', P + (c[0]-xmin)/(xmax-xmin||1)*(W-2*P));
      g.setAttribute('cy', H - P - (c[1]-ymin)/(ymax-ymin||1)*(H-2*P));
      g.setAttribute('r', 3); g.setAttribute('fill', '#06c');
      svg.append(g);
      if (t.labels && t.labels[i] !== undefined) {
        const tx = document.createElementNS(
          'http://www.w3.org/2000/svg', 'text');
        tx.setAttribute('x', +g.getAttribute('cx') + 5);
        tx.setAttribute('y', +g.getAttribute('cy') + 4);
        tx.setAttribute('font-size', 10);
        tx.textContent = String(t.labels[i]);
        svg.append(tx);
      }
    });
  }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


def _sanitize(obj):
    """NaN/Inf are not legal JSON and break the browser's JSON.parse —
    map them to null (a diverged score must not blank the UI)."""
    if isinstance(obj, float) and (obj != obj or obj in
                                   (float("inf"), float("-inf"))):
        return None
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _make_handler(server: "UIServer"):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code: int = 200):
            body = json.dumps(_sanitize(obj)).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path in ("/", "/train", "/train/overview.html"):
                body = _PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if url.path == "/train/sessions":
                self._json(server.session_ids())
                return
            if url.path == "/train/overview":
                q = parse_qs(url.query)
                sid = q.get("sid", [None])[0]
                self._json(server.overview(sid))
                return
            if url.path == "/train/histograms":
                q = parse_qs(url.query)
                self._json(server.histograms(q.get("sid", [None])[0]))
                return
            if url.path == "/train/model":
                q = parse_qs(url.query)
                self._json(server.model_page(q.get("sid", [None])[0]))
                return
            if url.path == "/train/system":
                q = parse_qs(url.query)
                self._json(server.system_page(q.get("sid", [None])[0]))
                return
            if url.path == "/train/tsne":
                self._json(server.tsne_coords())
                return
            if url.path == "/train/graph":
                q = parse_qs(url.query)
                self._json(server.graph_page(q.get("sid", [None])[0]))
                return
            if url.path == "/train/activations":
                self._json(server.activations())
                return
            if url.path == "/debugz":
                try:
                    self._json(server.debug_snapshot())
                except Exception:
                    logger.exception("debugz failed")
                    self._json(error_envelope(
                        "debug_error", 500,
                        "debug snapshot failed; see server log",
                    ), 500)
                return
            if url.path == "/metrics":
                # training-side registry (TelemetryListener /
                # StatsListener publish here): JSON by default,
                # ?format=prometheus for scraping
                _, fmt = parse_format_query(self.path)
                if fmt == "prometheus":
                    body = prometheus_text(server.registry).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(registry_snapshot(server.registry))
                return
            self._json(error_envelope("not_found", 404, "not found"),
                       404)

        def do_POST(self):
            path = urlparse(self.path).path
            if path == "/tsne/post":
                # shared body discipline with the serving tier:
                # 411 no Content-Length, 400 short read, 413 over cap
                try:
                    data = read_request_body(self, MAX_POST_BYTES)
                except HttpBodyError as e:
                    self._json(e.envelope, e.code)
                    return
                try:
                    payload = json.loads(data)
                    n = server.set_tsne_vectors(
                        payload["vectors"], payload.get("labels")
                    )
                except Exception as e:
                    self._json(error_envelope(
                        "bad_payload", 400, f"bad payload: {e}",
                    ), 400)
                    return
                self._json({"status": "ok", "points": n})
                return
            # RemoteReceiverModule analog: accept posted stats records
            if path != "/remoteReceive":
                self._json(error_envelope("not_found", 404,
                                          "not found"), 404)
                return
            if not server.remote_enabled:
                self._json(error_envelope(
                    "remote_disabled", 403, "remote receiver disabled",
                ), 403)
                return
            try:
                data = read_request_body(self, MAX_POST_BYTES)
            except HttpBodyError as e:
                self._json(e.envelope, e.code)
                return
            try:
                rec = decode_record(data)
            except Exception as e:
                self._json(error_envelope(
                    "bad_record", 400, f"bad record: {e}",
                ), 400)
                return
            storage = server.primary_storage()
            if isinstance(rec, StatsInitializationReport):
                storage.put_static_info(rec)
            else:
                storage.put_update(rec)
            self._json({"status": "ok"})

    return Handler


class UIServer:
    """Singleton UI server (reference ``UIServer.getInstance()`` /
    ``PlayUIServer``)."""

    _instance: Optional["UIServer"] = None
    _lock = threading.Lock()

    def __init__(self, port: Optional[int] = None,
                 host: Optional[str] = None):
        self.port = port if port is not None else int(
            os.environ.get(PORT_ENV_VAR, DEFAULT_PORT)
        )
        # default loopback-only: the remote receiver accepts
        # unauthenticated POSTs, so exposure must be an explicit choice
        self.host = host if host is not None else os.environ.get(
            HOST_ENV_VAR, "127.0.0.1"
        )
        self._storages: List[StatsStorage] = []
        self.remote_enabled = False
        # the process-wide training registry this server exports at
        # /metrics (StatsListener / TelemetryListener publish there)
        self.registry = default_registry()
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), _make_handler(self)
        )
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-ui",
        )
        self._thread.start()

    @classmethod
    def get_instance(cls, port: Optional[int] = None) -> "UIServer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(port)
            return cls._instance

    # -- reference API ---------------------------------------------------

    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._storages:
            self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._storages:
            self._storages.remove(storage)

    def enable_remote_listener(self) -> None:
        self.remote_enabled = True
        if not self._storages:
            self._storages.append(StatsStorage())

    def primary_storage(self) -> StatsStorage:
        if not self._storages:
            self._storages.append(StatsStorage())
        return self._storages[0]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        with UIServer._lock:
            if UIServer._instance is self:
                UIServer._instance = None

    def debug_snapshot(self) -> dict:
        """``GET /debugz``: read-only, bounded first-responder page —
        versions, attached sessions, the training-side registry, the
        active profiler state, and the flight-recorder tail (capped at
        ``flightrec.DEBUG_TAIL_LIMIT``)."""
        import jax
        import jaxlib

        from deeplearning4j_tpu import __version__ as pkg_version
        from deeplearning4j_tpu.observability import (
            flightrec,
            profiler,
        )

        out: dict = {
            "versions": {
                "deeplearning4j_tpu": pkg_version,
                "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
            },
            "backend": jax.default_backend(),
            "config": {
                "port": self.port,
                "remote_enabled": self.remote_enabled,
            },
            "sessions": self.session_ids(),
            "metrics": registry_snapshot(self.registry),
        }
        prof = profiler.get_active_profiler()
        if prof is not None:
            out["profiler"] = prof.snapshot()
        rec = flightrec.get_flight_recorder()
        if rec is not None:
            out["flight_recorder"] = {
                "capacity": rec.capacity,
                "last_step": rec.last_step(),
                "tail": flightrec._jsonable(
                    rec.tail(flightrec.DEBUG_TAIL_LIMIT)
                ),
            }
        return out

    # -- data for the page ----------------------------------------------

    def session_ids(self) -> List[str]:
        out = []
        for s in self._storages:
            out += s.list_session_ids()
        return sorted(set(out))

    def overview(self, session_id: Optional[str]) -> dict:
        # honor the requested session across ALL storages before
        # falling back to any storage's latest
        ordered = self._storages
        if session_id is not None:
            exact = [s for s in self._storages
                     if session_id in s.list_session_ids()]
            if exact:
                ordered = exact
        for storage in ordered:
            sids = storage.list_session_ids()
            if not sids:
                continue
            sid = session_id if session_id in sids else sids[-1]
            workers = storage.list_workers(sid)
            if not workers:
                continue
            wid = workers[0]
            updates = storage.get_all_updates(sid, wid)
            static = storage.get_static_info(sid, wid)
            latest = updates[-1] if updates else None
            return {
                "session": sid,
                "iterations": [u.iteration for u in updates],
                "scores": [u.score for u in updates],
                "model": dict(static.model) if static else {},
                "system": {
                    **(dict(static.software) if static else {}),
                    **(dict(static.hardware) if static else {}),
                    **({"host_rss_mb":
                        round(latest.memory.get("host_rss_mb", 0), 1)}
                       if latest else {}),
                },
            }
        return {"session": None, "iterations": [], "scores": [],
                "model": {}, "system": {}}

    def _session_updates(self, session_id: Optional[str]):
        """(static, updates) for the requested/latest session."""
        ordered = self._storages
        if session_id is not None:
            exact = [s for s in self._storages
                     if session_id in s.list_session_ids()]
            if exact:
                ordered = exact
        for storage in ordered:
            sids = storage.list_session_ids()
            if not sids:
                continue
            sid = session_id if session_id in sids else sids[-1]
            workers = storage.list_workers(sid)
            if not workers:
                continue
            wid = workers[0]
            return (
                storage.get_static_info(sid, wid),
                storage.get_all_updates(sid, wid),
            )
        return None, []

    def histograms(self, session_id: Optional[str]) -> dict:
        """HistogramModule analog: latest per-param histograms +
        mean-magnitude series over iterations (reference
        ``HistogramModule.java``)."""
        _, updates = self._session_updates(session_id)
        iters = [u.iteration for u in updates]
        param_mm: dict = {}
        update_mm: dict = {}
        for u in updates:
            for k in u.param_mean_magnitudes:
                param_mm.setdefault(k, [])
            for k in u.update_mean_magnitudes:
                update_mm.setdefault(k, [])
        for u in updates:
            for k in param_mm:
                param_mm[k].append(u.param_mean_magnitudes.get(k))
            for k in update_mm:
                update_mm[k].append(u.update_mean_magnitudes.get(k))
        latest_h = {}
        for u in reversed(updates):
            if u.param_histograms:
                latest_h = u.param_histograms
                break
        return {
            "iterations": iters,
            "param_mean_magnitudes": param_mm,
            "update_mean_magnitudes": update_mm,
            "latest_histograms": latest_h,
        }

    def model_page(self, session_id: Optional[str]) -> dict:
        """TrainModule model-page analog: layer table + latest per-layer
        param magnitudes (reference ``TrainModule.java`` model tab)."""
        static, updates = self._session_updates(session_id)
        latest = updates[-1] if updates else None
        layer_rows = []
        if static is not None:
            names = (static.model.get("layers", "") or "").split(",")
            mm = latest.param_mean_magnitudes if latest else {}
            for name in names:
                if not name:
                    continue
                w = mm.get(f"{name}_W")
                b = mm.get(f"{name}_b")
                layer_rows.append([
                    name,
                    "-" if w is None else f"{w:.6f}",
                    "-" if b is None else f"{b:.6f}",
                ])
            if layer_rows:
                layer_rows.insert(0, ["layer", "mean|W|", "mean|b|"])
        return {
            "model": dict(static.model) if static else {},
            "layers": layer_rows,
        }

    def system_page(self, session_id: Optional[str]) -> dict:
        """TrainModule system-page analog: software/hardware + memory
        over time (reference system tab + ``StatsListener:310``)."""
        static, updates = self._session_updates(session_id)
        return {
            "iterations": [u.iteration for u in updates],
            "rss_mb": [
                u.memory.get("host_rss_mb") for u in updates
            ],
            "duration_ms": [u.duration_ms for u in updates],
            "software": dict(static.software) if static else {},
            "hardware": dict(static.hardware) if static else {},
        }

    def graph_page(self, session_id: Optional[str]) -> dict:
        """Model-graph page data (reference ``FlowListenerModule`` /
        TrainModule model tab): nodes + edges recorded by
        StatsListener's init report."""
        static, _ = self._session_updates(session_id)
        if static is None:
            return {"nodes": [], "edges": []}
        try:
            g = json.loads(static.model.get("graph_json", "{}"))
        except json.JSONDecodeError:
            g = {}
        return {"nodes": g.get("nodes", []),
                "edges": g.get("edges", [])}

    # -- conv activations (reference ConvolutionalListenerModule) --------

    def set_activations(self, grids: dict) -> None:
        """{layer_name: base64 PNG} from a
        ConvolutionalIterationListener."""
        self._activations = dict(grids)

    def activations(self) -> dict:
        return {"grids": getattr(self, "_activations", {})}

    # -- t-SNE module (reference TsneModule.java) ------------------------

    MAX_TSNE_POINTS = 2000
    MAX_TSNE_DIM = 1024

    def set_tsne_vectors(self, vectors, labels=None) -> int:
        """Accept vectors, compute 2-D coords (already-2-D input is
        stored as-is, matching the reference's upload of precomputed
        coordinates)."""
        import numpy as np

        arr = np.asarray(vectors, dtype=np.float32)
        if arr.ndim != 2:
            raise ValueError("vectors must be 2-d [n, d]")
        if arr.shape[0] > self.MAX_TSNE_POINTS:
            raise ValueError(
                f"at most {self.MAX_TSNE_POINTS} points"
            )
        if arr.shape[1] > self.MAX_TSNE_DIM:
            raise ValueError(f"at most {self.MAX_TSNE_DIM} dims")
        if labels is not None and len(labels) != arr.shape[0]:
            raise ValueError("labels length mismatch")
        if arr.shape[1] == 2:
            coords = arr
        else:
            import jax

            from deeplearning4j_tpu.ops.dispatch import cpu_device
            from deeplearning4j_tpu.plot.tsne import Tsne

            n = arr.shape[0]
            perplexity = max(2.0, min(30.0, (n - 1) / 3.0))
            tsne = Tsne(max_iter=250, perplexity=perplexity, seed=12345)
            # host-side analytics: run on the CPU backend so the UI
            # thread never competes with training for the accelerator
            # (and the small-N gradient dynamics stay in full f32)
            cpu = (
                cpu_device() if jax.default_backend() != "cpu" else None
            )
            if cpu is not None:
                with jax.default_device(cpu):
                    coords = tsne.fit(arr)
            else:
                coords = tsne.fit(arr)
        self._tsne = {
            "coords": np.asarray(coords, np.float32).tolist(),
            "labels": list(labels) if labels is not None else None,
        }
        return arr.shape[0]

    def tsne_coords(self) -> dict:
        return getattr(self, "_tsne", {"coords": [], "labels": None})


class RemoteUIStatsStorageRouter:
    """HTTP POST router to a remote UI (reference
    ``RemoteUIStatsStorageRouter.java`` → ``RemoteReceiverModule``).
    Like the reference, transport failures never propagate into the
    training loop: failed posts are counted and, after
    ``max_consecutive_failures``, further sends are dropped with one
    warning (``retry_on_failure`` re-enables on the next success)."""

    def __init__(self, url: str, timeout: float = 5.0,
                 max_consecutive_failures: int = 10,
                 raise_on_error: bool = False):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.timeout = timeout
        self.max_consecutive_failures = max_consecutive_failures
        self.raise_on_error = raise_on_error
        self._failures = 0
        self._disabled_logged = False

    def _post(self, rec) -> None:
        if self._failures >= self.max_consecutive_failures:
            if not self._disabled_logged:
                import logging

                logging.getLogger(__name__).warning(
                    "Remote stats routing disabled after %d consecutive "
                    "failures (target %s)", self._failures, self.url,
                )
                self._disabled_logged = True
            return
        req = urllib.request.Request(
            self.url, data=rec.encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout
            ) as resp:
                resp.read()
            self._failures = 0
        except Exception:
            self._failures += 1
            if self.raise_on_error:
                raise

    def put_static_info(self, rec) -> None:
        self._post(rec)

    def put_update(self, rec) -> None:
        self._post(rec)
