"""Deterministic fault injection.

Every recovery path in this subsystem is exercised in tier-1 CI
without real preemptions: a ``ChaosPolicy`` is a *seeded schedule* of
which calls fail with what exception, and ``FaultyObjectStore`` /
``FlakyIterator`` thread it through the storage SPI and the dataset
iterator SPI. The same seed replays the same failure sequence —
``scripts/run_chaos.sh`` pins it so a red chaos run reproduces
locally bit-for-bit.

Failures are injected BEFORE the wrapped call runs, so a retried
operation observes at-most-once side effects per successful call —
matching real transient faults (connection refused, 503) rather than
torn writes, which the checkpoint layer's CRC manifests cover
separately.
"""

from __future__ import annotations

import os
import random
import signal
from typing import (
    Callable, Dict, IO, Iterator, List, Optional, Sequence, Set,
    Tuple, Union,
)

from deeplearning4j_tpu.cloud.storage import ObjectStore
from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator


class ChaosError(OSError):
    """The default injected fault: an OSError subclass so the default
    retry allowlist (``retry.DEFAULT_RETRY_ON``) catches it, and
    greppable in logs as chaos-injected rather than real."""


class ChaosPolicy:
    """Seeded schedule of call failures, keyed by operation name.

    Two scheduling modes, composable:

    - **explicit**: ``fail_calls={"read": {0, 1}}`` fails the first two
      ``read`` calls (0-based per-op call index) — the classic
      "2 failures then succeed" retry test;
    - **random**: ``failure_rate=0.2, seed=1337`` fails each call with
      probability 0.2 from a private ``random.Random(seed)`` — same
      seed, same schedule, regardless of wall clock.

    ``exception`` may be an exception class or a factory
    ``(op, index) -> Exception``. ``max_failures`` bounds total
    injections so a high rate cannot starve a bounded-retry caller
    forever.
    """

    def __init__(
        self,
        seed: int = 0,
        failure_rate: float = 0.0,
        fail_calls: Optional[Dict[str, Set[int]]] = None,
        exception: Union[type, Callable] = ChaosError,
        max_failures: Optional[int] = None,
    ):
        if not 0.0 <= failure_rate <= 1.0:
            raise ValueError("failure_rate must be in [0, 1]")
        self.seed = seed
        self.failure_rate = failure_rate
        self.fail_calls = {
            op: set(ix) for op, ix in (fail_calls or {}).items()
        }
        self.exception = exception
        self.max_failures = max_failures
        self._rng = random.Random(seed)
        self.calls: Dict[str, int] = {}       # per-op call counts
        self.injected: List[tuple] = []       # (op, index) of each fault

    @classmethod
    def fail_first(cls, n: int, ops: Iterator[str] = ("read",),
                   exception: Union[type, Callable] = ChaosError,
                   ) -> "ChaosPolicy":
        """Fail the first ``n`` calls of each named op, then succeed."""
        return cls(
            fail_calls={op: set(range(n)) for op in ops},
            exception=exception,
        )

    def _make_exception(self, op: str, index: int) -> BaseException:
        if isinstance(self.exception, type):
            return self.exception(f"chaos: injected fault in {op!r} "
                                  f"(call #{index})")
        return self.exception(op, index)

    def check(self, op: str) -> None:
        """Account one call of ``op``; raise its scheduled fault if
        any. Call this at the TOP of every instrumented operation."""
        index = self.calls.get(op, 0)
        self.calls[op] = index + 1
        if (self.max_failures is not None
                and len(self.injected) >= self.max_failures):
            return
        scheduled = index in self.fail_calls.get(op, ())
        if not scheduled and self.failure_rate > 0.0:
            scheduled = self._rng.random() < self.failure_rate
        if scheduled:
            self.injected.append((op, index))
            raise self._make_exception(op, index)


class FaultyObjectStore(ObjectStore):
    """ObjectStore decorator that consults a ChaosPolicy before every
    delegated operation. Stack under ``RetryingObjectStore`` to prove
    the retry budget end-to-end."""

    def __init__(self, inner: ObjectStore, policy: ChaosPolicy):
        self.inner = inner
        self.policy = policy

    def keys(self, prefix: str = "") -> List[str]:
        self.policy.check("keys")
        return self.inner.keys(prefix)

    def open(self, key: str) -> IO[bytes]:
        self.policy.check("open")
        return self.inner.open(key)

    def read(self, key: str) -> bytes:
        self.policy.check("read")
        return self.inner.read(key)

    def write(self, key: str, data: bytes) -> None:
        self.policy.check("write")
        self.inner.write(key, data)

    def download(self, key: str, to_path) -> None:
        self.policy.check("download")
        self.inner.download(key, to_path)

    def upload(self, from_path, key: str) -> None:
        self.policy.check("upload")
        self.inner.upload(from_path, key)


POISON_KINDS = ("wrong_shape", "wrong_dtype", "label_range",
                "huge_values")


class PoisonIterator(DataSetIterator):
    """DataSetIterator decorator that CORRUPTS scheduled batches
    instead of failing them — the bad-data analog of
    :class:`FlakyIterator`, feeding the validating pipeline's
    quarantine path (``datasets/validate.py``).

    Two scheduling modes, composable (mirroring ``ChaosPolicy``):

    - **explicit**: ``poison={3: "wrong_dtype", 7: "label_range"}``
      corrupts exactly those 0-based batch offsets with the named
      corruption kind;
    - **random**: ``poison_rate=0.2, seed=1337`` corrupts each batch
      with probability 0.2, kind drawn from ``POISON_KINDS`` — same
      seed, same storm.

    Corruption kinds (each trips a distinct validator reason code):

    - ``wrong_shape``   — features lose their last column;
    - ``wrong_dtype``   — features become strings (the corrupt-CSV
      symptom: a header row or sentinel text lands in the payload);
    - ``label_range``   — one label row becomes 7.0 (outside any
      normalized/one-hot range);
    - ``huge_values``   — one feature element becomes 1e12
      (finite but absurd: the magnitude check's prey).

    The inner batch is COPIED before corruption, so a quarantined
    offset replayed from the store differs from the pristine source
    batch — never the other way around. ``poisoned`` records
    ``(offset, kind)`` of every corruption for exact-count asserts.
    """

    def __init__(self, inner: DataSetIterator, seed: int = 0,
                 poison_rate: float = 0.0,
                 poison: Optional[Dict[int, str]] = None):
        if not 0.0 <= poison_rate <= 1.0:
            raise ValueError("poison_rate must be in [0, 1]")
        for kind in (poison or {}).values():
            if kind not in POISON_KINDS:
                raise ValueError(
                    f"unknown poison kind {kind!r}; pick from "
                    f"{POISON_KINDS}"
                )
        self.inner = inner
        self.poison = dict(poison or {})
        self.poison_rate = poison_rate
        self.seed = seed
        self._rng = random.Random(seed)
        self._offset = 0
        self.poisoned: List[tuple] = []       # (offset, kind)

    def _corrupt(self, ds: DataSet, kind: str) -> DataSet:
        import copy

        import numpy as np

        ds = copy.deepcopy(ds)
        feats = ds.features
        labels = ds.labels
        if kind == "wrong_shape":
            ds.features = np.asarray(feats)[..., :-1]
        elif kind == "wrong_dtype":
            ds.features = np.asarray(feats).astype("U8")
        elif kind == "label_range":
            labels = np.array(labels, copy=True)
            labels[0, ...] = 7.0
            ds.labels = labels
        elif kind == "huge_values":
            feats = np.array(feats, copy=True)
            flat = feats.reshape(-1)
            flat[0] = 1e12
            ds.features = flat.reshape(feats.shape)
        return ds

    def next(self) -> DataSet:
        ds = self.inner.next()
        at = self._offset
        self._offset += 1
        kind = self.poison.get(at)
        if kind is None and self.poison_rate > 0.0:
            if self._rng.random() < self.poison_rate:
                kind = POISON_KINDS[
                    self._rng.randrange(len(POISON_KINDS))
                ]
        if kind is None:
            return ds
        self.poisoned.append((at, kind))
        return self._corrupt(ds, kind)

    def has_next(self) -> bool:
        return self.inner.has_next()

    def reset(self) -> None:
        self.inner.reset()
        self._offset = 0
        self._rng = random.Random(self.seed)  # same seed, same storm

    def batch(self) -> int:
        return self.inner.batch()

    def total_examples(self) -> int:
        return self.inner.total_examples()


class ControlChannelChaos:
    """Control-plane transport decorator: host-granularity network
    faults for the cross-host control plane
    (``parallel/control_plane.py``). Wraps any transport exposing
    ``request(payload, timeout_s=)`` and injects, deterministically:

    - **drops** — a :class:`ChaosPolicy` consulted per request, keyed
      by the protocol op (``join`` / ``renew`` / ``barrier`` / ...):
      a scheduled call raises :class:`ChaosError` (an ``OSError``, so
      the agent's bounded retry treats it exactly like a dropped
      heartbeat frame);
    - **delays** — ``delay={op: seconds}`` sleeps before delegating
      (injectable ``sleep``), the slow-network half of the storm;
    - **partition** — ``partition=(start, end)`` fails EVERY request
      whose global index falls in ``[start, end)`` regardless of op:
      the coordinator is unreachable, retries exhaust, and the agent
      concludes :class:`CoordinatorLostException`.

    ``requests`` records ``(op, index)`` of every attempt for exact
    asserts; the same seed replays the same storm."""

    def __init__(self, inner, policy: Optional[ChaosPolicy] = None,
                 *, delay: Optional[Dict[str, float]] = None,
                 partition: Optional[Tuple[int, int]] = None,
                 sleep: Callable[[float], None] = None):
        import time

        self.inner = inner
        self.policy = policy
        self.delay = dict(delay or {})
        self.partition = partition
        self.sleep = sleep if sleep is not None else time.sleep
        self.total = 0
        self.requests: List[tuple] = []

    def request(self, payload: dict, timeout_s=None) -> dict:
        op = str(payload.get("op"))
        index = self.total
        self.total += 1
        self.requests.append((op, index))
        if self.partition is not None:
            lo, hi = self.partition
            if lo <= index < hi:
                raise ChaosError(
                    f"chaos: control channel partitioned "
                    f"(request #{index}, op {op!r})")
        if self.policy is not None:
            self.policy.check(op)
        d = self.delay.get(op)
        if d:
            self.sleep(d)
        return self.inner.request(payload, timeout_s=timeout_s)


class KillAtStep:
    """Host-granularity chaos: an iteration listener that SIGKILLs its
    OWN process the moment ``iteration_done`` reaches ``at_step`` —
    the kill-rank-N-at-step-K storm. SIGKILL, not an exception: the
    point is that nothing gets to clean up, exactly like a real host
    loss. Arm it on rank N only; every other rank trains normally
    until the control plane declares the death."""

    def __init__(self, at_step: int, sig: int = signal.SIGKILL):
        self.at_step = int(at_step)
        self.sig = sig

    def iteration_done(self, model, iteration: int) -> None:
        if int(iteration) >= self.at_step:
            os.kill(os.getpid(), self.sig)


class FlakyIterator(DataSetIterator):
    """DataSetIterator decorator whose ``next()`` consults a
    ChaosPolicy before delegating — the deterministic stand-in for a
    flaky shard fetch. Because the fault fires before the inner cursor
    advances, a retry re-fetches the SAME batch: recovery preserves
    the data order, which the kill/resume equivalence tests rely on."""

    def __init__(self, inner: DataSetIterator, policy: ChaosPolicy):
        self.inner = inner
        self.policy = policy

    def next(self) -> DataSet:
        self.policy.check("next")
        return self.inner.next()

    def has_next(self) -> bool:
        return self.inner.has_next()

    def reset(self) -> None:
        self.inner.reset()

    def batch(self) -> int:
        return self.inner.batch()

    def total_examples(self) -> int:
        return self.inner.total_examples()
