"""Per-request deadlines.

A ``Deadline`` is a wall-budget stamped at admission and threaded
through every stage of a request (queue wait, transform, predict) so
the *total* latency is bounded — per-stage timeouts compose badly:
three stages each "within budget" can still triple the user's wait.
The clock is injectable for deterministic tests, and expiry surfaces
as ``DeadlineExceededException`` carrying elapsed/budget so callers
(the serving tier's 504 envelope) can report both.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from deeplearning4j_tpu.exceptions import DeadlineExceededException


class Deadline:
    """Monotonic-clock budget. ``Deadline.after(0.5)`` expires 500 ms
    from now; ``Deadline.none()`` never expires (infinite budget) so
    call sites need no ``if deadline is not None`` branches."""

    def __init__(self, budget: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        if budget is not None and budget <= 0:
            raise ValueError("deadline budget must be > 0 (or None)")
        self.budget = budget
        self.clock = clock
        self._start = clock()

    @classmethod
    def after(cls, budget: Optional[float],
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(budget, clock=clock)

    @classmethod
    def none(cls, clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(None, clock=clock)

    def elapsed(self) -> float:
        return self.clock() - self._start

    def remaining(self) -> Optional[float]:
        """Seconds left (may be negative once expired); None when
        unbounded — the value ``threading.Event.wait`` wants."""
        if self.budget is None:
            return None
        return self.budget - self.elapsed()

    def expired(self) -> bool:
        return self.budget is not None and self.elapsed() >= self.budget

    def check(self, what: str = "operation") -> None:
        """Raise ``DeadlineExceededException`` if expired."""
        if self.expired():
            raise DeadlineExceededException(
                f"{what} exceeded its deadline: "
                f"{self.elapsed():.3f}s elapsed of {self.budget:.3f}s",
                elapsed=self.elapsed(), budget=self.budget,
            )
