"""Bounded retry with exponential backoff + jitter.

The reference stack never needed this in-process: Spark re-runs a lost
task and the S3 SDK retries internally. Here the runtime talks to
object stores and preemptible TPU hosts directly, so transient I/O
faults surface as exceptions in the fit loop — this module turns them
into bounded, deterministic-under-test retry loops.

Everything nondeterministic is injectable: ``sleep`` (tests pass a
recording stub so no wall-clock passes), and the jitter RNG (seeded via
``RetryPolicy.seed`` so a chaos run replays the same delays). Attempts
past the budget raise ``RetryExhaustedException`` carrying the attempt
count and last cause.
"""

from __future__ import annotations

import functools
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

from deeplearning4j_tpu.exceptions import RetryExhaustedException

# Transient-by-default: network/storage hiccups. OSError covers
# ConnectionError/TimeoutError/IOError; ValueError/KeyError and friends
# are logic bugs and propagate immediately.
DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)


@dataclass
class RetryPolicy:
    """Exponential backoff: attempt ``i`` (0-based) sleeps
    ``min(base_delay * multiplier**i, max_delay)`` scaled by a random
    factor in ``[1 - jitter, 1]`` (full-jitter-style decorrelation so a
    fleet of preempted workers doesn't thundering-herd the store).

    ``total_timeout`` bounds the WALL time of the whole retry loop
    (attempts + backoff sleeps) as a per-call ``Deadline``; it
    composes with an explicit ``retry_call(deadline=)`` — whichever
    budget is tighter wins — so a retry storm can never overrun the
    request deadline it runs under. ``clock`` is injectable for
    deterministic deadline tests."""

    max_attempts: int = 5
    base_delay: float = 0.1
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.5
    retry_on: Tuple[Type[BaseException], ...] = DEFAULT_RETRY_ON
    sleep: Callable[[float], None] = time.sleep
    seed: Optional[int] = None
    total_timeout: Optional[float] = None
    clock: Callable[[], float] = time.monotonic
    _rng: random.Random = field(init=False, repr=False, compare=False,
                                default=None)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.total_timeout is not None and self.total_timeout <= 0:
            raise ValueError("total_timeout must be > 0 (or None)")
        self._rng = random.Random(self.seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff delay after failed attempt ``attempt`` (0-based)."""
        d = min(self.base_delay * self.multiplier ** attempt,
                self.max_delay)
        if self.jitter > 0:
            d *= 1.0 - self.jitter * self._rng.random()
        return d


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               deadline=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying allowlisted exceptions
    under ``policy`` (default ``RetryPolicy()``). Non-allowlisted
    exceptions propagate on the first occurrence; an exhausted budget
    raises ``RetryExhaustedException`` chained to the last cause.

    ``deadline`` (a ``resilience.Deadline``, e.g. the serving tier's
    per-request budget) and ``policy.total_timeout`` bound the loop's
    wall time: an attempt never STARTS past the deadline, and a
    backoff sleep that would overrun it raises
    ``DeadlineExceededException`` immediately (chained to the last
    failure) instead of burning the remaining budget asleep.
    ``DeadlineExceededException`` is deliberately not a
    ``TimeoutError``, so it is never itself retried."""
    from deeplearning4j_tpu.observability.trace import get_tracer
    from deeplearning4j_tpu.resilience.deadline import Deadline

    policy = policy or RetryPolicy()
    deadlines = [] if deadline is None else [deadline]
    if policy.total_timeout is not None:
        deadlines.append(Deadline.after(policy.total_timeout,
                                        clock=policy.clock))
    tracer = get_tracer()
    name = str(getattr(fn, "__name__", fn))
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        for d in deadlines:
            d.check(name)
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:  # noqa: PERF203 — the point
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            delay = policy.delay_for(attempt)
            bounded = [d for d in deadlines
                       if d.remaining() is not None]
            if bounded:
                tightest = min(bounded, key=lambda d: d.remaining())
                if tightest.remaining() <= delay:
                    tracer.event("retry.deadline", attrs={
                        "fn": name, "attempt": attempt + 1,
                        "backoff_s": round(delay, 6),
                        "remaining_s": round(
                            tightest.remaining(), 6),
                    })
                    from deeplearning4j_tpu.exceptions import (
                        DeadlineExceededException,
                    )

                    raise DeadlineExceededException(
                        f"{name} backoff ({delay:.3f}s before "
                        f"attempt {attempt + 2}) would overrun the "
                        f"deadline ({max(tightest.remaining(), 0.0):.3f}s "
                        "left)",
                        elapsed=tightest.elapsed(),
                        budget=tightest.budget,
                    ) from e
            tracer.event("retry.attempt", attrs={
                "fn": name, "attempt": attempt + 1,
                "error": type(e).__name__,
                "backoff_s": round(delay, 6),
            })
            policy.sleep(delay)
    tracer.event("retry.exhausted", attrs={
        "fn": name, "attempts": policy.max_attempts,
        "error": type(last).__name__ if last else None,
    })
    raise RetryExhaustedException(
        f"{name} failed after "
        f"{policy.max_attempts} attempts: {last!r}",
        attempts=policy.max_attempts,
        last_cause=last,
    ) from last


def retrying(policy: Optional[RetryPolicy] = None):
    """Decorator form of ``retry_call``:

        @retrying(RetryPolicy(max_attempts=3))
        def fetch(key): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **kwargs)

        return wrapper

    return deco
