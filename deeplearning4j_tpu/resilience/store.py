"""Retrying decorator over the ObjectStore SPI.

``RetryingObjectStore`` wraps any backend (local, S3, GCS, or a
``FaultyObjectStore`` under test) so every read/write/list/transfer
runs under a ``RetryPolicy`` — the reference delegated this to the S3
SDK's internal retries; making it a first-class decorator means GCS,
local-NFS and injected-fault backends all share one bounded policy,
and the fit loop sees either a result or ``RetryExhaustedException``.

``open()`` retries the open itself but cannot retry a stream that dies
mid-read; whole-object ``read()`` is the resilient primitive (and what
``CloudDataSetIterator`` uses).
"""

from __future__ import annotations

from typing import IO, List, Optional

from deeplearning4j_tpu.cloud.storage import ObjectStore
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call


class RetryingObjectStore(ObjectStore):
    def __init__(self, inner: ObjectStore,
                 policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy or RetryPolicy()

    def keys(self, prefix: str = "") -> List[str]:
        return retry_call(self.inner.keys, prefix, policy=self.policy)

    def open(self, key: str) -> IO[bytes]:
        return retry_call(self.inner.open, key, policy=self.policy)

    def read(self, key: str) -> bytes:
        return retry_call(self.inner.read, key, policy=self.policy)

    def write(self, key: str, data: bytes) -> None:
        retry_call(self.inner.write, key, data, policy=self.policy)

    def download(self, key: str, to_path) -> None:
        retry_call(self.inner.download, key, to_path, policy=self.policy)

    def upload(self, from_path, key: str) -> None:
        retry_call(self.inner.upload, from_path, key, policy=self.policy)
