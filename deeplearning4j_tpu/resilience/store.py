"""Retrying decorator over the ObjectStore SPI.

``RetryingObjectStore`` wraps any backend (local, S3, GCS, or a
``FaultyObjectStore`` under test) so every read/write/list/transfer
runs under a ``RetryPolicy`` — the reference delegated this to the S3
SDK's internal retries; making it a first-class decorator means GCS,
local-NFS and injected-fault backends all share one bounded policy,
and the fit loop sees either a result or ``RetryExhaustedException``.

An optional ``CircuitBreaker`` composes on top: retry absorbs the
transient blips, and when even the retry budget keeps exhausting
(endpoint down, not flaky), the breaker trips so subsequent callers —
e.g. a serving tier's hot-reload path — fail fast with
``CircuitOpenException`` instead of stacking multi-attempt backoff
waits per call.

``open()`` retries the open itself but cannot retry a stream that dies
mid-read; whole-object ``read()`` is the resilient primitive (and what
``CloudDataSetIterator`` uses).
"""

from __future__ import annotations

from typing import IO, List, Optional

from deeplearning4j_tpu.cloud.storage import ObjectStore
from deeplearning4j_tpu.resilience.breaker import CircuitBreaker
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call


class RetryingObjectStore(ObjectStore):
    """``deadline_fn`` (optional) supplies the CURRENT request's
    ``Deadline`` per operation — the serving tier threads its
    admission-stamped budget here so a storage retry storm can never
    overrun the 504 envelope: attempts stop (and backoff sleeps are
    refused) the moment they would exceed the request budget,
    surfacing ``DeadlineExceededException`` instead of a late
    success nobody is waiting for. ``policy.total_timeout`` composes
    on top as a per-call wall bound independent of any request."""

    def __init__(self, inner: ObjectStore,
                 policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 deadline_fn=None):
        self.inner = inner
        self.policy = policy or RetryPolicy()
        self.breaker = breaker
        self.deadline_fn = deadline_fn

    def _call(self, fn, *args):
        deadline = (self.deadline_fn()
                    if self.deadline_fn is not None else None)
        if self.breaker is not None:
            return self.breaker.call(
                retry_call, fn, *args, policy=self.policy,
                deadline=deadline,
            )
        return retry_call(fn, *args, policy=self.policy,
                          deadline=deadline)

    def keys(self, prefix: str = "") -> List[str]:
        return self._call(self.inner.keys, prefix)

    def open(self, key: str) -> IO[bytes]:
        return self._call(self.inner.open, key)

    def read(self, key: str) -> bytes:
        return self._call(self.inner.read, key)

    def write(self, key: str, data: bytes) -> None:
        self._call(self.inner.write, key, data)

    def download(self, key: str, to_path) -> None:
        self._call(self.inner.download, key, to_path)

    def upload(self, from_path, key: str) -> None:
        self._call(self.inner.upload, from_path, key)
