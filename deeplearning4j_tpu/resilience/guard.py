"""Divergence guard: NaN/Inf detection BEFORE the optimizer update.

The reference's only defense against numerical divergence was
``InvalidScoreIterationTerminationCondition`` — it notices a NaN score
*after* the update already poisoned the parameters, and its only move
is to kill the run. Here the check rides inside the jitted train step:
loss and gradient global-norm are tested for finiteness, and when the
step is bad the parameter/updater/state updates are *not applied*
(``jnp.where`` select on the step output — free when the flag is
true, no host round-trip on the good path beyond the flag itself).

Host-side policy then decides what a bad step means:

- ``"skip"``: drop the minibatch's update and keep going (counters on
  the guard record how many were skipped);
- ``"rollback"``: additionally restore the last verified checkpoint —
  for slow-onset divergence where bad state predates the first
  non-finite loss.

``max_consecutive`` bounds either policy: a model that produces
nothing but NaNs raises ``DL4JFaultException`` instead of spinning.

The in-jit half (``divergence_ok``/``select_updates``) is imported by
the step builders in ``parallel/trainer.py`` and ``nn/multilayer.py``;
the host half is this ``DivergenceGuard`` object, shared across both
engines.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.exceptions import DL4JFaultException

SKIP = "skip"
ROLLBACK = "rollback"


def grad_global_norm_sq(grads) -> jax.Array:
    """Squared global norm over the inexact leaves of a gradient tree
    (jit-safe). Inf-on-overflow is fine — the guard only asks whether
    the result is finite."""
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            leaf32 = leaf.astype(jnp.float32)
            total = total + jnp.sum(leaf32 * leaf32)
    return total


def divergence_ok(score, grads) -> jax.Array:
    """Scalar bool: the step's loss AND gradients are all finite."""
    return jnp.logical_and(
        jnp.isfinite(score),
        jnp.isfinite(grad_global_norm_sq(grads)),
    )


def _select(ok, new, old):
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new, old
    )


def select_updates(ok, new_params, params, new_upd, upd_state,
                   new_state, state):
    """Apply the step's outputs only when ``ok``; otherwise keep the
    pre-step trees. Layer-state entries whose pytree structure changed
    during the step (a recurrent carry appearing) pass through as-is —
    they are per-minibatch scratch, not trajectory state."""
    sel_params = _select(ok, new_params, params)
    sel_upd = _select(ok, new_upd, upd_state)
    sel_state = {}
    for ln, st in new_state.items():
        old = state.get(ln, {})
        if (jax.tree_util.tree_structure(st)
                == jax.tree_util.tree_structure(old)):
            sel_state[ln] = _select(ok, st, old)
        else:
            sel_state[ln] = st
    return sel_params, sel_upd, sel_state


class DivergenceGuard:
    """Host-side divergence policy. Construct once, hand to
    ``MultiLayerNetwork.set_divergence_guard`` or
    ``DistributedTrainer(divergence_guard=...)``.

    Note: consulting the guard reads the step's ok-flag back from the
    device, which synchronizes every step — the cost of supervision.
    """

    def __init__(self, policy: str = SKIP, checkpoint_manager=None,
                 max_consecutive: int = 10):
        if policy not in (SKIP, ROLLBACK):
            raise ValueError(
                f"policy must be '{SKIP}' or '{ROLLBACK}', got {policy!r}"
            )
        if policy == ROLLBACK and checkpoint_manager is None:
            raise ValueError(
                "rollback policy needs a checkpoint_manager"
            )
        self.policy = policy
        self.checkpoint_manager = checkpoint_manager
        self.max_consecutive = max_consecutive
        self.skipped_steps = 0
        self.rollbacks = 0
        self.consecutive_bad = 0

    def good_step(self) -> None:
        self.consecutive_bad = 0

    def bad_step(self, model, on_restore=None) -> None:
        """One non-finite step was detected (its update was already
        suppressed in-jit). Applies the policy; ``on_restore`` runs
        after a rollback (the trainer re-places params on its mesh)."""
        self.consecutive_bad += 1
        if self.consecutive_bad > self.max_consecutive:
            raise DL4JFaultException(
                f"divergence guard: {self.consecutive_bad} consecutive "
                "non-finite steps — aborting instead of spinning"
            )
        if self.policy == SKIP:
            self.skipped_steps += 1
            return
        from deeplearning4j_tpu.resilience.checkpoint import restore_into

        restore_into(model, self.checkpoint_manager)
        self.rollbacks += 1
        if on_restore is not None:
            on_restore()
