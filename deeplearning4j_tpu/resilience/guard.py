"""Divergence guard: NaN/Inf *and statistical* anomaly detection
BEFORE the optimizer update.

The reference's only defense against numerical divergence was
``InvalidScoreIterationTerminationCondition`` — it notices a NaN score
*after* the update already poisoned the parameters, and its only move
is to kill the run. Here the check rides inside the jitted train step:
loss and gradient global-norm are tested for finiteness, and when the
step is bad the parameter/updater/state updates are *not applied*
(``jnp.where`` select on the step output — free when the flag is
true, no host round-trip on the good path beyond the flag itself).

The NaN/Inf check alone misses the bad-data failure mode the
divergence literature treats as table stakes for long unattended
runs: a *finite* loss spike or grad-norm explosion from one poisoned
batch sails straight into the updater. ``StatGuardConfig`` adds the
statistical half: an EWMA mean/variance of the loss and gradient
global-norm rides through the step like the loss-scale state
(device-resident, donated, no host sync), and a step whose loss or
grad-norm lands ``z_threshold`` standard deviations out — or
``spike_factor``x the running mean — is suppressed by the SAME
in-jit select. Tripped/non-finite samples are NOT folded into the
EWMA (a spike must not teach the guard that spikes are normal), and
the first ``warmup`` clean steps only accumulate. The state is tiny
(7 scalars) and serializes exactly through the checkpoint manifest
(``stat_guard_state_doc``/``stat_guard_state_from_doc``: float(f32)
-> JSON f64 -> f32 round-trips bitwise), so kill/resume replays the
identical trip decisions.

Host-side policy then decides what a bad step means:

- ``"skip"``: drop the minibatch's update and keep going (counters on
  the guard record how many were skipped, ``skipped_batches`` which
  iteration indices);
- ``"rollback"``: additionally restore the last verified checkpoint —
  for slow-onset divergence where bad state predates the first
  non-finite loss.

``max_consecutive`` bounds either policy: a model that produces
nothing but NaNs raises ``DL4JFaultException`` instead of spinning.

The in-jit half (``divergence_ok``/``select_updates``/
``stat_guard_update``) is imported by the step builders in
``parallel/trainer.py`` and ``nn/core.py``; the host half is this
``DivergenceGuard`` object, shared across both engines.

Metrics (catalogued in ARCHITECTURE.md):
``guard_spike_trips_total{signal}`` plus ``guard_loss_ewma`` /
``guard_gradnorm_ewma`` gauges, published at each consult of a
tripped step and each checkpoint capture.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.exceptions import DL4JFaultException

SKIP = "skip"
ROLLBACK = "rollback"

_GUARD_METRICS = None


def _guard_metrics():
    global _GUARD_METRICS
    if _GUARD_METRICS is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        reg = default_registry()
        _GUARD_METRICS = (
            reg.counter(
                "guard_spike_trips_total", labels=("signal",),
                help="statistical-guard trips by signal "
                     "(loss | gradnorm)",
            ),
            reg.gauge(
                "guard_loss_ewma",
                help="statistical guard: EWMA of the training loss",
            )._default(),
            reg.gauge(
                "guard_gradnorm_ewma",
                help="statistical guard: EWMA of the gradient "
                     "global norm",
            )._default(),
        )
    return _GUARD_METRICS


def grad_global_norm_sq(grads) -> jax.Array:
    """Squared global norm over the inexact leaves of a gradient tree
    (jit-safe). Inf-on-overflow is fine — the guard only asks whether
    the result is finite."""
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            leaf32 = leaf.astype(jnp.float32)
            total = total + jnp.sum(leaf32 * leaf32)
    return total


def divergence_ok(score, grads) -> jax.Array:
    """Scalar bool: the step's loss AND gradients are all finite."""
    return jnp.logical_and(
        jnp.isfinite(score),
        jnp.isfinite(grad_global_norm_sq(grads)),
    )


def _select(ok, new, old):
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new, old
    )


def select_updates(ok, new_params, params, new_upd, upd_state,
                   new_state, state):
    """Apply the step's outputs only when ``ok``; otherwise keep the
    pre-step trees. Layer-state entries whose pytree structure changed
    during the step (a recurrent carry appearing) pass through as-is —
    they are per-minibatch scratch, not trajectory state."""
    sel_params = _select(ok, new_params, params)
    sel_upd = _select(ok, new_upd, upd_state)
    sel_state = {}
    for ln, st in new_state.items():
        old = state.get(ln, {})
        if (jax.tree_util.tree_structure(st)
                == jax.tree_util.tree_structure(old)):
            sel_state[ln] = _select(ok, st, old)
        else:
            sel_state[ln] = st
    return sel_params, sel_upd, sel_state


# ---------------------------------------------------------------------------
# statistical anomaly guard (in-jit half)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StatGuardConfig:
    """Knobs of the statistical anomaly guard (hashable: the step
    builders close over it, so it is part of the compiled program).

    ``alpha`` is the EWMA smoothing factor, ``z_threshold`` the
    z-score past which a signal trips, ``spike_factor`` the
    multiple-of-the-mean ceiling (catches spikes before the variance
    estimate has warmed up to them), ``warmup`` the number of clean
    samples accumulated before either trip condition arms."""

    alpha: float = 0.02
    z_threshold: float = 6.0
    spike_factor: float = 10.0
    warmup: int = 20


# stable key order — the manifest doc and the pytree both use it
STAT_STATE_KEYS = ("loss_mean", "loss_var", "gnorm_mean", "gnorm_var",
                   "count", "trips_loss", "trips_gnorm")


def stat_guard_state() -> dict:
    """Fresh device-resident EWMA state, threaded through the jitted
    step exactly like the loss-scale state dict."""
    z = jnp.zeros((), jnp.float32)
    zi = jnp.zeros((), jnp.int32)
    return {
        "loss_mean": z, "loss_var": z,
        "gnorm_mean": z, "gnorm_var": z,
        "count": zi, "trips_loss": zi, "trips_gnorm": zi,
    }


def _signal_trip(x, mean, var, count, cfg: StatGuardConfig):
    """Scalar bool: is this (finite) sample anomalous vs its EWMA?"""
    warmed = count >= cfg.warmup
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    z = jnp.abs(x - mean) / std
    spike = x > cfg.spike_factor * jnp.maximum(mean, 1e-12)
    return warmed & ((z > cfg.z_threshold) | spike)


def _ewma_fold(mean, var, x, alpha, take):
    delta = x - mean
    new_mean = mean + alpha * delta
    new_var = (1.0 - alpha) * (var + alpha * delta * delta)
    return (jnp.where(take, new_mean, mean),
            jnp.where(take, new_var, var))


def stat_guard_update(sg: dict, cfg: StatGuardConfig, score, gnorm,
                      finite_ok):
    """One in-jit statistical-guard step: trip decision + EWMA fold.

    Returns ``(ok, new_state)``. ``ok`` is False when either signal
    trips (the caller ANDs it into the select). Non-finite or tripped
    samples are excluded from the fold — the running statistics track
    the CLEAN trajectory only, so one spike cannot drag the mean up
    and let the next one through."""
    x_loss = score.astype(jnp.float32)
    x_gn = gnorm.astype(jnp.float32)
    count = sg["count"]
    trip_loss = finite_ok & _signal_trip(
        x_loss, sg["loss_mean"], sg["loss_var"], count, cfg
    )
    trip_gn = finite_ok & _signal_trip(
        x_gn, sg["gnorm_mean"], sg["gnorm_var"], count, cfg
    )
    ok = jnp.logical_not(trip_loss | trip_gn)
    take = finite_ok & ok
    alpha = jnp.float32(cfg.alpha)
    loss_mean, loss_var = _ewma_fold(
        sg["loss_mean"], sg["loss_var"], x_loss, alpha, take
    )
    gn_mean, gn_var = _ewma_fold(
        sg["gnorm_mean"], sg["gnorm_var"], x_gn, alpha, take
    )
    new_sg = {
        "loss_mean": loss_mean, "loss_var": loss_var,
        "gnorm_mean": gn_mean, "gnorm_var": gn_var,
        "count": count + take.astype(jnp.int32),
        "trips_loss": sg["trips_loss"] + trip_loss.astype(jnp.int32),
        "trips_gnorm": sg["trips_gnorm"] + trip_gn.astype(jnp.int32),
    }
    return ok, new_sg


def stat_guard_state_doc(state: Optional[dict]) -> Optional[dict]:
    """Manifest form of the EWMA state. ``float(np.float32)`` is
    exactly representable in JSON's f64 and the round trip back
    through ``jnp.float32`` is bitwise — the property the
    kill/resume-bitwise chaos tests lean on."""
    if state is None:
        return None
    out = {}
    for k in STAT_STATE_KEYS:
        v = state[k]
        out[k] = int(v) if k in ("count", "trips_loss",
                                 "trips_gnorm") else float(v)
    return out


def stat_guard_state_from_doc(doc: dict) -> dict:
    state = {}
    for k in STAT_STATE_KEYS:
        v = doc.get(k, 0)
        state[k] = (jnp.asarray(int(v), jnp.int32)
                    if k in ("count", "trips_loss", "trips_gnorm")
                    else jnp.asarray(float(v), jnp.float32))
    return state


class DivergenceGuard:
    """Host-side divergence policy. Construct once, hand to
    ``MultiLayerNetwork.set_divergence_guard`` or
    ``DistributedTrainer(divergence_guard=...)``. With ``stats`` (a
    :class:`StatGuardConfig`, or ``True`` for the defaults) the step
    additionally threads the statistical anomaly guard.

    Note: consulting the guard reads the step's ok-flag back from the
    device, which synchronizes every step — the cost of supervision.
    """

    def __init__(self, policy: str = SKIP, checkpoint_manager=None,
                 max_consecutive: int = 10, stats=None):
        if policy not in (SKIP, ROLLBACK):
            raise ValueError(
                f"policy must be '{SKIP}' or '{ROLLBACK}', got {policy!r}"
            )
        if policy == ROLLBACK and checkpoint_manager is None:
            raise ValueError(
                "rollback policy needs a checkpoint_manager"
            )
        self.policy = policy
        self.checkpoint_manager = checkpoint_manager
        self.max_consecutive = max_consecutive
        if stats is True:
            stats = StatGuardConfig()
        if stats is not None and not isinstance(stats, StatGuardConfig):
            raise ValueError(
                "stats must be a StatGuardConfig, True, or None; "
                f"got {stats!r}"
            )
        self.stats = stats
        self.skipped_steps = 0
        self.rollbacks = 0
        self.consecutive_bad = 0
        # iteration indices whose update was suppressed (part of the
        # checkpoint ledger: a resumed run re-reports them honestly)
        self.skipped_batches: List[int] = []
        # last device trip counters seen, to diff into the labeled
        # metric without double counting
        self._seen_trips = {"loss": 0, "gradnorm": 0}

    def good_step(self) -> None:
        self.consecutive_bad = 0

    def publish_stats(self, model) -> None:
        """Mirror the device-resident EWMA state into the gauges and
        the labeled trip counter (diffed — idempotent per state).
        Called on each tripped consult and at checkpoint capture; a
        model without stat-guard state is a no-op."""
        state = getattr(model, "_stat_guard_state", None)
        if state is None:
            return
        trips, g_loss, g_gn = _guard_metrics()
        g_loss.set(float(state["loss_mean"]))
        g_gn.set(float(state["gnorm_mean"]))
        for signal, key in (("loss", "trips_loss"),
                            ("gradnorm", "trips_gnorm")):
            now = int(state[key])
            delta = now - self._seen_trips[signal]
            if delta > 0:
                trips.labels(signal).inc(delta)
            self._seen_trips[signal] = now

    def bad_step(self, model, on_restore=None,
                 step_index=None) -> None:
        """One bad step was detected — non-finite, or statistically
        anomalous when ``stats`` is armed (its update was already
        suppressed in-jit). Applies the policy; ``on_restore`` runs
        after a rollback (the trainer re-places params on its mesh).
        ``step_index`` names the offending step for the ledger — the
        async dispatch window passes it because it consults flags up
        to ``guard_lag`` steps after the counter moved on."""
        if step_index is None:
            step_index = int(getattr(model, "iteration_count", 0)) - 1
        self.skipped_batches.append(int(step_index))
        if self.stats is not None:
            self.publish_stats(model)
        self.consecutive_bad += 1
        from deeplearning4j_tpu.observability import flightrec

        flightrec.record_event(
            "guard_trip", step=int(step_index), policy=self.policy,
            consecutive=self.consecutive_bad,
        )
        # a guard trip is exactly the moment the last-N-steps context
        # matters: dump the ring (best-effort; never masks the abort)
        flightrec.dump_on_crash("guard_trip")
        if self.consecutive_bad > self.max_consecutive:
            raise DL4JFaultException(
                f"divergence guard: {self.consecutive_bad} consecutive "
                "non-finite steps — aborting instead of spinning"
            )
        if self.policy == SKIP:
            self.skipped_steps += 1
            return
        from deeplearning4j_tpu.resilience.checkpoint import restore_into

        restore_into(model, self.checkpoint_manager)
        self.rollbacks += 1
        if on_restore is not None:
            on_restore()


# ---------------------------------------------------------------------------
# checkpoint-manifest capture/apply (the bitwise kill/resume contract)
# ---------------------------------------------------------------------------


def guard_state_doc(model) -> Optional[dict]:
    """The manifest ``guard`` field for one model: statistical-guard
    EWMA state (bitwise-exact floats), the guard's skipped-batch
    ledger, and the data-plane quarantine ledger a ``ContinualTrainer``
    attached (``model._data_ledger``). ``None`` when nothing is armed
    — old manifests stay byte-identical."""
    # DistributedTrainer keeps its guard off-model; it leaves a
    # _ckpt_guard back-reference so manager.save(model) still captures
    # the ledger
    guard = (getattr(model, "divergence_guard", None)
             or getattr(model, "_ckpt_guard", None))
    sg = getattr(model, "_stat_guard_state", None)
    data = getattr(model, "_data_ledger", None)
    doc: dict = {}
    if sg is not None:
        doc["ewma"] = stat_guard_state_doc(sg)
        if guard is not None:
            guard.publish_stats(model)
    if guard is not None:
        doc["skipped"] = [int(i) for i in guard.skipped_batches]
        if guard.skipped_steps:
            doc["skipped_steps"] = int(guard.skipped_steps)
        if guard.rollbacks:
            doc["rollbacks"] = int(guard.rollbacks)
    if data:
        doc["data"] = dict(data)
    return doc or None


def apply_guard_state_doc(model, doc: Optional[dict]) -> None:
    """Inverse of ``guard_state_doc``: restore the EWMA state and the
    ledgers onto ``model`` (and its installed guard) so a resumed run
    replays the identical trip decisions."""
    if not doc:
        return
    ewma = doc.get("ewma")
    if ewma is not None:
        model._stat_guard_state = stat_guard_state_from_doc(ewma)
    guard = (getattr(model, "divergence_guard", None)
             or getattr(model, "_ckpt_guard", None))
    if guard is not None:
        guard.skipped_batches = [int(i) for i in doc.get("skipped", [])]
        guard.skipped_steps = int(doc.get("skipped_steps", 0))
        guard.rollbacks = int(doc.get("rollbacks", 0))
        if ewma is not None:
            # the metric diff base restarts at the restored counters
            guard._seen_trips = {
                "loss": int(ewma.get("trips_loss", 0)),
                "gradnorm": int(ewma.get("trips_gnorm", 0)),
            }
    if doc.get("data"):
        model._data_ledger = dict(doc["data"])
