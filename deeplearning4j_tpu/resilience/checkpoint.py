"""Atomic, versioned, write-behind training checkpoints with a
sharded two-phase cross-host commit and background scrub/repair.

The reference's ``CheckpointListener`` (deeplearning4j-nn) wrote
``checkpoint_<n>_<name>.zip`` files with a ``checkpoint.txt`` index but
no atomicity or verification story — a crash mid-save truncated the
newest zip and the next restore exploded. Here every checkpoint is:

- **atomic + durable**: files are staged to a temp file, fsync'd,
  ``os.replace``d into place, and the directory is fsync'd — a crash
  *or power loss* at any point leaves either the complete new version
  or nothing;
- **versioned**: named ``<prefix>-<step 8-digit>.zip`` by the model's
  iteration count, with a retention window (``keep_last``);
- **verified**: a manifest records step/epoch/CRC-32/size; restore
  checks the bytes against it and falls back to the previous version
  when the newest fails, raising ``CheckpointCorruptedException``
  only when no version survives.

**Write-behind saves** (``save(model, mode="async")``, or
``CheckpointManager(mode="async")``): the training thread only takes
buffer-isolated host copies of the state (the ``SnapshotRing`` copy
discipline — ``nn.core.host_snapshot_tree``; cross-process-sharded
leaves gather through ``_host_gather_leaf``); serialization, CRC,
manifest, replica mirroring and pruning all run on one bounded
background writer thread. At most one save is in flight — a newer
save supersedes a queued one (its handle resolves ``None`` with
``superseded=True``). ``flush()`` drains the writer; ``stop()``
flushes and joins; a synchronous ``save`` (the preemption emergency
path) flushes first, so an emergency checkpoint is never interleaved
with, or shadowed by, a half-finished background write.

**Sharded layout + two-phase commit** (``commit=`` a commit barrier,
e.g. :class:`LeaseCommitBarrier` over the PR-16 control plane): on a
multi-process mesh each host writes only its slice of the flat state
map to ``<prefix>-<step>/shard-<rank>.npz``. Commit is a two-phase
fence: (1) every rank arrives at a payload-carrying barrier with its
shard digest (file, CRC-32, size) — leaving the barrier means *every*
shard is durable; (2) rank 0 writes ``<prefix>-<step>/manifest.json``
*last* (atomic + fsync) as the single commit point, then a second
barrier releases the peers. A missing shard, a host dying mid-save,
or a torn manifest leaves an *uncommitted* directory that
``available()`` ignores and GC removes (grace-aged, never the step
being written). Shard npz files hold only arrays; the model config
rides inside the manifest, so restore reassembles the shards onto
whatever mesh is present (composing with the cross-mesh ZeRO
re-shard — checkpoints always hold canonical state).

Manifest format 1 (single zip), one JSON object per checkpoint:

    {"format": 1, "step": 128, "epoch": 2,
     "file": "checkpoint-00000128.zip",
     "crc32": 2914207069, "size": 18007, ...}

Manifest format 2 (sharded), at ``<prefix>-<step>/manifest.json``:

    {"format": 2, "step": 128, "epoch": 2,
     "dir": "checkpoint-00000128", "nshards": 2,
     "shards": {"0": {"file": "shard-0.npz", "crc32": ..,
                      "size": .., "keys": 7}, "1": {...}},
     "model": {"model_type": .., "configuration": {..},
               "iteration_count": 128, "epoch_count": 2}, ...}

The optional ``artifacts`` map carries named side blobs — AOT-
exported executables (``compile/aot.py``) ride here — each written
atomically and CRC-verified on read by the SAME manifest machinery
as the model bytes. The asymmetry is deliberate: a corrupt *model*
fails that version (restore falls back), while a corrupt *artifact*
only disables that artifact (``load_artifact`` returns None and the
consumer JITs) — a lost executable costs a compile, never a restore.

**Scrub + repair**: ``scrub_once()`` (or the background scrubber,
``scrub_interval_s=``) re-verifies every retained checkpoint's CRCs
at shard granularity. A corrupt component is repaired from the
replica (``replica_store=`` a second ``ObjectStore``, typically
wrapped in ``RetryingObjectStore``; committed checkpoints are
mirrored there after every save) when the replica's bytes match the
manifest CRC; otherwise the step is **quarantined** via a sibling
marker file and restore walks back past it — the corrupted-newest
fallback, extended to shard granularity.

``CheckpointListener`` plugs the manager into any fit loop via the
``IterationListener`` SPI (``optimize/listeners.py``).
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
import shutil
import threading
import time
import zipfile
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.exceptions import (
    CheckpointCommitAbortedException, CheckpointCorruptedException,
)
from deeplearning4j_tpu.observability import flightrec
from deeplearning4j_tpu.optimize.listeners import IterationListener

logger = logging.getLogger(__name__)

MANIFEST_FORMAT = 1
SHARDED_MANIFEST_FORMAT = 2


def _default_registry():
    from deeplearning4j_tpu.observability.metrics import default_registry

    return default_registry()


def atomic_write_bytes(path, data: bytes) -> None:
    """Durably write ``data`` to ``path``: temp file in the same
    directory, fsync, ``os.replace``, directory fsync (rename is
    atomic only within a filesystem; the fsyncs make it survive power
    loss, not just a process crash)."""
    from deeplearning4j_tpu.util.model_serializer import atomic_write

    atomic_write(path, lambda f: f.write(data))


def _crc32_of(path, chunk: int = 1 << 20) -> Tuple[int, int]:
    """(crc32, size) of a file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
            size += len(b)
    return crc & 0xFFFFFFFF, size


@dataclass(frozen=True)
class CheckpointInfo:
    """One verified-writable checkpoint version. ``artifacts`` maps
    artifact name -> {file, crc32, size} for side blobs (AOT
    executables) that ride the manifest's CRC story without gating
    the model restore. Format-2 (sharded) versions carry ``nshards``
    / ``shards`` / ``dir`` / ``model`` instead of a single zip's
    crc32/size."""

    step: int
    epoch: int
    file: str   # zip filename (format 1) / directory name (format 2)
    crc32: int
    size: int
    format: int = MANIFEST_FORMAT
    artifacts: dict = field(default_factory=dict)
    # ZeRO layout the model trained under at save time (e.g.
    # {"shards": 8}) — informational: the checkpoint always holds
    # canonical (gathered) updater state, so restore works on ANY
    # mesh; the field lets operators see which runs were sharded.
    # Manifests without it parse as zero=None.
    zero: Optional[dict] = None
    # Anomaly-defense trajectory state (resilience.guard_state_doc):
    # statistical-guard EWMA scalars as bitwise-exact floats, the
    # guard's skipped-batch ledger, and the data-plane quarantine
    # ledger. Restoring it makes a killed+resumed defended run replay
    # the identical skip decisions. Manifests without it parse as
    # guard=None.
    guard: Optional[dict] = None
    # sharded (format 2) fields: shard count, per-shard digests
    # ({"<rank>": {"file", "crc32", "size", "keys"}}), the directory
    # name, and the embedded model config document (shard npz files
    # hold only arrays)
    nshards: Optional[int] = None
    shards: dict = field(default_factory=dict)
    dir: Optional[str] = None
    model: Optional[dict] = None

    @property
    def is_sharded(self) -> bool:
        return self.nshards is not None

    def to_manifest(self) -> dict:
        if self.is_sharded:
            doc = {
                "format": SHARDED_MANIFEST_FORMAT, "step": self.step,
                "epoch": self.epoch, "dir": self.dir or self.file,
                "nshards": self.nshards, "shards": self.shards,
                "model": self.model,
            }
        else:
            doc = {
                "format": self.format, "step": self.step,
                "epoch": self.epoch, "file": self.file,
                "crc32": self.crc32, "size": self.size,
            }
        if self.artifacts:
            doc["artifacts"] = self.artifacts
        if self.zero:
            doc["zero"] = self.zero
        if self.guard:
            doc["guard"] = self.guard
        return doc

    @classmethod
    def from_manifest(cls, doc: dict) -> "CheckpointInfo":
        fmt = int(doc.get("format", MANIFEST_FORMAT))
        common = dict(
            step=int(doc["step"]), epoch=int(doc.get("epoch", 0)),
            format=fmt,
            artifacts=dict(doc.get("artifacts") or {}),
            zero=dict(doc["zero"]) if doc.get("zero") else None,
            guard=dict(doc["guard"]) if doc.get("guard") else None,
        )
        if fmt >= SHARDED_MANIFEST_FORMAT:
            return cls(
                file=doc["dir"], crc32=0, size=0,
                nshards=int(doc["nshards"]),
                shards=dict(doc.get("shards") or {}),
                dir=doc["dir"], model=dict(doc.get("model") or {}),
                **common,
            )
        return cls(
            file=doc["file"], crc32=int(doc["crc32"]),
            size=int(doc["size"]), **common,
        )


class AsyncSaveHandle:
    """Ticket for one write-behind save. ``wait()`` blocks until the
    background writer commits (returns the :class:`CheckpointInfo`),
    the save is superseded by a newer one (returns ``None``,
    ``superseded`` set), or the write fails (re-raises the writer's
    exception — e.g. :class:`CheckpointCommitAbortedException` when
    the cross-host commit fence aborted)."""

    def __init__(self, step: int):
        self.step = int(step)
        self.info: Optional[CheckpointInfo] = None
        self.error: Optional[BaseException] = None
        self.superseded = False
        self._event = threading.Event()

    def _resolve(self, info, error=None, superseded=False) -> None:
        self.info = info
        self.error = error
        self.superseded = bool(superseded)
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None
             ) -> Optional[CheckpointInfo]:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"write-behind save of step {self.step} still in "
                f"flight after {timeout}s")
        if self.error is not None:
            raise self.error
        return self.info


class LocalCommitBarrier:
    """Trivial commit fence for a single process: forces the sharded
    ``<prefix>-<step>/`` layout without a control plane (tests, or a
    single-host run that wants shard-granular scrub/repair). The
    barrier trivially proceeds with this rank's own digest."""

    def __init__(self, rank: int = 0, nshards: int = 1):
        self.rank = int(rank)
        self.nshards = int(nshards)

    def barrier(self, token: str, payload: dict) -> Dict[int, dict]:
        return {self.rank: payload}


class LeaseCommitBarrier:
    """The cross-host commit fence: rides a PR-16
    ``WorkerAgent.sync_barrier`` (payload-carrying named barrier over
    the lease coordinator), so commit membership is exactly the lease
    membership — a host dying mid-save bumps the epoch, the barrier
    reports it, and the commit aborts instead of publishing a
    manifest over a missing shard. ``rank``/``nshards`` track the
    agent's current grant, so an elastic downscale automatically
    narrows the shard layout of the next save."""

    def __init__(self, agent, timeout_s: Optional[float] = None):
        self.agent = agent
        self.timeout_s = timeout_s

    @property
    def rank(self) -> int:
        return int(self.agent.rank or 0)

    @property
    def nshards(self) -> int:
        return int(self.agent.num or 1)

    def barrier(self, token: str, payload: dict) -> Dict[int, dict]:
        from deeplearning4j_tpu.parallel.control_plane import (
            ControlPlaneException,
        )

        try:
            got = self.agent.sync_barrier(
                token, payload, timeout_s=self.timeout_s)
        except ControlPlaneException as e:
            raise CheckpointCommitAbortedException(
                f"commit barrier {token!r} failed: {e}") from e
        if got is None:
            raise CheckpointCommitAbortedException(
                f"membership changed during commit barrier {token!r}: "
                "the epoch the shards were written under no longer "
                "exists")
        return got


class CheckpointManager:
    """Atomic versioned checkpoint store over a local directory.

    ``save(model)`` stamps the version from ``model.iteration_count``;
    ``restore_latest()`` walks versions newest-first, skipping any that
    fail CRC/zip verification or are quarantined (with a warning), and
    returns the restored model + its info.

    Knobs beyond the classic ones:

    - ``mode``: default save mode — ``"sync"`` (write on the calling
      thread, the historical behavior) or ``"async"`` (write-behind:
      snapshot on the calling thread, everything else on a background
      writer; ``save`` returns an :class:`AsyncSaveHandle`). Either
      can be overridden per call via ``save(..., mode=)``.
    - ``commit``: a commit barrier (:class:`LeaseCommitBarrier` /
      :class:`LocalCommitBarrier`). When set, saves use the sharded
      ``<prefix>-<step>/shard-<rank>.npz`` layout with the two-phase
      commit; when ``None`` (default) the single-process zip path is
      unchanged.
    - ``replica_store``: a second ``ObjectStore`` (wrap it in
      ``RetryingObjectStore`` for flaky backends): committed
      checkpoints are mirrored there, and scrub/restore repair
      corrupt local components from it.
    - ``scrub_interval_s``: start a background scrubber re-verifying
      retained checkpoints' CRCs every interval (``scrub_once()``
      runs one deterministic pass for tests).
    - ``gc_grace_s``: minimum age before an *uncommitted* shard
      directory (no manifest — a torn or aborted commit) is
      garbage-collected; directories older than this, or below the
      newest committed step, are removed at prune time.
    """

    def __init__(self, directory, keep_last: int = 3,
                 prefix: str = "checkpoint", protect=None, *,
                 mode: str = "sync", commit=None, replica_store=None,
                 scrub_interval_s: Optional[float] = None,
                 gc_grace_s: float = 300.0, registry=None):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        if not re.fullmatch(r"[A-Za-z0-9._]+", prefix):
            raise ValueError(
                f"prefix {prefix!r} must be filename-safe "
                "(letters/digits/dot/underscore)"
            )
        if mode not in ("sync", "async"):
            raise ValueError(f"mode must be 'sync' or 'async', "
                             f"got {mode!r}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_last = keep_last
        self.prefix = prefix
        # retention guard: a callable returning step numbers that
        # pruning must NEVER delete, consulted at each save — the
        # promotion journal wires ``journal.referenced_steps`` here so
        # a rollback target outlives the keep_last window
        self.protect = protect
        self.mode = mode
        self.commit = commit
        self.replica = replica_store
        self.scrub_interval_s = scrub_interval_s
        self.gc_grace_s = float(gc_grace_s)
        # write-behind writer state: a single-slot pending queue
        # (newest wins) plus one busy flag — "at most one save in
        # flight" by construction
        self._wcond = threading.Condition()
        self._wpending: Optional[tuple] = None
        self._wbusy = False
        self._wstop = False
        self._wthread: Optional[threading.Thread] = None
        self._active_steps: set = set()   # sharded writes in flight
        self._scrub_stop: Optional[threading.Event] = None
        self._scrub_thread: Optional[threading.Thread] = None
        registry = registry if registry is not None \
            else _default_registry()
        self._m_pending = registry.gauge(
            "checkpoint_async_pending",
            help="write-behind checkpoint saves queued or in flight",
        )._default()
        self._m_write = registry.summary(
            "checkpoint_write_ms",
            help="checkpoint serialize+write+commit time (ms), off "
                 "the training thread for async saves",
        )._default()
        self._m_stall = registry.summary(
            "checkpoint_stall_ms",
            help="training-thread stall per checkpoint save (ms): "
                 "host-snapshot copy only for async, the full write "
                 "for sync",
        )._default()
        self._m_commit = registry.summary(
            "checkpoint_commit_barrier_ms",
            help="two-phase commit barrier wait per sharded save (ms)",
        )._default()
        self._m_scrub = registry.counter(
            "checkpoint_scrub_corrupt_total",
            help="corrupt checkpoint components found by the scrubber",
        )._default()
        self._m_repair = registry.counter(
            "checkpoint_repair_total",
            help="checkpoint components repaired from the replica "
                 "store",
        )._default()
        if scrub_interval_s is not None:
            self.start_scrubber(scrub_interval_s)

    # -- naming ---------------------------------------------------------

    def _zip_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}.zip"

    def _manifest_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}.json"

    def _dir_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}"

    def _quarantine_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}.quarantined"

    def _artifact_file_name(self, step: int, name: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", name):
            raise ValueError(
                f"artifact name {name!r} must be filename-safe "
                "(letters/digits/dot/underscore/dash)"
            )
        return f"{self.prefix}-{step:08d}.{name}.aot"

    # -- write ----------------------------------------------------------

    def save(self, model, artifacts=None, mode: Optional[str] = None):
        """Checkpoint ``model`` at its current iteration count.
        Re-saving the same step overwrites that version atomically.
        ``artifacts`` (optional ``{name: bytes}``, e.g. the AOT
        executables from ``compile.aot.export_serving_bundle``) are
        written as side files and CRC-recorded in the manifest's
        ``artifacts`` map — verified on read, but never gating the
        model restore.

        ``mode="sync"`` (default) writes on the calling thread and
        returns the :class:`CheckpointInfo`; ``mode="async"`` takes
        only the host snapshot here, hands the write to the
        background writer, and returns an :class:`AsyncSaveHandle`
        immediately. A sync save drains the writer first, so it is
        always the newest bytes on disk when it returns."""
        mode = self.mode if mode is None else mode
        if mode not in ("sync", "async"):
            raise ValueError(f"mode must be 'sync' or 'async', "
                             f"got {mode!r}")
        t0 = time.perf_counter()
        payload = self._snapshot_payload(model, artifacts)
        flightrec.record_event(
            "checkpoint_save_start", step=payload["step"], mode=mode,
            shards=(self.commit.nshards if self.commit is not None
                    else None))
        if mode == "async":
            handle = AsyncSaveHandle(payload["step"])
            with self._wcond:
                if self._wpending is not None:
                    _, old = self._wpending
                    old._resolve(None, superseded=True)
                    logger.info(
                        "write-behind save of step %d superseded by "
                        "step %d", old.step, handle.step)
                self._wpending = (payload, handle)
                self._ensure_writer_locked()
                self._set_pending_gauge_locked()
                self._wcond.notify_all()
            self._m_stall.observe((time.perf_counter() - t0) * 1000.0)
            return handle
        # sync: order after any in-flight background write, then
        # write inline — the emergency/preemption path rides this, so
        # when it returns the checkpoint is durable, complete, and
        # the newest on disk
        self.flush()
        info = self._write_payload(payload)
        self._m_stall.observe((time.perf_counter() - t0) * 1000.0)
        return info

    def _snapshot_payload(self, model, artifacts) -> dict:
        """The training-thread half of a save: buffer-isolated host
        copies of everything the writer needs, so the model may keep
        training the moment this returns."""
        from deeplearning4j_tpu.resilience.guard import guard_state_doc
        from deeplearning4j_tpu.util.model_serializer import (
            snapshot_model,
        )

        return {
            "step": int(model.iteration_count),
            "epoch": int(getattr(model, "epoch_count", 0)),
            "snap": snapshot_model(model),
            "artifacts": dict(artifacts or {}),
            "zero": dict(getattr(model, "_zero_layout", None) or {})
            or None,
            "guard": guard_state_doc(model),
        }

    # -- the background writer ------------------------------------------

    def _ensure_writer_locked(self) -> None:
        if self._wthread is not None and self._wthread.is_alive():
            return
        self._wstop = False
        self._wthread = threading.Thread(
            target=self._writer_loop, name="ckpt-writer", daemon=True)
        self._wthread.start()

    def _set_pending_gauge_locked(self) -> None:
        self._m_pending.set(
            float((self._wpending is not None) + self._wbusy))

    def _writer_loop(self) -> None:
        while True:
            with self._wcond:
                while self._wpending is None and not self._wstop:
                    self._wcond.wait()
                if self._wpending is None and self._wstop:
                    return
                payload, handle = self._wpending
                self._wpending = None
                self._wbusy = True
                self._set_pending_gauge_locked()
            try:
                info = self._write_payload(payload)
                handle._resolve(info)
            except BaseException as e:
                handle._resolve(None, error=e)
                logger.warning(
                    "write-behind checkpoint save of step %d failed: "
                    "%r", handle.step, e)
            finally:
                with self._wcond:
                    self._wbusy = False
                    self._set_pending_gauge_locked()
                    self._wcond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Drain the write-behind writer: block until no save is
        queued or in flight. Returns False on timeout (the writer
        keeps going; only the wait gives up)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._wcond:
            while self._wpending is not None or self._wbusy:
                if deadline is None:
                    self._wcond.wait(1.0)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._wcond.wait(remaining)
        return True

    def stop(self, timeout: Optional[float] = None) -> None:
        """Flush pending writes, stop the writer thread and the
        scrubber. The manager stays usable (sync saves; a subsequent
        async save restarts the writer)."""
        self.flush(timeout)
        with self._wcond:
            self._wstop = True
            self._wcond.notify_all()
            t = self._wthread
            self._wthread = None
        if t is not None:
            t.join(timeout=5)
        self.stop_scrubber()

    # -- the write itself -----------------------------------------------

    def _write_payload(self, payload: dict) -> CheckpointInfo:
        from deeplearning4j_tpu.observability.trace import get_tracer

        step = payload["step"]
        t0 = time.perf_counter()
        sharded = self.commit is not None
        with get_tracer().start_span("checkpoint.save", attrs={
            "step": step, "prefix": self.prefix,
            "sharded": sharded,
        }) as span:
            if sharded:
                with self._wcond:
                    self._active_steps.add(step)
                try:
                    info = self._write_sharded(payload)
                except CheckpointCommitAbortedException as e:
                    flightrec.record_event(
                        "checkpoint_abort", step=step,
                        reason=str(e)[:200])
                    span.set_attr("outcome", "aborted")
                    raise
                finally:
                    with self._wcond:
                        self._active_steps.discard(step)
            else:
                info = self._write_zip(payload)
            ms = (time.perf_counter() - t0) * 1000.0
            self._m_write.observe(ms)
            flightrec.record_event(
                "checkpoint_commit", step=step,
                ms=round(ms, 3), shards=info.nshards)
            span.set_attr("bytes", info.size)
        return info

    def _write_zip(self, payload: dict) -> CheckpointInfo:
        from deeplearning4j_tpu.util.model_serializer import (
            write_snapshot,
        )

        step, epoch = payload["step"], payload["epoch"]
        zpath = self.directory / self._zip_name(step)
        write_snapshot(payload["snap"], zpath)  # atomic + fsync
        crc, size = _crc32_of(zpath)
        artifact_map = {}
        for name, data in sorted(payload["artifacts"].items()):
            fname = self._artifact_file_name(step, name)
            atomic_write_bytes(self.directory / fname, data)
            artifact_map[name] = {
                "file": fname,
                "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                "size": len(data),
            }
        info = CheckpointInfo(
            step=step, epoch=epoch, file=zpath.name, crc32=crc,
            size=size, artifacts=artifact_map,
            zero=payload["zero"], guard=payload["guard"],
        )
        # manifest lands after the zip: a crash between the two
        # leaves an orphan zip that available() ignores, never a
        # manifest pointing at a missing/half zip
        atomic_write_bytes(
            self.directory / self._manifest_name(step),
            json.dumps(info.to_manifest(), indent=2).encode(),
        )
        self._clear_quarantine(step)
        self._mirror(info)
        self._prune()
        return info

    def _write_sharded(self, payload: dict) -> CheckpointInfo:
        from deeplearning4j_tpu.util.model_serializer import (
            snapshot_conf_doc, snapshot_flat_arrays,
        )

        step, epoch = payload["step"], payload["epoch"]
        rank = int(self.commit.rank)
        nshards = max(int(self.commit.nshards), 1)
        dirname = self._dir_name(step)
        dpath = self.directory / dirname
        dpath.mkdir(parents=True, exist_ok=True)
        flat = snapshot_flat_arrays(payload["snap"])
        mine = sorted(flat)[rank::nshards]
        buf = io.BytesIO()
        np.savez(buf, **{k: flat[k] for k in mine})
        data = buf.getvalue()
        fname = f"shard-{rank}.npz"
        atomic_write_bytes(dpath / fname, data)
        digest = {
            "rank": rank, "file": fname,
            "crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "size": len(data), "keys": len(mine),
        }
        # phase 1: every rank's shard is durable before anyone may
        # commit — leaving this barrier hands rank 0 all digests
        t0 = time.perf_counter()
        got = self.commit.barrier(f"{self.prefix}:{step}:shards",
                                  digest)
        info: Optional[CheckpointInfo] = None
        if rank == 0:
            shards_doc = {}
            for p in got.values():
                shards_doc[str(int(p["rank"]))] = {
                    "file": str(p["file"]), "crc32": int(p["crc32"]),
                    "size": int(p["size"]), "keys": int(p["keys"]),
                }
            artifact_map = {}
            for name, adata in sorted(payload["artifacts"].items()):
                self._artifact_file_name(step, name)  # validates name
                rel = f"{dirname}/{name}.aot"
                atomic_write_bytes(self.directory / rel, adata)
                artifact_map[name] = {
                    "file": rel,
                    "crc32": zlib.crc32(adata) & 0xFFFFFFFF,
                    "size": len(adata),
                }
            info = CheckpointInfo(
                step=step, epoch=epoch, file=dirname, crc32=0, size=0,
                format=SHARDED_MANIFEST_FORMAT, artifacts=artifact_map,
                zero=payload["zero"], guard=payload["guard"],
                nshards=len(shards_doc), shards=shards_doc,
                dir=dirname, model=snapshot_conf_doc(payload["snap"]),
            )
            # THE commit point: the manifest lands last, atomic +
            # fsync'd — until it exists this directory is invisible
            # to restore and fair game for GC
            atomic_write_bytes(
                dpath / "manifest.json",
                json.dumps(info.to_manifest(), indent=2).encode(),
            )
        # phase 2: peers block until the manifest is durable (or the
        # epoch moved). Once rank 0 has written the manifest the
        # checkpoint IS committed — a phase-2 abort after that point
        # is a reporting hiccup for rank 0, a real abort for peers
        # (they cannot know whether the manifest landed).
        try:
            self.commit.barrier(f"{self.prefix}:{step}:commit",
                                {"rank": rank})
        except CheckpointCommitAbortedException:
            if info is None:
                raise
            logger.warning(
                "commit barrier phase 2 of step %d aborted after the "
                "manifest was written; checkpoint is committed", step)
        self._m_commit.observe((time.perf_counter() - t0) * 1000.0)
        if info is None:
            doc = json.loads((dpath / "manifest.json").read_text())
            info = CheckpointInfo.from_manifest(doc)
        self._clear_quarantine(step)
        self._mirror(info, shard_rank=rank, shard_bytes=data)
        if rank == 0:
            self._prune()
        return info

    # -- replica mirroring ----------------------------------------------

    def _mirror(self, info: CheckpointInfo, shard_rank=None,
                shard_bytes=None) -> None:
        """Mirror a just-committed checkpoint to the replica store
        (best-effort: a mirror failure is logged, never fails the
        save — the local copy is already durable). Sharded saves
        mirror only this rank's shard; rank 0 adds the manifest and
        artifacts. Keys are the paths relative to the manager
        directory, so repair is a straight read-back."""
        if self.replica is None:
            return
        try:
            if info.is_sharded:
                d = info.dir or info.file
                if shard_rank is not None and shard_bytes is not None:
                    ent = info.shards.get(str(shard_rank))
                    if ent:
                        self.replica.write(f"{d}/{ent['file']}",
                                           shard_bytes)
                if shard_rank in (None, 0):
                    self.replica.write(
                        f"{d}/manifest.json",
                        (self.directory / d / "manifest.json"
                         ).read_bytes())
                    for ent in info.artifacts.values():
                        rel = ent.get("file")
                        if rel:
                            self.replica.write(
                                rel,
                                (self.directory / rel).read_bytes())
            else:
                self.replica.write(
                    info.file,
                    (self.directory / info.file).read_bytes())
                self.replica.write(
                    self._manifest_name(info.step),
                    (self.directory
                     / self._manifest_name(info.step)).read_bytes())
                for ent in info.artifacts.values():
                    rel = ent.get("file")
                    if rel:
                        self.replica.write(
                            rel, (self.directory / rel).read_bytes())
        except Exception as e:
            logger.warning(
                "replica mirror of step %d failed (local copy is "
                "durable): %r", info.step, e)

    # -- retention ------------------------------------------------------

    def _delete_version(self, info: CheckpointInfo) -> None:
        if info.is_sharded:
            shutil.rmtree(self.directory / (info.dir or info.file),
                          ignore_errors=True)
        else:
            names = [info.file, self._manifest_name(info.step)]
            for name in names:
                try:
                    os.unlink(self.directory / name)
                except OSError:
                    pass
        for a in info.artifacts.values():
            if isinstance(a, dict) and a.get("file"):
                try:
                    os.unlink(self.directory / a["file"])
                except OSError:
                    pass
        self._clear_quarantine(info.step)

    def _prune(self) -> None:
        versions = self.available()
        protected: set = set()
        if self.protect is not None:
            try:
                protected = {int(s) for s in self.protect()}
            except Exception:
                # a broken guard must fail SAFE: protect everything
                logger.warning("checkpoint protect callable failed; "
                               "skipping pruning", exc_info=True)
                return
        for info in versions[:-self.keep_last]:
            if info.step in protected:
                continue  # journal-referenced: never delete
            self._delete_version(info)
        self._gc_uncommitted(versions)

    def _gc_uncommitted(self, versions: List[CheckpointInfo]) -> None:
        """Remove shard directories whose commit never happened (no
        manifest): a torn two-phase commit, a host dead mid-save, or
        an aborted barrier. Never touches a step currently being
        written, and ages unknown directories past ``gc_grace_s``
        before collecting (a peer's save may still be in flight);
        directories below the newest committed step are garbage
        immediately."""
        newest = versions[-1].step if versions else -1
        pat = re.compile(re.escape(self.prefix) + r"-(\d{8})\Z")
        now = time.time()
        for p in self.directory.iterdir():
            if not p.is_dir():
                continue
            m = pat.fullmatch(p.name)
            if not m or (p / "manifest.json").exists():
                continue
            step = int(m.group(1))
            with self._wcond:
                if step in self._active_steps:
                    continue
            try:
                age = now - p.stat().st_mtime
            except OSError:
                continue
            if step < newest or age >= self.gc_grace_s:
                shutil.rmtree(p, ignore_errors=True)
                flightrec.record_event("checkpoint_gc", step=step)
                logger.info(
                    "collected uncommitted checkpoint directory %s",
                    p.name)

    # -- read -----------------------------------------------------------

    def available(self) -> List[CheckpointInfo]:
        """Committed versions, oldest first: format-1 sibling
        manifests plus format-2 ``<prefix>-<step>/manifest.json``
        commit points. Orphan zips, uncommitted shard directories
        (manifest never landed) and unreadable manifests are
        skipped."""
        out = []
        fpat = re.compile(re.escape(self.prefix) + r"-(\d{8})\.json\Z")
        dpat = re.compile(re.escape(self.prefix) + r"-(\d{8})\Z")
        for p in sorted(self.directory.iterdir()):
            mp: Optional[Path] = None
            if p.is_file() and fpat.fullmatch(p.name):
                mp = p
            elif p.is_dir() and dpat.fullmatch(p.name):
                mp = p / "manifest.json"
                if not mp.is_file():
                    continue  # uncommitted: restore must not see it
            if mp is None:
                continue
            try:
                out.append(CheckpointInfo.from_manifest(
                    json.loads(mp.read_text())
                ))
            except (ValueError, KeyError, OSError):
                logger.warning("skipping unreadable manifest %s", mp)
        out.sort(key=lambda i: i.step)
        return out

    def list_steps(self) -> List[int]:
        """Step numbers of every committed version, ascending — the
        public enumeration the promoter/shadow loop uses instead of
        touching manifest internals."""
        return [info.step for info in self.available()]

    def latest_step(self) -> Optional[int]:
        """Newest committed step, or None when the store is empty."""
        versions = self.available()
        return versions[-1].step if versions else None

    def last_step(self) -> Optional[int]:
        """Back-compat alias of ``latest_step``."""
        return self.latest_step()

    # -- quarantine ------------------------------------------------------

    def is_quarantined(self, step: int) -> bool:
        return (self.directory
                / self._quarantine_name(step)).exists()

    def quarantine(self, step: int, reason: str = "") -> None:
        """Mark a step corrupt-beyond-repair: restore walks back past
        it (the corrupted-newest fallback, at shard granularity) and
        prune eventually removes it. The marker is a sibling file, so
        quarantining never mutates the (possibly half-readable)
        checkpoint bytes themselves."""
        atomic_write_bytes(
            self.directory / self._quarantine_name(step),
            json.dumps({"step": int(step), "reason": reason,
                        "time": time.time()}).encode(),
        )
        flightrec.record_event("checkpoint_quarantined", step=step,
                               reason=reason[:200])
        logger.warning("checkpoint step %d quarantined: %s", step,
                       reason)

    def _clear_quarantine(self, step: int) -> None:
        try:
            os.unlink(self.directory / self._quarantine_name(step))
        except OSError:
            pass

    # -- verification ----------------------------------------------------

    def _corrupt_components(self, info: CheckpointInfo
                            ) -> List[Tuple[str, int, int]]:
        """Components of ``info`` whose on-disk bytes no longer match
        the manifest, as (relpath, expected_crc32, expected_size) —
        shard granularity for format 2, the whole zip for format 1."""
        bad = []
        if info.is_sharded:
            d = info.dir or info.file
            for _, ent in sorted(info.shards.items(),
                                 key=lambda kv: int(kv[0])):
                rel = f"{d}/{ent['file']}"
                try:
                    crc, size = _crc32_of(self.directory / rel)
                except OSError:
                    crc, size = -1, -1
                if (crc != int(ent["crc32"])
                        or size != int(ent["size"])):
                    bad.append((rel, int(ent["crc32"]),
                                int(ent["size"])))
        else:
            try:
                crc, size = _crc32_of(self.directory / info.file)
            except OSError:
                crc, size = -1, -1
            if crc != info.crc32 or size != info.size:
                bad.append((info.file, info.crc32, info.size))
        return bad

    def verify(self, info: CheckpointInfo) -> bool:
        """CRC + size + container-structure check without restoring
        (zip structure for format 1, npz readability per shard for
        format 2)."""
        if self._corrupt_components(info):
            return False
        try:
            if info.is_sharded:
                d = info.dir or info.file
                for _, ent in info.shards.items():
                    with np.load(self.directory / d / ent["file"],
                                 allow_pickle=False) as z:
                        list(z.files)
                return True
            with zipfile.ZipFile(self.directory / info.file) as zf:
                return zf.testzip() is None
        except (OSError, ValueError, zipfile.BadZipFile):
            return False

    def _repair(self, info: CheckpointInfo,
                bad: List[Tuple[str, int, int]]) -> bool:
        """Re-fetch corrupt components from the replica store; each
        replacement must match the manifest CRC before it lands
        (atomically). True iff every bad component was repaired."""
        if self.replica is None:
            return False
        for rel, crc, size in bad:
            try:
                data = self.replica.read(rel)
            except Exception as e:
                logger.warning(
                    "repair of %s from replica failed: %r", rel, e)
                return False
            if (len(data) != size
                    or (zlib.crc32(data) & 0xFFFFFFFF) != crc):
                logger.warning(
                    "replica copy of %s fails the manifest CRC too; "
                    "cannot repair", rel)
                return False
            atomic_write_bytes(self.directory / rel, data)
            self._m_repair.inc()
            flightrec.record_event("checkpoint_repair",
                                   step=info.step, file=rel)
            logger.info("repaired %s from the replica store", rel)
        return True

    # -- scrub ----------------------------------------------------------

    def scrub_once(self) -> dict:
        """One scrub pass: re-verify every committed version's CRCs
        at shard granularity; repair corrupt components from the
        replica when possible, quarantine the step otherwise. Returns
        a summary dict (checked/corrupt/repaired/quarantined)."""
        report = {"checked": 0, "corrupt": 0, "repaired": 0,
                  "quarantined": []}
        for info in self.available():
            if self.is_quarantined(info.step):
                continue
            report["checked"] += 1
            bad = self._corrupt_components(info)
            if not bad:
                continue
            report["corrupt"] += len(bad)
            self._m_scrub.inc(len(bad))
            flightrec.record_event(
                "checkpoint_scrub_corrupt", step=info.step,
                components=[b[0] for b in bad])
            if self._repair(info, bad):
                report["repaired"] += len(bad)
            else:
                self.quarantine(
                    info.step,
                    reason="scrub: " + ", ".join(b[0] for b in bad))
                report["quarantined"].append(info.step)
        return report

    def start_scrubber(self, interval_s: float) -> None:
        """Start the background scrubber (idempotent)."""
        if self._scrub_thread is not None \
                and self._scrub_thread.is_alive():
            return
        self.scrub_interval_s = float(interval_s)
        stop = threading.Event()
        self._scrub_stop = stop

        def _loop():
            while not stop.wait(self.scrub_interval_s):
                try:
                    self.scrub_once()
                except Exception:
                    logger.warning("checkpoint scrub pass failed",
                                   exc_info=True)

        self._scrub_thread = threading.Thread(
            target=_loop, name="ckpt-scrubber", daemon=True)
        self._scrub_thread.start()

    def stop_scrubber(self) -> None:
        if self._scrub_stop is not None:
            self._scrub_stop.set()
        if self._scrub_thread is not None:
            self._scrub_thread.join(timeout=5)
        self._scrub_thread = None
        self._scrub_stop = None

    # -- artifacts -------------------------------------------------------

    def load_artifact(self, info: CheckpointInfo,
                      name: str) -> Optional[bytes]:
        """Bytes of one named side artifact, CRC-verified against the
        manifest — or ``None`` when absent, unreadable, or corrupted
        (logged; the consumer falls back to computing the artifact's
        content, e.g. JIT-compiling instead of loading AOT). Never
        raises and never affects model-restore eligibility."""
        entry = info.artifacts.get(name)
        if not isinstance(entry, dict) or not entry.get("file"):
            return None
        path = self.directory / entry["file"]
        try:
            data = path.read_bytes()
        except OSError:
            logger.warning("artifact %r of step %d is missing (%s)",
                           name, info.step, path)
            return None
        if (len(data) != int(entry.get("size", -1))
                or (zlib.crc32(data) & 0xFFFFFFFF)
                != int(entry.get("crc32", -1))):
            logger.warning(
                "artifact %r of step %d failed CRC verification; "
                "ignoring it", name, info.step,
            )
            return None
        return data

    def load_artifacts(self, info: CheckpointInfo) -> dict:
        """All verifiable side artifacts of ``info`` as
        ``{name: bytes}`` (corrupted/missing ones silently absent)."""
        out = {}
        for name in info.artifacts:
            data = self.load_artifact(info, name)
            if data is not None:
                out[name] = data
        return out

    # -- restore ---------------------------------------------------------

    def restore(self, info: CheckpointInfo, load_updater: bool = True):
        """Restore one specific version (verified; quarantined steps
        fail verification by definition). A corrupt component is
        repaired from the replica first when one is configured —
        only then does the version fail."""
        from deeplearning4j_tpu.util.model_serializer import (
            model_from_flat, restore_model,
        )

        if self.is_quarantined(info.step):
            raise CheckpointCorruptedException(
                f"checkpoint step {info.step} is quarantined")
        if not self.verify(info):
            bad = self._corrupt_components(info)
            if not (bad and self._repair(info, bad)
                    and self.verify(info)):
                raise CheckpointCorruptedException(
                    f"checkpoint step {info.step} ({info.file}) "
                    "failed verification")
        if info.is_sharded:
            d = info.dir or info.file
            flat: Dict[str, np.ndarray] = {}
            for _, ent in sorted(info.shards.items(),
                                 key=lambda kv: int(kv[0])):
                with np.load(self.directory / d / ent["file"],
                             allow_pickle=False) as z:
                    for k in z.files:
                        flat[k] = z[k]
            return model_from_flat(info.model, flat,
                                   load_updater=load_updater)
        return restore_model(
            self.directory / info.file, load_updater=load_updater
        )

    def restore_latest(self, load_updater: bool = True):
        """(model, info) for the newest restorable version, falling
        back to earlier versions when the newest is corrupted or
        quarantined — the recovery path a preemption mid-save
        exercises, extended to shard granularity. Raises
        ``CheckpointCorruptedException`` when no version survives."""
        from deeplearning4j_tpu.observability.trace import get_tracer

        with get_tracer().start_span(
            "checkpoint.restore", attrs={"prefix": self.prefix},
        ) as span:
            versions = self.available()
            if not versions:
                span.set_attr("outcome", "none_available")
                raise CheckpointCorruptedException(
                    f"no checkpoints under {self.directory}"
                )
            fallbacks = 0
            for info in reversed(versions):
                try:
                    model = self.restore(info,
                                         load_updater=load_updater)
                except CheckpointCorruptedException:
                    logger.warning(
                        "checkpoint step %d failed verification; "
                        "falling back to the previous version",
                        info.step,
                    )
                    fallbacks += 1
                    continue
                except Exception:
                    # a manifest that verifies but won't deserialize
                    # is corruption too (valid zip, mangled npz member)
                    logger.warning(
                        "checkpoint step %d failed to deserialize; "
                        "falling back to the previous version",
                        info.step, exc_info=True,
                    )
                    fallbacks += 1
                    continue
                span.set_attr("step", info.step)
                span.set_attr("fallbacks", fallbacks)
                return model, info
            span.set_attr("outcome", "all_corrupted")
            raise CheckpointCorruptedException(
                f"all {len(versions)} checkpoint versions under "
                f"{self.directory} failed verification"
            )


def restore_into(model, source, load_updater: bool = True):
    """Restore checkpoint state INTO an existing model instance — the
    resume primitive. ``source`` is a CheckpointManager (newest
    restorable version wins), a CheckpointInfo-bearing (manager, info)
    pair, or a checkpoint zip path. Copies params, layer state, updater
    state, and the step/epoch counters; the caller's jitted programs
    stay valid because shapes/dtypes are unchanged (enforced by a
    config identity check).

    Returns ``(model, step)``.
    """
    from deeplearning4j_tpu.util.model_serializer import restore_model

    info = None
    if isinstance(source, CheckpointManager):
        restored, info = source.restore_latest(load_updater=load_updater)
    elif (isinstance(source, tuple) and len(source) == 2
            and isinstance(source[0], CheckpointManager)):
        manager, info = source
        restored = manager.restore(info, load_updater=load_updater)
    else:
        restored = restore_model(source, load_updater=load_updater)

    if json.dumps(model.conf.to_dict(), sort_keys=True) != json.dumps(
        restored.conf.to_dict(), sort_keys=True
    ):
        raise ValueError(
            "checkpoint configuration does not match this model — "
            "restore into a fresh model via CheckpointManager.restore_"
            "latest()/restore_model() instead"
        )
    model.params = restored.params
    model.state = restored.state
    if load_updater and restored.updater_state is not None:
        model.updater_state = restored.updater_state
        # checkpoints hold canonical updater state: a model that was
        # ZeRO-sharded is canonical again until its trainer re-places
        # (and re-shards) — possibly on a different-sized mesh
        if getattr(model, "_zero_layout", None):
            model._zero_layout = None
    model.iteration_count = restored.iteration_count
    model.epoch_count = restored.epoch_count
    if info is not None and info.guard:
        # bitwise-reproducible skips: the EWMA scalars and skip/
        # quarantine ledgers come back exactly as saved, so a resumed
        # defended run replays the identical trip decisions
        from deeplearning4j_tpu.resilience.guard import (
            apply_guard_state_doc,
        )

        apply_guard_state_doc(model, info.guard)
    return model, restored.iteration_count


class CheckpointListener(IterationListener):
    """Checkpoint every N iterations through the ``IterationListener``
    SPI (reference ``CheckpointListener`` analog, atomic + verified).
    Attach to a model (``model.listeners``) or pass the manager to the
    trainer — both fit loops invoke ``iteration_done`` per step. With a
    ``mode="async"`` manager the save is write-behind: ``last_saved``
    holds the :class:`AsyncSaveHandle` until it resolves."""

    def __init__(self, manager: CheckpointManager, frequency: int = 100):
        self.manager = manager
        self.frequency = max(int(frequency), 1)
        self.last_saved = None

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.last_saved = self.manager.save(model)
