"""Atomic, versioned training checkpoints.

The reference's ``CheckpointListener`` (deeplearning4j-nn) wrote
``checkpoint_<n>_<name>.zip`` files with a ``checkpoint.txt`` index but
no atomicity or verification story — a crash mid-save truncated the
newest zip and the next restore exploded. Here every checkpoint is:

- **atomic**: the zip is written to a temp file in the target
  directory and ``os.replace``d into place, so a crash at any point
  leaves either the complete new version or nothing;
- **versioned**: named ``<prefix>-<step 8-digit>.zip`` by the model's
  iteration count, with a retention window (``keep_last``);
- **verified**: a sibling ``<prefix>-<step>.json`` manifest records
  step/epoch/CRC-32/size; restore checks the zip against it and falls
  back to the previous version when the newest fails (the
  corrupted-tail case a preemption mid-upload produces), raising
  ``CheckpointCorruptedException`` only when no version survives.

Manifest format (version 1), one JSON object per checkpoint:

    {"format": 1, "step": 128, "epoch": 2,
     "file": "checkpoint-00000128.zip",
     "crc32": 2914207069, "size": 18007,
     "artifacts": {"aot-output-b8": {
         "file": "checkpoint-00000128.aot-output-b8.aot",
         "crc32": 1234567, "size": 40960}}}

The optional ``artifacts`` map carries named side blobs — AOT-
exported executables (``compile/aot.py``) ride here — each written
atomically next to the zip and CRC-verified on read by the SAME
manifest machinery as the model zip. The asymmetry is deliberate:
a corrupt *model* zip fails that version (restore falls back to the
previous one), while a corrupt *artifact* only disables that
artifact (``load_artifact`` returns None and the consumer JITs) —
a lost executable costs a compile, never a restore. Manifests
without the field parse as ``artifacts={}`` (old checkpoints keep
restoring).

``CheckpointListener`` plugs the manager into any fit loop via the
``IterationListener`` SPI (``optimize/listeners.py``).
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import zipfile
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional, Tuple

from deeplearning4j_tpu.exceptions import CheckpointCorruptedException
from deeplearning4j_tpu.optimize.listeners import IterationListener

logger = logging.getLogger(__name__)

MANIFEST_FORMAT = 1


def atomic_write_bytes(path, data: bytes) -> None:
    """Write ``data`` to ``path`` via temp-file + ``os.replace`` in the
    same directory (rename is atomic only within a filesystem)."""
    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _crc32_of(path, chunk: int = 1 << 20) -> Tuple[int, int]:
    """(crc32, size) of a file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
            size += len(b)
    return crc & 0xFFFFFFFF, size


@dataclass(frozen=True)
class CheckpointInfo:
    """One verified-writable checkpoint version. ``artifacts`` maps
    artifact name -> {file, crc32, size} for side blobs (AOT
    executables) that ride the manifest's CRC story without gating
    the model restore."""

    step: int
    epoch: int
    file: str   # zip filename, relative to the manager directory
    crc32: int
    size: int
    format: int = MANIFEST_FORMAT
    artifacts: dict = field(default_factory=dict)
    # ZeRO layout the model trained under at save time (e.g.
    # {"shards": 8}) — informational: the zip always holds canonical
    # (gathered) updater state, so restore works on ANY mesh; the
    # field lets operators see which runs were sharded. Manifests
    # without it parse as zero=None (old checkpoints keep restoring).
    zero: Optional[dict] = None
    # Anomaly-defense trajectory state (resilience.guard_state_doc):
    # statistical-guard EWMA scalars as bitwise-exact floats, the
    # guard's skipped-batch ledger, and the data-plane quarantine
    # ledger. Restoring it makes a killed+resumed defended run replay
    # the identical skip decisions. Manifests without it parse as
    # guard=None (old checkpoints keep restoring).
    guard: Optional[dict] = None

    def to_manifest(self) -> dict:
        doc = {
            "format": self.format, "step": self.step,
            "epoch": self.epoch, "file": self.file,
            "crc32": self.crc32, "size": self.size,
        }
        if self.artifacts:
            doc["artifacts"] = self.artifacts
        if self.zero:
            doc["zero"] = self.zero
        if self.guard:
            doc["guard"] = self.guard
        return doc

    @classmethod
    def from_manifest(cls, doc: dict) -> "CheckpointInfo":
        return cls(
            step=int(doc["step"]), epoch=int(doc.get("epoch", 0)),
            file=doc["file"], crc32=int(doc["crc32"]),
            size=int(doc["size"]),
            format=int(doc.get("format", MANIFEST_FORMAT)),
            artifacts=dict(doc.get("artifacts") or {}),
            zero=dict(doc["zero"]) if doc.get("zero") else None,
            guard=dict(doc["guard"]) if doc.get("guard") else None,
        )


class CheckpointManager:
    """Atomic versioned checkpoint store over a local directory.

    ``save(model)`` stamps the version from ``model.iteration_count``;
    ``restore_latest()`` walks versions newest-first, skipping any that
    fail CRC/zip verification (with a warning), and returns the
    restored model + its info. Cloud replication composes on top:
    upload the directory with ``StorageUploader`` over a
    ``RetryingObjectStore`` (object-store PUTs are already atomic).
    """

    def __init__(self, directory, keep_last: int = 3,
                 prefix: str = "checkpoint", protect=None):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        if not re.fullmatch(r"[A-Za-z0-9._]+", prefix):
            raise ValueError(
                f"prefix {prefix!r} must be filename-safe "
                "(letters/digits/dot/underscore)"
            )
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_last = keep_last
        self.prefix = prefix
        # retention guard: a callable returning step numbers that
        # pruning must NEVER delete, consulted at each save — the
        # promotion journal wires ``journal.referenced_steps`` here so
        # a rollback target outlives the keep_last window
        self.protect = protect

    # -- naming ---------------------------------------------------------

    def _zip_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}.zip"

    def _manifest_name(self, step: int) -> str:
        return f"{self.prefix}-{step:08d}.json"

    def _artifact_file_name(self, step: int, name: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", name):
            raise ValueError(
                f"artifact name {name!r} must be filename-safe "
                "(letters/digits/dot/underscore/dash)"
            )
        return f"{self.prefix}-{step:08d}.{name}.aot"

    # -- write ----------------------------------------------------------

    def save(self, model, artifacts=None) -> CheckpointInfo:
        """Checkpoint ``model`` at its current iteration count.
        Re-saving the same step overwrites that version atomically.
        ``artifacts`` (optional ``{name: bytes}``, e.g. the AOT
        executables from ``compile.aot.export_serving_bundle``) are
        written as sibling files and CRC-recorded in the manifest's
        ``artifacts`` map — verified on read, but never gating the
        model restore."""
        from deeplearning4j_tpu.observability.trace import get_tracer
        from deeplearning4j_tpu.resilience.guard import guard_state_doc
        from deeplearning4j_tpu.util.model_serializer import write_model

        step = int(model.iteration_count)
        epoch = int(getattr(model, "epoch_count", 0))
        with get_tracer().start_span("checkpoint.save", attrs={
            "step": step, "prefix": self.prefix,
        }) as span:
            zpath = self.directory / self._zip_name(step)
            write_model(model, zpath)  # atomic (temp + os.replace)
            crc, size = _crc32_of(zpath)
            artifact_map = {}
            for name, data in sorted((artifacts or {}).items()):
                fname = self._artifact_file_name(step, name)
                atomic_write_bytes(self.directory / fname, data)
                artifact_map[name] = {
                    "file": fname,
                    "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                    "size": len(data),
                }
            info = CheckpointInfo(
                step=step, epoch=epoch, file=zpath.name, crc32=crc,
                size=size, artifacts=artifact_map,
                zero=dict(getattr(model, "_zero_layout", None) or {})
                or None,
                guard=guard_state_doc(model),
            )
            # manifest lands after the zip: a crash between the two
            # leaves an orphan zip that available() ignores, never a
            # manifest pointing at a missing/half zip
            atomic_write_bytes(
                self.directory / self._manifest_name(step),
                json.dumps(info.to_manifest(), indent=2).encode(),
            )
            self._prune()
            span.set_attr("bytes", size)
        return info

    def _prune(self) -> None:
        versions = self.available()
        protected: set = set()
        if self.protect is not None:
            try:
                protected = {int(s) for s in self.protect()}
            except Exception:
                # a broken guard must fail SAFE: protect everything
                logger.warning("checkpoint protect callable failed; "
                               "skipping pruning", exc_info=True)
                return
        for info in versions[:-self.keep_last]:
            if info.step in protected:
                continue  # journal-referenced: never delete
            names = [info.file, self._manifest_name(info.step)]
            names.extend(
                a.get("file") for a in info.artifacts.values()
                if isinstance(a, dict) and a.get("file")
            )
            for name in names:
                try:
                    os.unlink(self.directory / name)
                except OSError:
                    pass

    # -- read -----------------------------------------------------------

    def available(self) -> List[CheckpointInfo]:
        """Manifested versions, oldest first. Orphan zips (manifest
        never landed) and unreadable manifests are skipped."""
        out = []
        pat = re.compile(
            re.escape(self.prefix) + r"-(\d{8})\.json\Z"
        )
        for p in sorted(self.directory.iterdir()):
            if not pat.fullmatch(p.name):
                continue
            try:
                out.append(CheckpointInfo.from_manifest(
                    json.loads(p.read_text())
                ))
            except (ValueError, KeyError, OSError):
                logger.warning("skipping unreadable manifest %s", p)
        out.sort(key=lambda i: i.step)
        return out

    def list_steps(self) -> List[int]:
        """Step numbers of every manifested version, ascending — the
        public enumeration the promoter/shadow loop uses instead of
        touching manifest internals."""
        return [info.step for info in self.available()]

    def latest_step(self) -> Optional[int]:
        """Newest manifested step, or None when the store is empty."""
        versions = self.available()
        return versions[-1].step if versions else None

    def last_step(self) -> Optional[int]:
        """Back-compat alias of ``latest_step``."""
        return self.latest_step()

    def verify(self, info: CheckpointInfo) -> bool:
        """CRC + size + zip-structure check without restoring."""
        zpath = self.directory / info.file
        try:
            crc, size = _crc32_of(zpath)
            if crc != info.crc32 or size != info.size:
                return False
            with zipfile.ZipFile(zpath) as zf:
                return zf.testzip() is None
        except (OSError, zipfile.BadZipFile):
            return False

    def load_artifact(self, info: CheckpointInfo,
                      name: str) -> Optional[bytes]:
        """Bytes of one named side artifact, CRC-verified against the
        manifest — or ``None`` when absent, unreadable, or corrupted
        (logged; the consumer falls back to computing the artifact's
        content, e.g. JIT-compiling instead of loading AOT). Never
        raises and never affects model-restore eligibility."""
        entry = info.artifacts.get(name)
        if not isinstance(entry, dict) or not entry.get("file"):
            return None
        path = self.directory / entry["file"]
        try:
            data = path.read_bytes()
        except OSError:
            logger.warning("artifact %r of step %d is missing (%s)",
                           name, info.step, path)
            return None
        if (len(data) != int(entry.get("size", -1))
                or (zlib.crc32(data) & 0xFFFFFFFF)
                != int(entry.get("crc32", -1))):
            logger.warning(
                "artifact %r of step %d failed CRC verification; "
                "ignoring it", name, info.step,
            )
            return None
        return data

    def load_artifacts(self, info: CheckpointInfo) -> dict:
        """All verifiable side artifacts of ``info`` as
        ``{name: bytes}`` (corrupted/missing ones silently absent)."""
        out = {}
        for name in info.artifacts:
            data = self.load_artifact(info, name)
            if data is not None:
                out[name] = data
        return out

    def restore(self, info: CheckpointInfo, load_updater: bool = True):
        """Restore one specific version (verified)."""
        from deeplearning4j_tpu.util.model_serializer import restore_model

        if not self.verify(info):
            raise CheckpointCorruptedException(
                f"checkpoint step {info.step} ({info.file}) failed "
                "verification"
            )
        model = restore_model(
            self.directory / info.file, load_updater=load_updater
        )
        return model

    def restore_latest(self, load_updater: bool = True):
        """(model, info) for the newest restorable version, falling
        back to earlier versions when the newest is corrupted — the
        recovery path a preemption mid-save exercises. Raises
        ``CheckpointCorruptedException`` when no version survives."""
        from deeplearning4j_tpu.observability.trace import get_tracer

        with get_tracer().start_span(
            "checkpoint.restore", attrs={"prefix": self.prefix},
        ) as span:
            versions = self.available()
            if not versions:
                span.set_attr("outcome", "none_available")
                raise CheckpointCorruptedException(
                    f"no checkpoints under {self.directory}"
                )
            fallbacks = 0
            for info in reversed(versions):
                try:
                    model = self.restore(info,
                                         load_updater=load_updater)
                except CheckpointCorruptedException:
                    logger.warning(
                        "checkpoint step %d failed verification; "
                        "falling back to the previous version",
                        info.step,
                    )
                    fallbacks += 1
                    continue
                except Exception:
                    # a manifest that verifies but won't deserialize
                    # is corruption too (valid zip, mangled npz member)
                    logger.warning(
                        "checkpoint step %d failed to deserialize; "
                        "falling back to the previous version",
                        info.step, exc_info=True,
                    )
                    fallbacks += 1
                    continue
                span.set_attr("step", info.step)
                span.set_attr("fallbacks", fallbacks)
                return model, info
            span.set_attr("outcome", "all_corrupted")
            raise CheckpointCorruptedException(
                f"all {len(versions)} checkpoint versions under "
                f"{self.directory} failed verification"
            )


def restore_into(model, source, load_updater: bool = True):
    """Restore checkpoint state INTO an existing model instance — the
    resume primitive. ``source`` is a CheckpointManager (newest
    restorable version wins), a CheckpointInfo-bearing (manager, info)
    pair, or a checkpoint zip path. Copies params, layer state, updater
    state, and the step/epoch counters; the caller's jitted programs
    stay valid because shapes/dtypes are unchanged (enforced by a
    config identity check).

    Returns ``(model, step)``.
    """
    from deeplearning4j_tpu.util.model_serializer import restore_model

    info = None
    if isinstance(source, CheckpointManager):
        restored, info = source.restore_latest(load_updater=load_updater)
    elif (isinstance(source, tuple) and len(source) == 2
            and isinstance(source[0], CheckpointManager)):
        manager, info = source
        restored = manager.restore(info, load_updater=load_updater)
    else:
        restored = restore_model(source, load_updater=load_updater)

    if json.dumps(model.conf.to_dict(), sort_keys=True) != json.dumps(
        restored.conf.to_dict(), sort_keys=True
    ):
        raise ValueError(
            "checkpoint configuration does not match this model — "
            "restore into a fresh model via CheckpointManager.restore_"
            "latest()/restore_model() instead"
        )
    model.params = restored.params
    model.state = restored.state
    if load_updater and restored.updater_state is not None:
        model.updater_state = restored.updater_state
        # checkpoints hold canonical updater state: a model that was
        # ZeRO-sharded is canonical again until its trainer re-places
        # (and re-shards) — possibly on a different-sized mesh
        if getattr(model, "_zero_layout", None):
            model._zero_layout = None
    model.iteration_count = restored.iteration_count
    model.epoch_count = restored.epoch_count
    if info is not None and info.guard:
        # bitwise-reproducible skips: the EWMA scalars and skip/
        # quarantine ledgers come back exactly as saved, so a resumed
        # defended run replays the identical trip decisions
        from deeplearning4j_tpu.resilience.guard import (
            apply_guard_state_doc,
        )

        apply_guard_state_doc(model, info.guard)
    return model, restored.iteration_count


class CheckpointListener(IterationListener):
    """Checkpoint every N iterations through the ``IterationListener``
    SPI (reference ``CheckpointListener`` analog, atomic + verified).
    Attach to a model (``model.listeners``) or pass the manager to the
    trainer — both fit loops invoke ``iteration_done`` per step."""

    def __init__(self, manager: CheckpointManager, frequency: int = 100):
        self.manager = manager
        self.frequency = max(int(frequency), 1)
        self.last_saved: Optional[CheckpointInfo] = None

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.last_saved = self.manager.save(model)
