"""Signal-driven preemption handling.

On the target hardware preemption is the *normal* failure mode:
``cloud/provision.py`` models ``preemptible=True`` TPU VMs, and the
platform delivers SIGTERM with a short grace window before the host
vanishes. The reference stack never handled this in-process (Spark
re-ran lost tasks); here the trainer itself must turn the signal into
an emergency checkpoint before the clock runs out.

:class:`PreemptionHandler` installs SIGTERM/SIGINT handlers (plus a
chaos-injectable simulated notice, :meth:`PreemptionHandler.notify`)
that set an atomic flag. Every fit driver — ``DistributedTrainer.fit``,
both engines' epoch loop (``nn/core.fit_batches``), the continual
trainer, and early stopping — polls the flag at step boundaries via
:func:`check_fit` and, when set, runs :meth:`emergency_stop`:

1. **quiesce** — drain the ``AsyncDispatchWindow`` (in-flight steps
   complete; the guard flags are collected) and shut down the
   ``PrefetchIterator`` worker with a bounded join, both in
   try/finally, so the checkpoint below never races a worker thread
   mid-``device_put``;
2. **checkpoint** — write an emergency versioned checkpoint through
   the existing ``CheckpointManager`` (atomic + CRC-manifested; AOT
   artifacts attached when the caller provides them — the continual
   trainer routes through its own ``publish()``);
3. **raise** — :class:`PreemptedException` unwinds the fit; the
   :func:`exit_on_preemption` context manager translates it into a
   documented exit code (see table below) for process-level callers.

Exit codes (catalogued in ARCHITECTURE.md):

- ``EXIT_PREEMPTED`` (75, ``EX_TEMPFAIL``) — preempted AND the
  emergency checkpoint landed; a restart resumes losslessly.
- ``EXIT_PREEMPTED_DIRTY`` (76) — preempted but no checkpoint was
  written (no manager configured, or the save itself failed); a
  restart resumes from the previous published version.

The serving tier reuses the same notice differently: ``ModelServer``
and ``ServingRouter`` register drain callbacks
(:meth:`PreemptionHandler.on_preemption`) so SIGTERM becomes the
existing graceful drain — in-flight requests finish, new work is shed
— instead of a checkpoint.

Everything is injectable for tests: ``notify()`` simulates the signal
without touching process state, the clock is wall-only for the drain
timeout, and handlers restore the previously-installed signal
disposition on :meth:`uninstall`.
"""

from __future__ import annotations

import contextlib
import logging
import signal as _signal
import sys
import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.exceptions import DL4JFaultException

logger = logging.getLogger(__name__)

# sysexits.h EX_TEMPFAIL: "try again later" — exactly what a
# preempted-but-checkpointed trainer means to its supervisor
EXIT_PREEMPTED = 75
# preempted without a durable emergency checkpoint (no manager, or
# the save failed): restart resumes from the previous version
EXIT_PREEMPTED_DIRTY = 76

DEFAULT_SIGNALS = (_signal.SIGTERM, _signal.SIGINT)


class PreemptedException(DL4JFaultException):
    """Raised from a fit loop's step boundary after the emergency
    checkpoint attempt. ``checkpoint`` is the ``CheckpointInfo`` when
    the save landed (None otherwise); ``checkpoint_failed`` is True
    when a save was attempted and raised."""

    def __init__(self, message: str, step: Optional[int] = None,
                 checkpoint=None, checkpoint_failed: bool = False,
                 reason: str = "signal"):
        super().__init__(message)
        self.step = step
        self.checkpoint = checkpoint
        self.checkpoint_failed = checkpoint_failed
        self.reason = reason

    @property
    def exit_code(self) -> int:
        if self.checkpoint is not None and not self.checkpoint_failed:
            return EXIT_PREEMPTED
        return EXIT_PREEMPTED_DIRTY


_lock = threading.Lock()
_active: Optional["PreemptionHandler"] = None


def active_handler() -> Optional["PreemptionHandler"]:
    """The installed handler, or None (no preemption handling)."""
    return _active


def preemption_requested() -> bool:
    """True when a handler is installed and a notice has arrived.
    The no-handler fast path is one global read — cheap enough for
    every step boundary in every fit driver."""
    h = _active
    return h is not None and h.requested


def check_fit(model=None, *, manager=None, window=None, prefetch=None,
              checkpoint_fn: Optional[Callable] = None,
              artifacts=None) -> None:
    """Step-boundary poll used by the fit drivers: no-op until a
    preemption notice arrives, then :meth:`PreemptionHandler.
    emergency_stop` (drain + checkpoint + raise). See module
    docstring for who calls this."""
    h = _active
    if h is None or not h.requested:
        return
    h.emergency_stop(model, manager=manager, window=window,
                     prefetch=prefetch, checkpoint_fn=checkpoint_fn,
                     artifacts=artifacts)


@contextlib.contextmanager
def exit_on_preemption():
    """Process-level wrapper: translate :class:`PreemptedException`
    into the documented exit code::

        with exit_on_preemption():
            trainer.fit(iterator, epochs=50)
    """
    try:
        yield
    except PreemptedException as e:
        logger.info("exiting on preemption (%s): exit code %d",
                    e.reason, e.exit_code)
        sys.exit(e.exit_code)


class PreemptionHandler:
    """Install SIGTERM/SIGINT -> atomic-flag translation (module
    docstring). ``manager`` (a ``CheckpointManager``) is the default
    emergency-checkpoint target when a fit driver has none of its
    own; ``artifact_fn(model)`` supplies the artifacts map attached
    to the emergency save (e.g. the AOT serving bundle).

    Usable as a context manager (install on enter, uninstall on
    exit). ``notify()`` is the chaos-injectable simulated preemption
    notice: identical consequences to the real signal, no process
    state touched — tests drive the whole emergency path with it.
    """

    def __init__(self, manager=None, *,
                 artifact_fn: Optional[Callable] = None,
                 signals=DEFAULT_SIGNALS,
                 drain_timeout: float = 5.0,
                 registry=None):
        self.manager = manager
        self.artifact_fn = artifact_fn
        self.signals = tuple(signals)
        self.drain_timeout = float(drain_timeout)
        self._flag = threading.Event()
        self._reason: str = ""
        self._prev = {}
        self._callbacks: List[Callable] = []
        self._cb_lock = threading.Lock()
        if registry is None:
            from deeplearning4j_tpu.observability.metrics import (
                default_registry,
            )

            registry = default_registry()
        self._m_notices = registry.counter(
            "preemption_notices_total",
            help="preemption notices observed (signals + simulated)",
        )._default()
        self._m_checkpoints = registry.counter(
            "preemption_emergency_checkpoints_total",
            help="emergency checkpoints written on preemption",
        )._default()
        self._m_drain_ms = registry.summary(
            "preemption_drain_ms",
            help="notice -> quiesced-and-checkpointed latency (ms)",
        )._default()

    # -- install / uninstall --------------------------------------------

    def install(self) -> "PreemptionHandler":
        """Install the signal handlers (main thread only — a
        ``signal.signal`` constraint) and make this the process-wide
        active handler that ``check_fit`` consults. The previous
        dispositions are saved for :meth:`uninstall`."""
        global _active
        for sig in self.signals:
            self._prev[sig] = _signal.signal(sig, self._on_signal)
        with _lock:
            _active = self
        return self

    def uninstall(self) -> None:
        """Restore the saved signal dispositions and deactivate."""
        global _active
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):  # non-main thread teardown
                pass
        self._prev.clear()
        with _lock:
            if _active is self:
                _active = None

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- the notice -----------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._flag.is_set()

    @property
    def reason(self) -> str:
        return self._reason

    def clear(self) -> None:
        """Reset the flag (tests; a real notice is never unset)."""
        self._flag.clear()
        self._reason = ""

    def notify(self, reason: str = "simulated") -> None:
        """Deliver a preemption notice. Called by the signal handler
        with the signal name, or directly by chaos tests — the
        simulated notice and the real signal are indistinguishable
        downstream. Idempotent: repeat notices don't re-run
        callbacks."""
        first = not self._flag.is_set()
        self._reason = self._reason or reason
        self._flag.set()
        if not first:
            return
        self._notified_at = time.monotonic()
        self._m_notices.inc()
        logger.warning("preemption notice received (%s)", reason)
        with self._cb_lock:
            callbacks = list(self._callbacks)
        if callbacks:
            # never run drains inside the signal frame: hand them to
            # a thread so the interrupted main thread resumes fast
            t = threading.Thread(
                target=self._run_callbacks, args=(callbacks, reason),
                daemon=True, name="dl4j-preemption-drain",
            )
            t.start()

    def _run_callbacks(self, callbacks, reason) -> None:
        for cb in callbacks:
            try:
                cb(reason)
            except Exception:
                logger.exception("preemption callback failed")

    def _on_signal(self, signum, frame) -> None:
        try:
            name = _signal.Signals(signum).name
        except ValueError:  # pragma: no cover
            name = f"signal-{signum}"
        self.notify(reason=name)

    def on_preemption(self, callback: Callable) -> "PreemptionHandler":
        """Register ``callback(reason)`` to run (on a daemon thread)
        when the notice arrives — the serving tier registers its
        graceful drain here. A callback registered after the notice
        runs immediately on the caller's thread."""
        with self._cb_lock:
            self._callbacks.append(callback)
        if self._flag.is_set():
            self._run_callbacks([callback], self._reason or "signal")
        return self

    # -- the emergency path ---------------------------------------------

    def emergency_stop(self, model=None, *, manager=None, window=None,
                       prefetch=None,
                       checkpoint_fn: Optional[Callable] = None,
                       artifacts=None) -> None:
        """Quiesce -> emergency checkpoint -> raise (module
        docstring). Always raises :class:`PreemptedException`; the
        drain legs run in try/finally so the checkpoint never races a
        worker thread, and a drain fault is chained onto the raised
        exception instead of masking it."""
        t0 = time.monotonic()
        step = int(getattr(model, "iteration_count", 0)) if model is not None else None
        drain_fault: Optional[BaseException] = None
        try:
            try:
                if window is not None:
                    window.drain()
            finally:
                if prefetch is not None:
                    shutdown = getattr(prefetch, "shutdown", None)
                    if shutdown is not None:
                        try:
                            shutdown(timeout=self.drain_timeout,
                                     raise_pending=True)
                        except TypeError:
                            # plain iterators without the bounded
                            # signature (AsyncDataSetIterator)
                            shutdown()
        except Exception as e:
            # the window may surface a guard abort, the prefetch a
            # pending worker fault: neither may cost us the
            # checkpoint — the grace window is already ticking
            drain_fault = e
            logger.warning("drain fault during emergency stop "
                           "(checkpointing anyway): %r", e)
        info = None
        failed = False
        mgr = manager if manager is not None else self.manager
        from deeplearning4j_tpu.observability import flightrec

        rec = flightrec.get_flight_recorder()
        if rec is not None and rec.enabled:
            rec.event("preemption_notice",
                      reason=self._reason or "notice", step=step)
        try:
            if checkpoint_fn is not None:
                info = checkpoint_fn()
            elif mgr is not None and model is not None:
                arts = artifacts
                if arts is None and self.artifact_fn is not None:
                    arts = self.artifact_fn(model)
                if rec is not None and rec.enabled:
                    # the ring rides the emergency manifest as a
                    # CRC-verified artifact: the postmortem (last-N
                    # steps, timings, MFU, events) travels WITH the
                    # checkpoint the resume will load. The dump runs
                    # after the drain above, so its last step record
                    # is the step the checkpoint (and resume) is at.
                    try:
                        arts = dict(arts) if arts else {}
                        arts.setdefault(
                            "flightrec.jsonl",
                            rec.dump_bytes(reason="preemption"),
                        )
                    except Exception:  # never cost us the checkpoint
                        logger.exception(
                            "flight-recorder artifact dump failed")
                # force the synchronous path: it drains any
                # write-behind save first, so the emergency
                # checkpoint is complete, durable, and the newest on
                # disk when the exit code promises "checkpointed"
                try:
                    info = mgr.save(model, artifacts=arts,
                                    mode="sync")
                except TypeError:
                    # duck-typed managers without a mode kwarg
                    info = mgr.save(model, artifacts=arts)
        except Exception:
            failed = True
            logger.exception("emergency checkpoint failed at step %s",
                             step)
        if info is not None and not failed:
            self._m_checkpoints.inc()
            logger.warning(
                "emergency checkpoint written at step %d (%s)",
                getattr(info, "step", -1), self._reason or "notice",
            )
        self._m_drain_ms.observe((time.monotonic() - t0) * 1000.0)
        exc = PreemptedException(
            f"preempted ({self._reason or 'notice'}) at step {step}; "
            + ("emergency checkpoint written"
               if info is not None and not failed
               else "no emergency checkpoint"),
            step=step, checkpoint=info, checkpoint_failed=failed,
            reason=self._reason or "notice",
        )
        if drain_fault is not None:
            exc.__cause__ = drain_fault
        raise exc
