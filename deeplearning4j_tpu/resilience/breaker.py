"""Circuit breaker: fail fast when a dependency is poisoned.

Retry (``retry.py``) handles *transient* faults — a flaky read that
succeeds on attempt 2. A breaker handles *persistent* ones: a model
that raises on every predict, a store whose endpoint is down. Without
one, every request burns a worker (and a retry budget) rediscovering
the same failure; with one, the Nth consecutive failure trips the
circuit and subsequent callers are rejected in microseconds until a
probe proves recovery.

State machine (the classic three-state breaker):

    CLOSED --[failure_threshold consecutive failures]--> OPEN
    OPEN   --[reset_timeout elapsed]-->                  HALF_OPEN
    HALF_OPEN --[probe succeeds]--> CLOSED
    HALF_OPEN --[probe fails]-->    OPEN   (re-stamped, trips += 1)

In HALF_OPEN at most ``half_open_max_probes`` calls are admitted
concurrently; the rest are rejected like OPEN so a recovery probe is
not a thundering herd. The clock is injectable (``clock=``) so tests
drive the OPEN -> HALF_OPEN transition without sleeping, and all
transitions are lock-protected — ``try_acquire``/``record_*`` may be
called from any number of worker threads.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from deeplearning4j_tpu.exceptions import CircuitOpenException

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Reusable three-state breaker (see module docstring).

    Two usage shapes:

    - wrap a callable: ``breaker.call(fn, *args)`` — raises
      ``CircuitOpenException`` when rejected;
    - manual accounting for request pipelines:
      ``try_acquire()`` -> run -> ``record_success()`` /
      ``record_failure()`` (every successful acquire MUST be paired
      with exactly one record call).
    """

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 30.0,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "breaker"):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout < 0:
            raise ValueError("reset_timeout must be >= 0")
        if half_open_max_probes < 1:
            raise ValueError("half_open_max_probes must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.half_open_max_probes = half_open_max_probes
        self.clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probes = 0
        self.trips = 0  # total CLOSED/HALF_OPEN -> OPEN transitions

    # -- state ----------------------------------------------------------

    def _emit_transition(self, frm: str, to: str) -> None:
        """Breaker transitions are rare and operationally loud — they
        go to the global trace/event stream (no-op unless a tracer is
        installed). Called under the breaker lock; the tracer has its
        own lock and never calls back into the breaker."""
        from deeplearning4j_tpu.observability.trace import get_tracer

        get_tracer().event("breaker.transition", attrs={
            "name": self.name, "from": frm, "to": to,
            "consecutive_failures": self._consecutive_failures,
            "trips": self.trips,
        })

    def _state_locked(self) -> str:
        """Current state, applying the lazy OPEN -> HALF_OPEN
        transition (no timer thread: the clock is consulted on use)."""
        if (self._state == OPEN
                and self.clock() - self._opened_at >= self.reset_timeout):
            self._state = HALF_OPEN
            self._probes = 0
            self._emit_transition(OPEN, HALF_OPEN)
        return self._state

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    def retry_after(self) -> float:
        """Seconds until the breaker will admit a half-open probe
        (0.0 unless OPEN)."""
        with self._lock:
            if self._state_locked() != OPEN:
                return 0.0
            return max(
                0.0,
                self.reset_timeout - (self.clock() - self._opened_at),
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state_locked(),
                "trips": self.trips,
                "consecutive_failures": self._consecutive_failures,
            }

    # -- accounting -----------------------------------------------------

    def try_acquire(self) -> bool:
        """Admit or reject one call. CLOSED always admits; OPEN always
        rejects; HALF_OPEN admits up to ``half_open_max_probes``
        concurrent probes. An admitted call must record exactly one
        success or failure."""
        with self._lock:
            s = self._state_locked()
            if s == CLOSED:
                return True
            if s == OPEN:
                return False
            if self._probes < self.half_open_max_probes:
                self._probes += 1
                return True
            return False

    def _trip_locked(self) -> None:
        frm = self._state
        self._state = OPEN
        self._opened_at = self.clock()
        self._probes = 0
        self.trips += 1
        self._emit_transition(frm, OPEN)

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probes = 0
                self._emit_transition(HALF_OPEN, CLOSED)
            self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            s = self._state_locked()
            if s == HALF_OPEN:
                self._trip_locked()  # probe failed: straight back open
            elif (s == CLOSED
                  and self._consecutive_failures >= self.failure_threshold):
                self._trip_locked()

    # -- callable wrapper -----------------------------------------------

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under the breaker, raising
        ``CircuitOpenException`` when rejected. Any exception from
        ``fn`` counts as a failure and propagates."""
        if not self.try_acquire():
            raise CircuitOpenException(
                f"circuit {self.name!r} is {self.state}: "
                f"{self._consecutive_failures} consecutive failures",
                retry_after=self.retry_after(),
            )
        try:
            result = fn(*args, **kwargs)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result
