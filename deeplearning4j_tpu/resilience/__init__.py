"""Fault-tolerant training runtime (net-new vs the reference, whose
Spark layer survived worker loss because parameter-averaging rounds
are restartable by construction — per-step TPU training needs an
explicit subsystem; cf. PAPERS.md "TensorFlow: A system for
large-scale machine learning", which treats checkpoint/recovery as
first-class for the same reason).

Four cooperating pieces:

- **atomic versioned checkpoints** (``checkpoint.py``):
  ``CheckpointManager`` — temp-file + ``os.replace`` writes, CRC-32
  manifests, retention window, corrupted-newest fallback on restore —
  plus ``CheckpointListener`` (the ``IterationListener`` hook) and
  ``restore_into`` (the resume primitive behind
  ``MultiLayerNetwork.resume`` / ``DistributedTrainer.resume``);
- **retry with exponential backoff + jitter** (``retry.py``):
  ``RetryPolicy`` / ``retry_call`` / ``@retrying``, raising
  ``RetryExhaustedException`` past the budget;
- **circuit breaking** (``breaker.py``): ``CircuitBreaker`` — closed
  -> open after N consecutive failures -> half-open probe -> closed —
  so persistent faults fail fast instead of burning retry budgets
  (the serving tier wires it around predict and reload);
- **deadlines** (``deadline.py``): ``Deadline`` — one wall-budget
  across queue wait + execution, expiring as
  ``DeadlineExceededException`` with elapsed/budget;
- **retrying storage** (``store.py``): ``RetryingObjectStore`` over
  any ObjectStore backend, optionally breaker-guarded;
- **deterministic fault injection** (``chaos.py``): ``ChaosPolicy``
  seeded failure schedules, ``FaultyObjectStore``, ``FlakyIterator``,
  and ``PoisonIterator`` (seeded bad-data corruption for the
  validating-pipeline storms);
- **divergence guard** (``guard.py``): in-step NaN/Inf detection on
  loss + gradient global-norm with skip-step or
  rollback-to-last-checkpoint policies, plus the statistical anomaly
  guard (``StatGuardConfig``): device-resident EWMA mean/variance of
  loss and grad-norm, z-score/spike trips reusing the same in-jit
  skip machinery, state checkpointed bitwise in the manifest
  (``guard_state_doc``/``apply_guard_state_doc``);
- **preemption handling** (``preemption.py``): ``PreemptionHandler``
  — SIGTERM/SIGINT (or a simulated notice) -> atomic flag -> drain +
  emergency checkpoint + ``PreemptedException`` at the next step
  boundary, with documented exit codes (``EXIT_PREEMPTED`` /
  ``EXIT_PREEMPTED_DIRTY``) and serving-drain callbacks.
"""

from deeplearning4j_tpu.resilience.breaker import (  # noqa: F401
    CircuitBreaker,
)
from deeplearning4j_tpu.resilience.chaos import (  # noqa: F401
    ChaosError,
    ChaosPolicy,
    FaultyObjectStore,
    FlakyIterator,
    PoisonIterator,
)
from deeplearning4j_tpu.resilience.deadline import (  # noqa: F401
    Deadline,
)
from deeplearning4j_tpu.resilience.checkpoint import (  # noqa: F401
    AsyncSaveHandle,
    CheckpointInfo,
    CheckpointListener,
    CheckpointManager,
    LeaseCommitBarrier,
    LocalCommitBarrier,
    atomic_write_bytes,
    restore_into,
)
from deeplearning4j_tpu.resilience.guard import (  # noqa: F401
    DivergenceGuard,
    StatGuardConfig,
    apply_guard_state_doc,
    guard_state_doc,
)
from deeplearning4j_tpu.resilience.preemption import (  # noqa: F401
    EXIT_PREEMPTED,
    EXIT_PREEMPTED_DIRTY,
    PreemptedException,
    PreemptionHandler,
    active_handler,
    exit_on_preemption,
    preemption_requested,
)
from deeplearning4j_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy,
    retry_call,
    retrying,
)
from deeplearning4j_tpu.resilience.store import (  # noqa: F401
    RetryingObjectStore,
)
