"""ctypes bindings for the native data-loader (see ``loader.cpp`` —
the C++ runtime piece standing in for the reference's libnd4j/DataVec
decode path). Built lazily with g++ on first use and cached next to
the source; every entry point has a numpy fallback so the package
works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "loader.cpp")
_SO = os.path.join(_DIR, "_loader.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[str]:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return _SO
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building if needed; None when no
    toolchain / build failure (callers fall back to numpy)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        stale = (
            os.path.exists(_SO)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
        so = (
            _SO if os.path.exists(_SO) and not stale else _build()
        )
        if so is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _build_failed = True
            return None
        lib.idx3_header.restype = ctypes.c_int
        lib.split_cifar_records.restype = ctypes.c_int
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def _ptr(a: np.ndarray, t):
    return a.ctypes.data_as(ctypes.POINTER(t))


def parse_idx3(buf: bytes) -> np.ndarray:
    """IDX3 image bytes -> uint8 [n, rows*cols] (native; numpy
    fallback mirrors datasets.mnist.read_idx_images)."""
    lib = get_lib()
    arr = np.frombuffer(buf, np.uint8)
    if lib is not None:
        n = ctypes.c_int64()
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.idx3_header(
            _ptr(arr, ctypes.c_uint8), ctypes.c_int64(arr.size),
            ctypes.byref(n), ctypes.byref(rows), ctypes.byref(cols),
        )
        if rc != 0:
            raise ValueError(f"bad IDX3 data (code {rc})")
        d = rows.value * cols.value
        return arr[16:16 + n.value * d].reshape(n.value, d).copy()
    import struct

    magic, n, rows, cols = struct.unpack(">IIII", buf[:16])
    if magic != 2051:
        raise ValueError(f"bad IDX3 magic {magic}")
    return (
        np.frombuffer(buf[16:16 + n * rows * cols], np.uint8)
        .reshape(n, rows * cols).copy()
    )


def normalize_u8(images: np.ndarray) -> np.ndarray:
    """uint8 -> float32 in [0, 1]."""
    images = np.ascontiguousarray(images, np.uint8)
    lib = get_lib()
    out = np.empty(images.shape, np.float32)
    if lib is not None:
        lib.normalize_u8(
            _ptr(images, ctypes.c_uint8), _ptr(out, ctypes.c_float),
            ctypes.c_int64(images.size),
        )
        return out
    return images.astype(np.float32) / 255.0


def assemble_batch(features_u8: np.ndarray, labels_u8: np.ndarray,
                   perm: np.ndarray, n_classes: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Fused gather+normalize+one-hot (one memory pass in C++):
    returns (x float32 [b, d], y float32 [b, n_classes])."""
    features_u8 = np.ascontiguousarray(features_u8, np.uint8)
    labels_u8 = np.ascontiguousarray(labels_u8, np.uint8)
    perm = np.ascontiguousarray(perm, np.int64)
    b = perm.size
    d = features_u8.shape[1]
    lib = get_lib()
    if lib is not None:
        x = np.empty((b, d), np.float32)
        y = np.empty((b, n_classes), np.float32)
        lib.assemble_batch_u8(
            _ptr(features_u8, ctypes.c_uint8),
            _ptr(labels_u8, ctypes.c_uint8),
            _ptr(perm, ctypes.c_int64),
            ctypes.c_int64(b), ctypes.c_int64(d),
            ctypes.c_int64(n_classes),
            _ptr(x, ctypes.c_float), _ptr(y, ctypes.c_float),
        )
        return x, y
    x = features_u8[perm].astype(np.float32) / 255.0
    y = np.zeros((b, n_classes), np.float32)
    y[np.arange(b), labels_u8[perm]] = 1.0
    return x, y


def split_cifar(buf: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 binary records -> (images u8 [n, 3072], labels u8 [n])."""
    arr = np.frombuffer(buf, np.uint8)
    if arr.size % 3073:
        raise ValueError(
            f"size {arr.size} not a multiple of the 3073-byte record"
        )
    n = arr.size // 3073
    lib = get_lib()
    if lib is not None:
        images = np.empty((n, 3072), np.uint8)
        labels = np.empty((n,), np.uint8)
        rc = lib.split_cifar_records(
            _ptr(arr, ctypes.c_uint8), ctypes.c_int64(arr.size),
            _ptr(images, ctypes.c_uint8), _ptr(labels, ctypes.c_uint8),
        )
        if rc != 0:
            raise ValueError("bad CIFAR records")
        return images, labels
    rec = arr.reshape(n, 3073)
    return rec[:, 1:].copy(), rec[:, 0].copy()
