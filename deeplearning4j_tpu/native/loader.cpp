// Native data-loader kernels (the C++ runtime component the reference
// delegates to libnd4j/DataVec for: dataset decode + batch assembly,
// SURVEY.md §2.3 native-component checklist "data-loader").
//
// Exposed as a plain C ABI consumed via ctypes
// (deeplearning4j_tpu/native/__init__.py builds this file with g++ on
// first use and falls back to numpy when no toolchain exists).
//
// Functions fuse the host-side per-batch passes that the numpy path
// performs separately (gather rows by permutation, uint8->float32
// normalize, one-hot expand), so one pass over memory feeds the
// device-bound pipeline.

#include <cstdint>
#include <cstdio>
#include <cstring>

extern "C" {

// Parse an IDX3 image file already loaded into memory.
// Returns 0 on success; fills n/rows/cols. Data begins at offset 16.
int idx3_header(const uint8_t* buf, int64_t len, int64_t* n,
                int64_t* rows, int64_t* cols) {
    if (len < 16) return 1;
    uint32_t magic = (uint32_t(buf[0]) << 24) | (uint32_t(buf[1]) << 16)
                   | (uint32_t(buf[2]) << 8) | uint32_t(buf[3]);
    if (magic != 2051u) return 2;
    auto be = [&](int off) {
        return (int64_t(buf[off]) << 24) | (int64_t(buf[off + 1]) << 16)
             | (int64_t(buf[off + 2]) << 8) | int64_t(buf[off + 3]);
    };
    *n = be(4);
    *rows = be(8);
    *cols = be(12);
    if (len < 16 + (*n) * (*rows) * (*cols)) return 3;
    return 0;
}

// uint8 [n, d] image rows -> float32 [n, d] in [0, 1].
void normalize_u8(const uint8_t* src, float* dst, int64_t count) {
    static float lut[256];
    static bool init = false;
    if (!init) {
        for (int i = 0; i < 256; i++) lut[i] = float(i) / 255.0f;
        init = true;
    }
    for (int64_t i = 0; i < count; i++) dst[i] = lut[src[i]];
}

// Fused batch assembly: gather rows of u8 features by perm, normalize
// to float32, and one-hot the labels — one pass per example.
//   features: [n, d] uint8; labels: [n] uint8; perm: [b] int64
//   out_x: [b, d] float32; out_y: [b, n_classes] float32 (pre-zeroed
//   not required — fully written)
void assemble_batch_u8(const uint8_t* features, const uint8_t* labels,
                       const int64_t* perm, int64_t b, int64_t d,
                       int64_t n_classes, float* out_x, float* out_y) {
    static float lut[256];
    static bool init = false;
    if (!init) {
        for (int i = 0; i < 256; i++) lut[i] = float(i) / 255.0f;
        init = true;
    }
    for (int64_t r = 0; r < b; r++) {
        const uint8_t* src = features + perm[r] * d;
        float* dst = out_x + r * d;
        for (int64_t j = 0; j < d; j++) dst[j] = lut[src[j]];
        float* y = out_y + r * n_classes;
        memset(y, 0, sizeof(float) * n_classes);
        int64_t cls = labels[perm[r]];
        if (cls >= 0 && cls < n_classes) y[cls] = 1.0f;
    }
}

// CIFAR-10 binary records: [rec][0]=label, [rec][1..3072]=RGB planes.
// Splits into images [n, 3072] u8 + labels [n] u8.
int split_cifar_records(const uint8_t* buf, int64_t len,
                        uint8_t* images, uint8_t* labels) {
    const int64_t rec = 3073;
    if (len % rec) return 1;
    int64_t n = len / rec;
    for (int64_t i = 0; i < n; i++) {
        labels[i] = buf[i * rec];
        memcpy(images + i * 3072, buf + i * rec + 1, 3072);
    }
    return 0;
}

}  // extern "C"
