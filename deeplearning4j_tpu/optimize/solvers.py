"""Secondary optimizers: LineGradientDescent, ConjugateGradient, LBFGS
with backtracking line search (reference ``optimize/solvers/``:
``BackTrackLineSearch.java:1`` (354 LoC), ``LBFGS.java:1``,
``ConjugateGradient.java``, ``LineGradientDescent.java``, selected by
the ``OptimizationAlgorithm`` enum on the conf).

TPU-first design: the reference runs the line search as a host loop of
separate native score evaluations; here ONE jitted XLA program per
optimizer step contains gradient, direction computation, and the whole
Armijo backtracking loop (``lax.while_loop``) — zero host round-trips
mid-step. Parameters are handled as a single raveled vector
(``jax.flatten_util.ravel_pytree``), the flat-view analog of the
reference's parameter view array (``MultiLayerNetwork.init():367``).
The score closure is built once per Solver so step programs compile
once per batch shape; minibatch data rides in as traced arguments.

Divergence from the reference, by design: the SGD-family updater/lr
schedule machinery does not wrap these algorithms (the reference
threads its GradientUpdater into every solver); here the line search
owns the step size, with ``learning_rate`` as the initial trial step —
the idiomatic formulation of these methods.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

# LBFGS memory depth (reference LBFGS.java uses m=4; 10 is the common
# modern default and costs only m extra vectors)
LBFGS_MEMORY = 10


def backtrack_line_search(f, p, score0, grad, direction, initial_step,
                          max_iters: int = 5, c1: float = 1e-4,
                          rho: float = 0.5):
    """Armijo backtracking (reference ``BackTrackLineSearch.java``):
    shrink alpha until f(p + alpha*d) <= f(p) + c1*alpha*(g.d).
    Pure-jax (runs inside the enclosing jit as a lax.while_loop).
    Returns (alpha, new_score); alpha == 0 if no decrease was found."""
    gd = jnp.vdot(grad, direction)

    def cond(carry):
        alpha, it, score = carry
        armijo_ok = score <= score0 + c1 * alpha * gd
        return (~armijo_ok) & (it < max_iters)

    def body(carry):
        alpha, it, _ = carry
        new_alpha = alpha * rho
        return new_alpha, it + 1, f(p + new_alpha * direction)

    alpha0 = jnp.asarray(initial_step, p.dtype)
    alpha, _, score = jax.lax.while_loop(
        cond, body, (alpha0, 0, f(p + alpha0 * direction))
    )
    ok = score <= score0 + c1 * alpha * gd
    return jnp.where(ok, alpha, 0.0), jnp.where(ok, score, score0)


# ---------------------------------------------------------------------------
# Optimizer steps. ``score`` is static (one stable closure per Solver:
# (flat_params, x, y, mask, fmask) -> scalar); data args are traced.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(0, 8))
def _lgd_step(score, p, state, x, y, mask, fmask, step0, max_ls):
    f = lambda q: score(q, state, x, y, mask, fmask)
    s, g = jax.value_and_grad(f)(p)
    alpha, new_s = backtrack_line_search(
        f, p, s, g, -g, step0, max_iters=max_ls
    )
    return p - alpha * g, new_s


@partial(jax.jit, static_argnums=(0, 10))
def _cg_step(score, p, prev_g, prev_d, state, x, y, mask, fmask, step0,
             max_ls):
    """Polak-Ribiere nonlinear CG with automatic restart (reference
    ``ConjugateGradient.java`` uses the same beta)."""
    f = lambda q: score(q, state, x, y, mask, fmask)
    s, g = jax.value_and_grad(f)(p)
    beta = jnp.vdot(g, g - prev_g) / jnp.maximum(
        jnp.vdot(prev_g, prev_g), 1e-30
    )
    beta = jnp.maximum(beta, 0.0)  # restart when beta < 0
    d = -g + beta * prev_d
    # fall back to steepest descent if d is not a descent direction
    d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
    alpha, new_s = backtrack_line_search(
        f, p, s, g, d, step0, max_iters=max_ls
    )
    return p + alpha * d, g, d, new_s


@partial(jax.jit, static_argnums=(0, 12))
def _lbfgs_step(score, p, s_mem, y_mem, rho_mem, count, state, x, y,
                mask, fmask, step0, max_ls):
    """Two-loop recursion over a fixed-size rolling memory (static
    shapes for XLA; reference ``LBFGS.java`` keeps LinkedLists)."""
    f = lambda q: score(q, state, x, y, mask, fmask)
    s, g = jax.value_and_grad(f)(p)
    m = s_mem.shape[0]

    def valid(i):
        # slot i holds a real pair if i >= m - min(count, m)
        return (i >= m - jnp.minimum(count, m)).astype(p.dtype)

    def loop1(carry, i):
        q, alphas = carry
        a = valid(i) * rho_mem[i] * jnp.vdot(s_mem[i], q)
        return (q - a * y_mem[i], alphas.at[i].set(a)), None

    (q, alphas), _ = jax.lax.scan(
        loop1, (g, jnp.zeros((m,), p.dtype)), jnp.arange(m - 1, -1, -1)
    )
    # initial Hessian scaling gamma = s.y / y.y of the newest pair
    last = m - 1
    gamma = jnp.where(
        count > 0,
        jnp.vdot(s_mem[last], y_mem[last])
        / jnp.maximum(jnp.vdot(y_mem[last], y_mem[last]), 1e-30),
        1.0,
    )

    def loop2(r, i):
        b = valid(i) * rho_mem[i] * jnp.vdot(y_mem[i], r)
        return r + valid(i) * (alphas[i] - b) * s_mem[i], None

    r, _ = jax.lax.scan(loop2, gamma * q, jnp.arange(m))
    d = -r
    d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
    alpha, new_s = backtrack_line_search(
        f, p, s, g, d, step0, max_iters=max_ls
    )
    new_p = p + alpha * d
    # roll the memory and append the new (s, y) pair (curvature guard)
    s_vec = new_p - p
    y_vec = jax.grad(f)(new_p) - g
    sy = jnp.vdot(s_vec, y_vec)
    curv_ok = sy > 1e-10
    s_mem = jnp.where(
        curv_ok, jnp.roll(s_mem, -1, axis=0).at[last].set(s_vec), s_mem
    )
    y_mem = jnp.where(
        curv_ok, jnp.roll(y_mem, -1, axis=0).at[last].set(y_vec), y_mem
    )
    rho_mem = jnp.where(
        curv_ok,
        jnp.roll(rho_mem, -1).at[last].set(1.0 / jnp.maximum(sy, 1e-30)),
        rho_mem,
    )
    count = count + jnp.where(curv_ok, 1, 0)
    return new_p, s_mem, y_mem, rho_mem, count, new_s


# ---------------------------------------------------------------------------
# Solver facade (reference optimize/Solver.java)
# ---------------------------------------------------------------------------

_ALGOS = ("LINE_GRADIENT_DESCENT", "CONJUGATE_GRADIENT", "LBFGS")


def is_solver_algo(algo: str) -> bool:
    return algo in _ALGOS


class Solver:
    """Runs ``conf.iterations`` (or ``iterations``) optimizer steps of
    the configured algorithm on one batch (reference ``Solver`` builder
    + ``BaseOptimizer.optimize``). LBFGS/CG state persists across
    ``optimize`` calls until ``reset_state()``."""

    def __init__(self, net):
        self.net = net
        algo = net.conf.optimization_algo
        if not is_solver_algo(algo):
            raise ValueError(
                f"Solver handles {_ALGOS}; '{algo}' uses the SGD path"
            )
        self.algo = algo
        self.max_ls = int(
            getattr(net.conf, "max_num_line_search_iterations", 5)
        )
        if net.params is None:
            net.init()
        flat, self._unravel = ravel_pytree(net.params)
        self._n = int(flat.size)
        self._dtype = flat.dtype
        net_ref = net
        unravel = self._unravel
        # ComputationGraph's _score_pure takes input/label/mask LISTS
        self._is_graph = hasattr(net.conf, "vertex_inputs")

        if self._is_graph:
            def score(flat_params, state, x, y, mask, fmask):
                s, _ = net_ref._score_pure(
                    unravel(flat_params), state, x, y, mask, None,
                    train=False, fmasks=fmask,
                )
                return s
        else:
            def score(flat_params, state, x, y, mask, fmask):
                # state rides as a traced arg: a stale-state constant
                # baked at first trace would silently misuse later BN
                # running stats
                s, _ = net_ref._score_pure(
                    unravel(flat_params), state, x, y, mask, None,
                    train=False, fmask=fmask,
                )
                return s

        self._score = score  # stable identity -> one compile per shape
        self.reset_state()

    def reset_state(self) -> None:
        m = LBFGS_MEMORY
        self._s_mem = jnp.zeros((m, self._n), self._dtype)
        self._y_mem = jnp.zeros((m, self._n), self._dtype)
        self._rho_mem = jnp.zeros((m,), self._dtype)
        self._count = jnp.asarray(0, jnp.int32)
        self._prev_g = None
        self._prev_d = None

    def _initial_step(self) -> float:
        for s in self.net.updater_def.settings.values():
            return float(s.learning_rate)
        return 1.0

    def optimize(self, x, y, mask=None, fmask=None,
                 iterations: Optional[int] = None):
        """For a ComputationGraph, ``x``/``y``/``mask``/``fmask`` may
        be lists (multi-input/-output); scalars/arrays are wrapped."""
        net = self.net
        dtype = self._dtype

        def conv(v):
            return (
                None if v is None else jnp.asarray(np.asarray(v), dtype)
            )

        if self._is_graph:
            as_list = lambda v: (
                None if v is None
                else [conv(e) for e in
                      (v if isinstance(v, (list, tuple)) else [v])]
            )
            x, y = as_list(x), as_list(y)
            mask, fmask = as_list(mask), as_list(fmask)
        else:
            x, y, mask, fmask = conv(x), conv(y), conv(mask), conv(fmask)
        p, _ = ravel_pytree(net.params)
        step0 = self._initial_step()
        iters = iterations or net.conf.iterations
        score = None
        state = net.state
        if self.algo == "LINE_GRADIENT_DESCENT":
            for _ in range(iters):
                p, score = _lgd_step(
                    self._score, p, state, x, y, mask, fmask, step0,
                    self.max_ls,
                )
        elif self.algo == "CONJUGATE_GRADIENT":
            if self._prev_g is None:
                self._prev_g = jax.grad(
                    lambda q: self._score(q, state, x, y, mask, fmask)
                )(p)
                self._prev_d = -self._prev_g
            for _ in range(iters):
                p, self._prev_g, self._prev_d, score = _cg_step(
                    self._score, p, self._prev_g, self._prev_d,
                    state, x, y, mask, fmask, step0, self.max_ls,
                )
        else:  # LBFGS
            for _ in range(iters):
                (
                    p, self._s_mem, self._y_mem, self._rho_mem,
                    self._count, score,
                ) = _lbfgs_step(
                    self._score, p, self._s_mem, self._y_mem,
                    self._rho_mem, self._count, state, x, y, mask,
                    fmask, step0, self.max_ls,
                )
        net.params = self._unravel(p)
        net.iteration_count += iters
        net._last_score = score
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration_count)
        return score
