"""Profiler hooks (SURVEY.md §5 tracing: "same listener SPI + jax
profiler hooks" — the reference has only PerformanceListener timing;
the TPU-era upgrade is a listener that brackets training with the XLA
profiler so traces open in TensorBoard/XProf/Perfetto).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import IterationListener


class ProfilerListener(IterationListener):
    """Capture a jax profiler trace for iterations
    [start_iteration, start_iteration + num_iterations) (device +
    host timelines, one trace directory per session).

    Usage::

        net.listeners.append(ProfilerListener("/tmp/trace", 10, 5))
        net.fit(data)          # iterations 10..14 are traced
    """

    # force the per-step fit path: under the fused lax.scan path all
    # listener callbacks fire after the chunk's single dispatch, so a
    # trace started there would bracket no device work
    supports_batched_iterations = False

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 num_iterations: int = 5):
        # fail fast: an unwritable trace directory must error HERE,
        # not after the run has trained start_iteration steps and the
        # profiler tries its first write
        try:
            os.makedirs(log_dir, exist_ok=True)
        except OSError as e:
            raise ValueError(
                f"ProfilerListener log_dir {log_dir!r} cannot be "
                f"created: {e}"
            ) from e
        if not os.access(log_dir, os.W_OK):
            raise ValueError(
                f"ProfilerListener log_dir {log_dir!r} is not "
                "writable"
            )
        self.log_dir = log_dir
        self.start_iteration = int(start_iteration)
        self.stop_iteration = int(start_iteration) + int(num_iterations)
        self._active = False
        self.trace_dir: Optional[str] = None

    def _start(self) -> None:
        import jax

        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._active = True

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self.trace_dir = self.log_dir
        # surface the trace location in the event log (and the span
        # sink, when a global tracer is installed) instead of only
        # returning it to whoever remembers to read .trace_dir
        from deeplearning4j_tpu.observability.trace import get_tracer

        get_tracer().event("profiler.trace_ready", attrs={
            "trace_dir": self.trace_dir,
        })
        logging.getLogger(__name__).info(
            "profiler trace written to %s", self.trace_dir
        )

    def iteration_done(self, model, iteration: int) -> None:
        if not self._active and (
            self.start_iteration <= iteration < self.stop_iteration
        ):
            self._start()
        elif self._active and iteration >= self.stop_iteration:
            # block so the trace includes finished device work
            try:
                float(model.score_value)
            except Exception:
                pass
            self._stop()

    def on_epoch_end(self, model) -> None:
        """Finalize an open trace when training ends before
        ``stop_iteration`` — an unfinalized jax trace blocks any later
        ``start_trace`` in the process."""
        if self._active:
            try:
                float(model.score_value)
            except Exception:
                pass
            self._stop()

    def close(self) -> None:
        if self._active:
            self._stop()


def annotate(name: str):
    """Named trace span for host-side phases (jax TraceAnnotation) —
    usable around data loading / eval to label the profile."""
    import jax

    return jax.profiler.TraceAnnotation(name)
