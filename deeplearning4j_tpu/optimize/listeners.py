"""Training listeners (reference: ``optimize/api/IterationListener`` +
``optimize/listeners/*`` — the universal L2<->L8 hook, invoked from
``StochasticGradientDescent.optimize():64-65``; here invoked from the
host-side fit loop after each jitted step).

Note on TPU semantics: reading ``model.score_value`` forces a device
sync; ``PerformanceListener`` therefore reports true end-to-end step
throughput including transfer, like the reference's wall-clock numbers.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)


class IterationListener:
    """SPI: ``iteration_done(model, iteration)``."""

    def iteration_done(self, model, iteration: int) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference
    ``ScoreIterationListener``)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(int(print_iterations), 1)

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.print_iterations == 0:
            logger.info(
                "Score at iteration %d is %s", iteration, model.score_value
            )


class PerformanceListener(IterationListener):
    """samples/sec + batches/sec (reference
    ``PerformanceListener.java:18,:71-86`` — the metric named in
    BASELINE.md)."""

    def __init__(self, frequency: int = 1, report: bool = False):
        self.frequency = max(int(frequency), 1)
        self.report = report
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._samples_since = 0
        self.samples_per_sec = float("nan")
        self.batches_per_sec = float("nan")
        self.history: List[Tuple[int, float, float]] = []

    def record_batch(self, num_examples: int) -> None:
        self._samples_since += num_examples

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            batches = iteration - self._last_iter
            self.batches_per_sec = batches / dt if dt > 0 else float("inf")
            if self._samples_since:
                self.samples_per_sec = (
                    self._samples_since / dt if dt > 0 else float("inf")
                )
            self.history.append(
                (iteration, self.samples_per_sec, self.batches_per_sec)
            )
            if self.report:
                logger.info(
                    "iteration %d: %.1f batches/sec, %.1f samples/sec",
                    iteration, self.batches_per_sec, self.samples_per_sec,
                )
            self._last_time = now
            self._last_iter = iteration
            self._samples_since = 0


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (reference
    ``CollectScoresIterationListener``)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(int(frequency), 1)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners: IterationListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int) -> None:
        for listener in self.listeners:
            listener.iteration_done(model, iteration)


class ParamAndGradientIterationListener(IterationListener):
    """Parameter-magnitude tracking (reference
    ``ParamAndGradientIterationListener``); records mean |param| per
    layer each N iterations."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(int(frequency), 1)
        self.records: List[dict] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        import numpy as np

        rec = {"iteration": iteration}
        for ln, lp in (model.params or {}).items():
            for pn, p in lp.items():
                rec[f"{ln}.{pn}"] = float(np.mean(np.abs(np.asarray(p))))
        self.records.append(rec)
