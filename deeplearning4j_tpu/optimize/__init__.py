"""Optimization & listeners (reference ``optimize/**``)."""

from deeplearning4j_tpu.optimize.profiler import (  # noqa: F401
    ProfilerListener,
    annotate,
)
from deeplearning4j_tpu.optimize.solvers import (  # noqa: F401
    Solver,
    backtrack_line_search,
    is_solver_algo,
)
from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    ComposableIterationListener,
    IterationListener,
    ParamAndGradientIterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
