"""Optimization & listeners (reference ``optimize/**``)."""

from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    ComposableIterationListener,
    IterationListener,
    ParamAndGradientIterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
