"""Keras model import (reference ``deeplearning4j-modelimport`` —
SURVEY.md §2.8)."""

from deeplearning4j_tpu.modelimport.keras import (
    IncompatibleKerasConfigurationException,
    import_functional_api_config,
    import_functional_api_model,
    import_sequential_model,
    import_sequential_model_config,
)

__all__ = [
    "IncompatibleKerasConfigurationException",
    "import_functional_api_config",
    "import_functional_api_model",
    "import_sequential_model",
    "import_sequential_model_config",
]
